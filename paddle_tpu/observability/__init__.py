"""paddle_tpu.observability — the framework-wide metrics plane.

The tracing half of the reference stack (``profiler.RecordEvent``, chrome
export, serving spans) answers *where did this microsecond go*; this
package answers *how is the system doing* — one process-wide registry
where trainer throughput/MFU, the goodput ledger, serving latency
percentiles, compile-cache counters, and resilience events all land, with
exporters (JSONL time-series, Prometheus text, console) and a crash flight
recorder consuming it. Reference analogue: profiler_statistic + the ips
timer + the fleet monitors, unified.

Zero-cost contract (same discipline as RecordEvent): every instrumented
call site guards on one registry flag; until :func:`enable` (or an
explicit exporter/flight attach) flips it, instrumentation is an attribute
load + branch.

Quickstart::

    import paddle_tpu.observability as obs
    obs.enable(jsonl_path="metrics.jsonl", prom_path="metrics.prom",
               flight_dir="./flight")
    trainer.fit(loader, steps=1000, checkpoint_manager=mgr)  # auto-metered
    obs.publish()                      # snapshot -> attached exporters
    print(obs.console())               # human-readable table

Pull model: :func:`collect` refreshes the derived gauges (goodput buckets,
compile-cache counters) and snapshots every series; exporters render the
snapshot. The serving engine pushes its own gauges/counters at reconcile
boundaries (`ContinuousBatchingEngine.publish_metrics`).
"""

from __future__ import annotations

from typing import List, Optional

from . import exporters as exporters  # noqa: F401 (re-export module)
from . import flight_recorder, goodput
from . import sentry as sentry  # noqa: F401 (re-export module)
from . import tracing as tracing  # noqa: F401 (re-export module)
from .exporters import (ConsoleSummary, JSONLExporter, PrometheusExporter,
                        parse_prometheus, render_prometheus)
from .goodput import GoodputLedger, ledger
from .metrics import (REGISTRY, Counter, Gauge, Histogram, MetricsRegistry,
                      enabled, registry)
from .tracing import TRACER, Span, TraceContext, Tracer, tracer

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "REGISTRY", "enabled", "enable", "disable", "collect", "publish",
    "console", "GoodputLedger", "ledger", "goodput", "flight_recorder",
    "sentry", "exporters", "JSONLExporter", "PrometheusExporter",
    "ConsoleSummary", "render_prometheus", "parse_prometheus",
    "observe_train_metrics",
    "tracing", "TRACER", "Tracer", "Span", "TraceContext", "tracer",
]

_EXPORTERS: List[object] = []


def enable(jsonl_path: Optional[str] = None,
           prom_path: Optional[str] = None,
           prom_http_port: Optional[int] = None,
           console: bool = False,
           flight_dir: Optional[str] = None,
           jsonl_max_bytes: Optional[int] = None,
           jsonl_keep_segments: int = 3) -> MetricsRegistry:
    """Flip the metrics plane on and attach the requested consumers.

    Every argument is optional — ``enable()`` with none just arms the
    registry (tests, ad-hoc inspection). ``prom_http_port=0`` picks an
    ephemeral port (read it back from the exporter's ``.port``).
    ``jsonl_max_bytes`` turns on JSONL segment rotation (keep-last-
    ``jsonl_keep_segments``) so a long-lived job's time-series stays
    bounded on disk.

    Idempotent per exporter kind: re-enabling replaces (closes) a
    previously attached exporter of the same kind instead of stacking a
    duplicate — a re-run setup cell must not double-write the JSONL
    time-series or re-bind the HTTP port.
    """
    def _replace(cls, factory):
        # close the old exporter BEFORE constructing the new one: a fixed
        # prom_http_port must be released before the replacement binds it
        for old in [e for e in _EXPORTERS if isinstance(e, cls)]:
            close = getattr(old, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
            _EXPORTERS.remove(old)
        _EXPORTERS.append(factory())

    if jsonl_path:
        _replace(JSONLExporter, lambda: JSONLExporter(
            jsonl_path, max_bytes=jsonl_max_bytes,
            keep_segments=jsonl_keep_segments))
    if prom_path or prom_http_port is not None:
        _replace(PrometheusExporter,
                 lambda: PrometheusExporter(path=prom_path,
                                            http_port=prom_http_port))
    if console:
        _replace(ConsoleSummary, lambda: ConsoleSummary(echo=True))
    if flight_dir:
        flight_recorder.install(dir=flight_dir)
    REGISTRY.enable()
    return REGISTRY


def disable() -> None:
    """Tear the plane down: close exporters, stop the flight recorder,
    disarm the registry (instrumented sites fall back to the one-branch
    no-op)."""
    for e in _EXPORTERS:
        close = getattr(e, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass
    _EXPORTERS.clear()
    rec = flight_recorder.recorder()
    rec.uninstall()
    rec.stop()
    REGISTRY.disable()


def attached_exporters() -> List[object]:
    return list(_EXPORTERS)


def collect() -> List[dict]:
    """Refresh derived gauges (goodput buckets, compile-cache counters),
    then snapshot every series."""
    if REGISTRY.enabled:
        ledger().publish()
        try:
            from ..core import compile_cache as _cc
            st = _cc.stats()
            g = REGISTRY.gauge("pt_compile_cache",
                               "compile-cache counters (hits/misses/"
                               "aot_hits/traces/executables)")
            for k in ("hits", "misses", "aot_hits", "traces",
                      "executables"):
                g.set(st.get(k, 0), kind=k)
        except Exception:
            pass
    return REGISTRY.collect()


def publish() -> List[dict]:
    """collect() + hand the snapshot to every attached exporter. Safe to
    call when disabled (returns the — empty — snapshot)."""
    snap = collect()
    for e in _EXPORTERS:
        try:
            e.export(snap)
        except Exception:
            pass
    return snap


def console() -> str:
    """One-shot human-readable table of the current snapshot."""
    return ConsoleSummary().export(collect())


def observe_train_metrics(m) -> None:
    """Trainer log-boundary hook: mirror one TrainMetrics emission into
    the registry. Near-zero when the plane is off (single guard)."""
    if not REGISTRY.enabled:
        return
    REGISTRY.counter("pt_train_steps_total", "optimizer steps logged").inc()
    REGISTRY.gauge("pt_train_loss", "loss at the last log boundary").set(
        m.loss)
    REGISTRY.gauge("pt_train_tokens_per_sec", "training throughput",
                   "tokens/s").set(m.tokens_per_sec)
    REGISTRY.gauge("pt_train_mfu", "model FLOPs utilization").set(m.mfu)
    REGISTRY.gauge("pt_train_lr", "learning rate").set(m.lr)
    REGISTRY.histogram("pt_train_step_seconds", "per-step wall time",
                       "s").observe(m.step_time_s)
