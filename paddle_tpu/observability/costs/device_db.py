"""Device capability tables for the cost observatory: peak FLOP/s, HBM
bandwidth, inter-chip link bandwidth.

One definition per number: bf16 peak FLOP/s comes from the trainer's
``PEAK_FLOPS`` table (the MFU denominator every throughput report already
uses) and the v5e/v5p HBM + v5p ICI constants come from
``parallel/projection.py`` (cited public specs, asserted by
tests/test_projection) — this module only ADDS the device kinds those
tables don't carry, each with its source in a comment. Every lookup falls
back to a nominal CPU tier so the observatory stays usable (and testable)
on hosts with no accelerator: the absolute predictions are then
meaningless, but the RATIOS the acceptance tests pin (K=1 vs K=4 step
time, comm ∝ bytes) survive any constant scaling.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

__all__ = ["DeviceSpec", "device_spec", "current_device_kind"]

# bytes/s; v5e + v5p imported from projection.py (cited), the rest from
# the same public per-generation spec sheets (cloud.google.com/tpu/docs)
_HBM_BW_EXTRA = {
    "tpu v4": 1228e9,        # v4: 32 GB @ 1228 GB/s
    "tpu v6 lite": 1640e9,   # v6e (trillium): 32 GB @ 1640 GB/s
    "cpu": 50e9,             # nominal DRAM tier for smoke runs
}

# bytes/s per chip, aggregate over ICI links (approximate: link count x
# per-link rate from the launch specs; the planner only needs an
# order-of-magnitude prior until tools/op_cost_probe.py measures)
_LINK_BW_EXTRA = {
    "tpu v4": 300e9,         # 6 links x 50 GB/s
    "tpu v5 lite": 200e9,    # v5e: 1600 Gbit/s aggregate
    "tpu v5e": 200e9,
    "tpu v6 lite": 400e9,    # v6e: 3200 Gbit/s aggregate
    "cpu": 10e9,             # nominal host-interconnect tier
}


@dataclass(frozen=True)
class DeviceSpec:
    kind: str
    peak_flops: float        # bf16 FLOP/s per chip
    hbm_bw: float            # bytes/s per chip
    link_bw: float           # bytes/s per chip over one mesh axis

    def as_dict(self) -> Dict[str, float]:
        return {"kind": self.kind, "peak_flops": self.peak_flops,
                "hbm_bw": self.hbm_bw, "link_bw": self.link_bw}


def _peak_table() -> Dict[str, float]:
    # the trainer owns the MFU denominator; a jax-free environment
    # (analyzing a saved .hlo dump) falls back to the nominal CPU tier
    try:
        from ...trainer.trainer import PEAK_FLOPS
        return dict(PEAK_FLOPS)
    except Exception:
        return {"cpu": 1e12}


def _hbm_table() -> Dict[str, float]:
    out = dict(_HBM_BW_EXTRA)
    try:
        from ...parallel.projection import HBM_BW
        out["tpu v5 lite"] = out["tpu v5e"] = HBM_BW["v5e"]
        out["tpu v5"] = out["tpu v5p"] = HBM_BW["v5p"]
    except Exception:
        out.setdefault("tpu v5 lite", 819e9)
        out.setdefault("tpu v5", 2765e9)
    return out


def _link_table() -> Dict[str, float]:
    out = dict(_LINK_BW_EXTRA)
    try:
        from ...parallel.projection import ICI_AGG
        out["tpu v5"] = out["tpu v5p"] = ICI_AGG["v5p"]
    except Exception:
        out.setdefault("tpu v5", 600e9)
    return out


def current_device_kind(default: str = "cpu") -> str:
    # ONE device-kind probe: delegate to the autotune helper the TuneDB
    # keys already use, so DB keys and spec lookups can never disagree
    try:
        from ...ops.pallas.autotune import _device_kind
        return _device_kind(default=default)
    except Exception:
        return default


def _match(table: Dict[str, float], kind: str,
           fallback: float) -> float:
    kind = kind.lower()
    # longest-substring match so "tpu v5 lite" beats "tpu v5"
    best, best_len = None, -1
    for k, v in table.items():
        if k in kind and len(k) > best_len:
            best, best_len = v, len(k)
    return best if best is not None else fallback


def device_spec(kind: Optional[str] = None) -> DeviceSpec:
    """Spec for ``kind`` (defaults to the current jax device), with the
    nominal CPU tier as the universal fallback."""
    kind = kind or current_device_kind()
    return DeviceSpec(
        kind=kind,
        peak_flops=_match(_peak_table(), kind, 1e12),
        hbm_bw=_match(_hbm_table(), kind, 50e9),
        link_bw=_match(_link_table(), kind, 10e9),
    )
