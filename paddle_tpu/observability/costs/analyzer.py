"""Analytical flop/byte attribution over a compiled graph's optimized HLO.

THE one flop formula (ISSUE 9 acceptance): bench's ``mfu_analytical``, the
live ``pt_model_flops_utilization`` gauge and graph_lint's flop-floor
budget all call :func:`attribute_costs` over the PR 8 ``HloModule`` — there
is no second, hand-maintained per-model formula to drift from the program
XLA actually runs. (``model.flops_per_token`` remains the PaLM-convention
closed form the HEADLINE MFU quotes for cross-paper comparability; the two
conventions are reported side by side, never mixed.)

Attribution walks the instruction stream the ``analysis/hlo.py`` parser
already produces:

* **dot** — ``2 x out_elems x contracted_elems`` (contracting dims from the
  instruction's ``lhs_contracting_dims`` attribute against the lhs operand
  shape; batch dims ride in ``out_elems``);
* **reduce / reduce-window** — one flop per reduced input element;
* **elementwise / transcendental** — one flop per output element (a
  deliberate single bucket: the roofline verdicts this feeds are decided
  by dots and bytes, not by exp-vs-add microcosts);
* **fusion** — flops of the called computation; HBM bytes are the fusion's
  operands + outputs (counting its internals would uncount exactly what
  fusion exists to avoid);
* **while** — body + condition, multiplied by XLA's
  ``known_trip_count`` backend config (1 + a report note when absent);
* **collectives** — zero flops, payload bytes routed to ``comm_bytes``
  (priced per mesh axis by :func:`price_census`);
* **custom-call** — zero flops, operands + outputs bytes, and the opcode
  lands in ``unmodeled`` so a Pallas-kernel-heavy graph reports HOW MUCH
  of itself the model didn't see instead of silently under-counting.

Per top-level op the roofline verdict is
``max(flops/peak, bytes/hbm_bw, comm_bytes/link_bw)`` with the arg-max as
its bound (compute | hbm | comm); the predicted step time is the sum over
the entry computation — serialized execution, i.e. an upper bound that
ignores XLA's overlap, which is exactly why the drift between predicted
and measured is itself exported as a monitored ratio.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ...analysis.hlo import HloModule, ShapeLeaf, parse_shape
from .device_db import DeviceSpec, device_spec

__all__ = ["OpCost", "CostReport", "attribute_costs", "price_census",
           "dominant_dots"]

# no flops, no bytes: control/meta instructions with no payload traffic
_FREE_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id", "domain", "add-dependency",
    "opt-barrier", "rng-get-and-update-state",
})
# pure data movement: bytes counted, zero flops
_MOVE_OPS = frozenset({
    "copy", "copy-start", "transpose", "reshape", "broadcast",
    "slice", "dynamic-slice", "dynamic-update-slice", "gather", "scatter",
    "concatenate", "pad", "reverse", "iota", "convert", "reduce-precision",
    "sort", "select-and-scatter", "rng", "rng-bit-generator",
})
_COLLECTIVE_BASES = frozenset({
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
})
_CALLED_RE = re.compile(
    r"(?:calls|body|condition|to_apply|true_computation|false_computation)"
    r"=%([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_TRIP_RE = re.compile(r'"known_trip_count":\s*\{"n":\s*"(\d+)"')
_DIMS_RE = {
    "lhs": re.compile(r"lhs_contracting_dims=\{([0-9,\s]*)\}"),
}


@dataclass
class OpCost:
    """One entry-computation instruction with its (recursively aggregated)
    cost and roofline verdict."""
    name: str
    opcode: str
    op_name: str
    flops: float
    bytes: float
    comm_bytes: float
    seconds: float = 0.0
    bound: str = "hbm"            # compute | hbm | comm

    def describe(self) -> str:
        return (f"{self.opcode}({self.name}) {self.flops:.3g} flops, "
                f"{self.bytes:.3g} B, {self.comm_bytes:.3g} comm B "
                f"-> {self.seconds * 1e6:.1f} us [{self.bound}]"
                + (f" <- {self.op_name}" if self.op_name else ""))


@dataclass
class CostReport:
    spec: DeviceSpec
    ops: List[OpCost]
    total_flops: float
    total_bytes: float
    total_comm_bytes: float
    predicted_compute_s: float
    predicted_hbm_s: float
    predicted_comm_s: float
    predicted_step_s: float
    bound_seconds: Dict[str, float]        # compute/hbm/comm -> seconds
    unmodeled: Dict[str, int]              # opcode -> count (flops unseen)
    notes: List[str] = field(default_factory=list)
    dots: List[Tuple[int, int, int, str, int]] = field(
        default_factory=list)              # (m, k, n, dtype, count)

    def summary(self) -> Dict[str, float]:
        return {
            "total_flops": self.total_flops,
            "total_bytes": self.total_bytes,
            "total_comm_bytes": self.total_comm_bytes,
            "predicted_step_s": self.predicted_step_s,
            "predicted_compute_s": self.predicted_compute_s,
            "predicted_hbm_s": self.predicted_hbm_s,
            "predicted_comm_s": self.predicted_comm_s,
        }


def _strip_comments(text: str) -> str:
    return re.sub(r"/\*.*?\*/", "", text)


def _split_top_commas(s: str) -> List[str]:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _operand_tokens(ins) -> List[str]:
    """The operand list text, split on top-level commas. Works off the
    raw line so nothing beyond the PR 8 parser is required."""
    clean = _strip_comments(ins.raw)
    m = re.search(re.escape(ins.opcode) + r"\(", clean)
    if not m:
        return []
    i = m.end() - 1
    depth, j = 0, i
    for j in range(i, len(clean)):
        if clean[j] == "(":
            depth += 1
        elif clean[j] == ")":
            depth -= 1
            if depth == 0:
                break
    inner = clean[i + 1:j]
    return _split_top_commas(inner)


def _operand_leaves(ins, name2leaves) -> List[List[ShapeLeaf]]:
    """Shape leaves per operand: inline shapes when the printer emitted
    them, else resolved through the module-wide name table."""
    out = []
    for tok in _operand_tokens(ins):
        leaves = parse_shape(tok)
        if not leaves:
            nm = re.search(r"%([\w.\-]+)", tok)
            if nm:
                leaves = name2leaves.get(nm.group(1), [])
        out.append(leaves)
    return out


def _leaves_bytes(leaves_list: List[List[ShapeLeaf]]) -> float:
    return float(sum(l.bytes for leaves in leaves_list for l in leaves))


def _contracted_elems(ins, operands) -> float:
    """Product of the lhs contracting-dim sizes of a dot."""
    m = _DIMS_RE["lhs"].search(ins.raw)
    if not m or not operands or not operands[0]:
        return 1.0
    lhs = operands[0][0]
    prod = 1.0
    for tok in m.group(1).replace(" ", "").split(","):
        if tok == "":
            continue
        d = int(tok)
        if d < len(lhs.dims):
            prod *= lhs.dims[d]
    return prod


class _Walker:
    def __init__(self, mod: HloModule):
        self.mod = mod
        self.comps = {c.name: c for c in mod.computations}
        self.name2leaves = {i.name: i.shape_leaves
                            for i in mod.instructions}
        self.memo: Dict[Tuple[str, bool], Tuple[float, float, float]] = {}
        self.unmodeled: Dict[str, int] = {}
        self.notes: List[str] = []
        self.dots: Dict[Tuple[int, int, int, str], int] = {}

    # -- per-instruction cost (recursive) -----------------------------------

    def ins_cost(self, ins, fused: bool) -> Tuple[float, float, float]:
        """(flops, hbm_bytes, comm_bytes) of one instruction. ``fused``
        suppresses byte counting (we're inside a fusion body, whose
        traffic is accounted at the fusion's boundary)."""
        op = ins.opcode
        if op in _FREE_OPS:
            return 0.0, 0.0, 0.0
        # async pairs (all-reduce-start/-done, copy-start/-done,
        # async-start/-done): ALL cost is booked at the -start — the
        # -done completes the same operation, so giving it the
        # elementwise default would add phantom flops and double-count
        # the payload bytes (TPU lowers collectives this way by default)
        if op.endswith("-done"):
            return 0.0, 0.0, 0.0

        called = _CALLED_RE.findall(ins.raw)
        bm = _BRANCHES_RE.search(ins.raw)
        if bm:
            called += re.findall(r"%([\w.\-]+)", bm.group(1))

        out_bytes = float(ins.bytes)
        operands = _operand_leaves(ins, self.name2leaves)
        io_bytes = 0.0 if fused else _leaves_bytes(operands) + out_bytes
        out_elems = float(sum(l.num_elements for l in ins.shape_leaves))

        base = op[:-6] if op.endswith("-start") else op
        if base in _COLLECTIVE_BASES:
            return 0.0, io_bytes, out_bytes

        if op == "fusion":
            f = c = 0.0
            for name in called:
                cf, _, cc = self.comp_cost(name, fused=True)
                f += cf
                c += cc
            return f, io_bytes, c

        if op == "while":
            trip = 1
            tm = _TRIP_RE.search(ins.raw)
            if tm:
                trip = int(tm.group(1))
            else:
                self.notes.append(
                    f"while {ins.name}: no known_trip_count — body "
                    f"counted once")
            f = b = c = 0.0
            for name in called:
                cf, cb, cc = self.comp_cost(name, fused=fused)
                f += cf
                b += cb
                c += cc
            return f * trip, b * trip, c * trip

        if op in ("call", "async-start"):
            f = b = c = 0.0
            for name in called:
                cf, cb, cc = self.comp_cost(name, fused=fused)
                f += cf
                b += cb
                c += cc
            return f, b + io_bytes, c

        if op == "conditional":
            # one branch executes: take the most expensive (upper bound)
            best = (0.0, 0.0, 0.0)
            for name in called:
                cand = self.comp_cost(name, fused=fused)
                if cand[0] + cand[2] > best[0] + best[2]:
                    best = cand
            return best[0], best[1] + io_bytes, best[2]

        if op == "dot":
            k = _contracted_elems(ins, operands)
            flops = 2.0 * out_elems * k
            if ins.shape_leaves:
                lf = ins.shape_leaves[0]
                n = lf.dims[-1] if lf.dims else 1
                m_dim = int(out_elems / max(n, 1))
                self.dots[(m_dim, int(k), int(n), lf.dtype)] = \
                    self.dots.get((m_dim, int(k), int(n), lf.dtype), 0) + 1
            return flops, io_bytes, 0.0

        if op == "convolution":
            # rhs elems / output feature dim ~ flops per output element
            rhs = operands[1][0] if len(operands) > 1 and operands[1] \
                else None
            per_out = (rhs.num_elements / max(ins.shape_leaves[0].dims[-1], 1)
                       if rhs is not None and ins.shape_leaves
                       and ins.shape_leaves[0].dims else 1.0)
            return 2.0 * out_elems * per_out, io_bytes, 0.0

        if op in ("reduce", "reduce-window"):
            in_elems = sum(l.num_elements for leaves in operands[:1]
                           for l in leaves)
            return float(in_elems), io_bytes, 0.0

        if op in _MOVE_OPS:
            return 0.0, io_bytes, 0.0

        if op == "custom-call":
            # opaque kernel (Pallas, cuDNN, host callback): flops unseen
            self.unmodeled[op] = self.unmodeled.get(op, 0) + 1
            return 0.0, io_bytes, 0.0

        # default: elementwise-ish — one flop per output element
        return out_elems, io_bytes, 0.0

    def comp_cost(self, name: str, fused: bool) -> Tuple[float, float,
                                                         float]:
        key = (name, fused)
        if key in self.memo:
            return self.memo[key]
        self.memo[key] = (0.0, 0.0, 0.0)       # cycle guard
        comp = self.comps.get(name)
        if comp is None:
            return 0.0, 0.0, 0.0
        f = b = c = 0.0
        for ins in comp.instructions:
            cf, cb, cc = self.ins_cost(ins, fused)
            f += cf
            b += cb
            c += cc
        self.memo[key] = (f, b, c)
        return self.memo[key]


def attribute_costs(mod: HloModule,
                    spec: Optional[DeviceSpec] = None) -> CostReport:
    """Walk ``mod``'s entry computation and return the per-op cost table,
    totals, and the roofline prediction against ``spec`` (defaults to the
    current device, CPU-tier fallbacks included)."""
    spec = spec or device_spec()
    w = _Walker(mod)
    entry = next((c for c in mod.computations if c.is_entry), None)
    ops: List[OpCost] = []
    if entry is not None:
        for ins in entry.instructions:
            f, b, c = w.ins_cost(ins, fused=False)
            if f == 0.0 and b == 0.0 and c == 0.0:
                continue
            ops.append(OpCost(name=ins.name, opcode=ins.opcode,
                              op_name=ins.op_name, flops=f, bytes=b,
                              comm_bytes=c))
    total_f = sum(o.flops for o in ops)
    total_b = sum(o.bytes for o in ops)
    total_c = sum(o.comm_bytes for o in ops)
    bound_s = {"compute": 0.0, "hbm": 0.0, "comm": 0.0}
    step_s = 0.0
    for o in ops:
        cands = {"compute": o.flops / spec.peak_flops,
                 "hbm": o.bytes / spec.hbm_bw,
                 "comm": o.comm_bytes / spec.link_bw}
        o.bound = max(cands, key=cands.get)
        o.seconds = cands[o.bound]
        bound_s[o.bound] += o.seconds
        step_s += o.seconds
    dots = sorted(((m, k, n, dt, cnt)
                   for (m, k, n, dt), cnt in w.dots.items()),
                  key=lambda t: -(2 * t[0] * t[1] * t[2] * t[4]))
    return CostReport(
        spec=spec, ops=ops,
        total_flops=total_f, total_bytes=total_b, total_comm_bytes=total_c,
        predicted_compute_s=total_f / spec.peak_flops,
        predicted_hbm_s=total_b / spec.hbm_bw,
        predicted_comm_s=total_c / spec.link_bw,
        predicted_step_s=step_s,
        bound_seconds=bound_s,
        unmodeled=dict(w.unmodeled),
        notes=w.notes,
        dots=dots,
    )


def price_census(census: Dict, bandwidths: Optional[Dict[str, float]] = None,
                 spec: Optional[DeviceSpec] = None) -> Dict:
    """Price the PR 8 collective census: bytes over a mesh axis ÷ that
    axis's link bandwidth = predicted comm seconds (the 'missing back
    half' of ROADMAP item 3). ``bandwidths`` maps axis name -> bytes/s;
    axes it doesn't name (including the unclassified "?") fall back to
    ``spec.link_bw``. Pure arithmetic over the census table — exact, no
    wall clock — so a synthetic bandwidth table yields exact ratios."""
    spec = spec or device_spec()
    bandwidths = bandwidths or {}
    per_axis: Dict[str, Dict[str, float]] = {}
    per_op: List[Dict] = []
    total_s = 0.0
    for c in census.get("table", []):
        bw = float(bandwidths.get(c.axis, spec.link_bw))
        sec = c.bytes / bw
        total_s += sec
        ax = per_axis.setdefault(c.axis, {"bytes": 0.0, "seconds": 0.0,
                                          "bandwidth": bw})
        ax["bytes"] += c.bytes
        ax["seconds"] += sec
        per_op.append({"opcode": c.opcode, "axis": c.axis,
                       "bytes": c.bytes, "seconds": sec,
                       "op_name": c.op_name})
    return {"per_axis": per_axis, "per_op": per_op,
            "total_comm_bytes": float(
                census.get("total_collective_bytes", 0)),
            "total_comm_s": total_s}


def dominant_dots(report: CostReport, top: int = 3) -> List[Dict]:
    """The ``top`` dot shapes by total flops — the shapes
    tools/op_cost_probe.py microbenches into the OpCostDB."""
    out = []
    for m, k, n, dtype, count in report.dots[:top]:
        out.append({"m": m, "k": k, "n": n, "dtype": dtype,
                    "count": count, "flops": 2.0 * m * k * n * count})
    return out
