"""paddle_tpu.observability.costs — the op-level cost observatory.

Three coupled layers (ISSUE 9, the back half of ROADMAP item 3):

1. **Analytical attribution** (:mod:`analyzer`): every fusion/dot/
   collective in a compiled graph's optimized HLO gets a flops + bytes
   estimate, yielding a per-graph roofline (compute- vs HBM- vs
   comm-bound per op, predicted step time from the
   :mod:`device_db` peak-flops/HBM-BW/link-BW tables with CPU-tier
   fallbacks) and :func:`price_census` prices the PR 8 collective census
   per mesh axis (bytes ÷ axis link bandwidth).
2. **Measured timings**: ``tools/op_cost_probe.py`` times the canonical
   registry graphs and their dominant dots (interleaved min-of-rounds)
   and persists an :class:`OpCostDB` next to the kernel ``TuneDB``
   (``ops/pallas/autotune.py``) keyed by op signature + device kind —
   the sharding planner that follows reads measured latencies instead of
   guesses.
3. **Live breakdown** (:mod:`live`): trainer and serving publish
   ``pt_step_time_breakdown`` / ``pt_model_flops_utilization`` /
   ``pt_hbm_bw_utilization`` / ``pt_step_time_predicted_over_measured``
   through the PR 4 registry.

Deliberately NOT imported by ``paddle_tpu.observability``'s own
``__init__`` — the metrics plane stays importable without the analysis
stack; consumers import ``paddle_tpu.observability.costs`` explicitly.
"""

from .analyzer import (CostReport, OpCost, attribute_costs, dominant_dots,
                       price_census)
from .device_db import DeviceSpec, current_device_kind, device_spec
from .live import CostWatch

# the measured-latency DB lives next to TuneDB (same load/merge/corrupt-
# warning machinery); re-exported here as the observatory's public handle
from ...ops.pallas.autotune import OpCostDB, get_op_cost_db

__all__ = [
    "CostReport", "OpCost", "attribute_costs", "dominant_dots",
    "price_census", "DeviceSpec", "device_spec", "current_device_kind",
    "CostWatch", "OpCostDB", "get_op_cost_db",
]
