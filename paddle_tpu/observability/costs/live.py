"""Live cost gauges: the runtime half of the cost observatory.

A :class:`CostWatch` pairs ONE compiled executable's analytical cost
report (:func:`analyzer.attribute_costs`) with measured wall times and
publishes, through the PR 4 registry:

* ``pt_step_time_breakdown{component,bucket}`` — the measured per-step
  wall time split into compute / collective / exposed_comm / host /
  stall seconds. The buckets SUM TO the measured step time by
  construction (same discipline as the goodput ledger): compute and
  comm are the analytical predictions, scaled down proportionally if
  they exceed what the wall clock allows, and stall is the unattributed
  residual (input pipeline, dispatch gaps, overlap the serialized model
  didn't credit). The comm share is further split by the ISSUE 14
  overlap analyzer: ``collective`` is the part start→done windows hide
  behind compute, ``exposed_comm`` the priced census minus that
  overlap-window compute — the serialization actually on the clock.
* ``pt_exposed_comm_fraction{component}`` — exposed ÷ total priced comm
  seconds, published ONLY when the executable has async collective
  windows (a sync-lowered backend is trivially 100% exposed and would
  page a sentry on every CPU run for a structural non-event).
* ``pt_model_flops_utilization{component}`` — HLO-attributed flops ÷
  (measured time × device peak): the MFU definition shared with bench's
  ``mfu_analytical`` and graph_lint's flop floor.
* ``pt_hbm_bw_utilization{component}`` — attributed HBM bytes ÷
  (measured time × HBM bandwidth).
* ``pt_step_time_predicted_over_measured{component}`` — the cost model
  watching itself: drift between prediction and reality is a monitored
  signal, not a silent assumption.

Attachment is lazy and failure-tolerant: executables that can't render
optimized HLO (the AOT-deserialized restart path) simply leave the gauges
unpublished — the hot path never pays for, or fails on, the observatory.
"""

from __future__ import annotations

from typing import Optional

from ..metrics import REGISTRY
from .analyzer import CostReport, attribute_costs
from .device_db import DeviceSpec, device_spec

__all__ = ["CostWatch"]


class CostWatch:
    """Analytical cost model of one executable + gauge publisher."""

    def __init__(self, component: str,
                 spec: Optional[DeviceSpec] = None):
        self.component = component
        self.spec = spec or device_spec()
        self.report: Optional[CostReport] = None
        # overlap verdict for the observed executable: fraction of its
        # priced comm seconds NOT covered by start->done window compute,
        # and how many async windows it has. Defaults (1.0, 0) = "all
        # exposed, no async machinery" — the conservative truth for a
        # report attached without HLO overlap analysis.
        self.overlap_fraction: float = 1.0
        self.overlap_async: int = 0
        self._exec_id: Optional[int] = None
        # per-executable report cache: a trainer alternating between two
        # bucketed batch shapes re-observes a different executable every
        # log boundary — the HLO must not re-parse each time
        self._reports: dict = {}

    # -- attachment ----------------------------------------------------------

    def observe_executable(self, compiled) -> bool:
        """Analyze ``compiled`` (anything with ``as_text()`` yielding
        optimized HLO). Re-observing the same object is a no-op; any
        failure leaves the watch unattached and returns False."""
        if compiled is None:
            return self.report is not None
        rid = id(compiled)
        if self._exec_id == rid and self.report is not None:
            return True
        cached = self._reports.get(rid)
        if cached is not None:
            (self.report, self.overlap_fraction,
             self.overlap_async) = cached
            self._exec_id = rid
            return True
        as_text = getattr(compiled, "as_text", None)
        if as_text is None:
            return False
        try:
            from ...analysis.hlo import parse_hlo
            mod = parse_hlo(as_text())
            self.report = attribute_costs(mod, spec=self.spec)
            # overlap split of the comm bucket (ISSUE 14); any analysis
            # failure (unpaired start, exotic lowering) falls back to
            # fully-exposed rather than silently crediting the overlap
            try:
                from ...analysis.overlap import overlap_report
                ov = overlap_report(mod, spec=self.spec)
                self.overlap_fraction = ov["exposed_comm_fraction"]
                self.overlap_async = ov["async_collectives"]
            except Exception:
                self.overlap_fraction, self.overlap_async = 1.0, 0
            self._exec_id = rid
            if len(self._reports) >= 8:     # bounded; ids are stable while
                self._reports.clear()       # the owner caches executables
            self._reports[rid] = (self.report, self.overlap_fraction,
                                  self.overlap_async)
            return True
        except Exception:
            return False

    @property
    def attached(self) -> bool:
        return self.report is not None

    # -- publication ---------------------------------------------------------

    def publish(self, measured_step_s: float, host_s: float = 0.0,
                steps_per_exec: int = 1) -> Optional[dict]:
        """Publish the gauges for one measured per-step time.

        ``steps_per_exec`` maps the analyzed executable onto step units
        (the K=4 superstep scan executes 4 optimizer steps per run), so a
        per-step measured time composes with a per-execution flop count.
        Returns the published dict (None when unattached/disabled)."""
        r = self.report
        if r is None or not REGISTRY.enabled or measured_step_s <= 0:
            return None
        k = max(1, int(steps_per_exec))
        exec_s = measured_step_s * k
        mfu = r.total_flops / (exec_s * self.spec.peak_flops)
        hbm = r.total_bytes / (exec_s * self.spec.hbm_bw)
        ratio = r.predicted_step_s / exec_s

        # breakdown (per step): analytical compute/comm, scaled to fit
        # inside the measured wall time net of host overhead; residual is
        # the stall bucket. Buckets sum EXACTLY to measured_step_s.
        host = min(max(host_s, 0.0), measured_step_s)
        compute = r.predicted_compute_s / k
        comm = r.predicted_comm_s / k
        avail = measured_step_s - host
        attributed = compute + comm
        scale = min(1.0, avail / attributed) if attributed > 0 else 0.0
        compute *= scale
        comm *= scale
        stall = max(0.0, measured_step_s - host - compute - comm)
        # split the scaled comm share by the overlap verdict — hidden
        # (start->done windows cover it with compute) vs exposed. The
        # split preserves the exact-sum invariant: hidden + exposed is
        # the comm share by construction.
        exposed = comm * min(max(self.overlap_fraction, 0.0), 1.0)
        hidden = comm - exposed

        lbl = {"component": self.component}
        g = REGISTRY.gauge(
            "pt_step_time_breakdown",
            "measured per-step wall time split into compute/collective/"
            "exposed_comm/host/stall (buckets sum to the measured step "
            "time; collective = comm hidden behind overlap-window "
            "compute, exposed_comm = the rest)", "s")
        g.set(compute, bucket="compute", **lbl)
        g.set(hidden, bucket="collective", **lbl)
        g.set(exposed, bucket="exposed_comm", **lbl)
        g.set(host, bucket="host", **lbl)
        g.set(stall, bucket="stall", **lbl)
        if self.overlap_async > 0:
            # sync-lowered backends (CPU CI) are structurally 100%
            # exposed; publishing that would page the sentry's ratio
            # band on a non-event, so the fraction gauge exists only
            # where overlap machinery is actually in play
            REGISTRY.gauge(
                "pt_exposed_comm_fraction",
                "exposed / total priced comm seconds of the executable "
                "on the clock (only published when it has async "
                "collective windows)").set(self.overlap_fraction, **lbl)
        REGISTRY.gauge(
            "pt_model_flops_utilization",
            "HLO-attributed flops / (measured time x device peak) — the "
            "one analytical MFU definition (shared with bench "
            "mfu_analytical and graph_lint's flop floor)").set(mfu, **lbl)
        REGISTRY.gauge(
            "pt_hbm_bw_utilization",
            "HLO-attributed HBM bytes / (measured time x HBM "
            "bandwidth)").set(hbm, **lbl)
        REGISTRY.gauge(
            "pt_step_time_predicted_over_measured",
            "roofline-predicted / measured step time — cost-model drift "
            "as a monitored signal").set(ratio, **lbl)
        return {"mfu": mfu, "hbm_bw_utilization": hbm,
                "predicted_over_measured": ratio,
                "exposed_comm_fraction": self.overlap_fraction,
                "breakdown": {"compute": compute, "collective": hidden,
                              "exposed_comm": exposed,
                              "host": host, "stall": stall}}
