"""Goodput ledger — split training wall-time into accounted buckets.

Large-scale training accounting in the Google style: *goodput* is the
fraction of wall-clock a run spends making forward progress that survives
to the final checkpoint. Everything else — compiling, checkpointing,
restoring, replaying rolled-back steps, winding down for a preemption — is
overhead the resilience/compile subsystems exist to shrink, and a number
nobody measures never shrinks.

The ledger is a wall-clock *state machine*, not a profiler: at any instant
exactly one bucket owns the clock (default ``productive_step`` while a run
is active), and :meth:`span` switches attribution for its dynamic extent.
Buckets therefore sum to the run's measured wall-time *exactly* — the
acceptance invariant — and metering happens only at the boundaries the
training loop already crosses (dispatch, log, checkpoint, restore), never
adding a device fence.

Rollback accounting works by *reclassification*: :meth:`note_checkpoint`
watermarks the productive seconds at each committed step; when the runtime
rolls back to step S, the productive time accrued since S's watermark is
moved into ``rollback_wasted`` — those steps will be replayed, so their
first execution bought nothing.

Buckets:

``productive_step``   default attribution while a run is active
``compile``           trace + XLA compile (core/compile_cache meters it)
``checkpoint_save``   host-blocking part of CheckpointManager.save/finalize
``restore``           CheckpointManager.restore (resume + rollback loads)
``rollback_wasted``   productive time reclassified by note_rollback
``preemption_lost``   SIGTERM latch → orderly exit (minus nested saves)
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from .metrics import REGISTRY

__all__ = ["BUCKETS", "GoodputLedger", "ledger"]

BUCKETS = ("productive_step", "compile", "checkpoint_save", "restore",
           "rollback_wasted", "preemption_lost")


class GoodputLedger:
    def __init__(self):
        self._lock = threading.RLock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.buckets: Dict[str, float] = {b: 0.0 for b in BUCKETS}
            self._stack = []          # nested span bucket names
            self._last_t: Optional[float] = None
            self._depth = 0           # nested run_start (fit-in-fit probes)
            self._marks: Dict[int, float] = {}   # step -> productive@mark
            self.rollbacks = 0

    # -- internal clock ------------------------------------------------------

    def _settle(self, now: float) -> None:
        """Credit the elapsed slice to the currently-owning bucket."""
        if self._last_t is None:
            return
        cur = self._stack[-1] if self._stack else "productive_step"
        self.buckets[cur] += max(0.0, now - self._last_t)
        self._last_t = now

    # -- run lifecycle -------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._depth > 0

    def run_start(self) -> None:
        with self._lock:
            self._depth += 1
            if self._depth == 1:
                self._last_t = time.perf_counter()

    def run_end(self) -> None:
        with self._lock:
            if self._depth == 0:
                return
            self._settle(time.perf_counter())
            self._depth -= 1
            if self._depth == 0:
                self._last_t = None

    # -- attribution ---------------------------------------------------------

    @contextmanager
    def span(self, bucket: str):
        """Attribute the enclosed wall-time to ``bucket``. Nestable: an
        inner span owns the clock for its extent (a checkpoint save inside
        a preemption wind-down books as checkpoint_save). Outside an
        active run this is a timing no-op — the sum-to-wall-time invariant
        holds over the run window only."""
        if bucket not in self.buckets:
            raise ValueError(f"unknown goodput bucket {bucket!r}")
        with self._lock:
            if not self.running:
                active = False
            else:
                active = True
                self._settle(time.perf_counter())
                self._stack.append(bucket)
        try:
            yield self
        finally:
            if active:
                with self._lock:
                    if self.running:
                        self._settle(time.perf_counter())
                    if self._stack and self._stack[-1] == bucket:
                        self._stack.pop()

    def note_checkpoint(self, step: int) -> None:
        """Watermark the productive seconds at a committed step — the
        anchor a later rollback reclassifies against."""
        with self._lock:
            if not self.running:
                return
            self._settle(time.perf_counter())
            self._marks[int(step)] = self.buckets["productive_step"]

    def note_rollback(self, step: int) -> None:
        """Move the productive time accrued since ``step``'s watermark
        into ``rollback_wasted`` (no watermark — e.g. resumed from a
        previous process — wastes everything since run start, which is
        exactly what gets replayed)."""
        with self._lock:
            if not self.running:
                return
            self._settle(time.perf_counter())
            mark = self._marks.get(int(step), 0.0)
            wasted = max(0.0, self.buckets["productive_step"] - mark)
            self.buckets["productive_step"] -= wasted
            self.buckets["rollback_wasted"] += wasted
            self.rollbacks += 1
            # replayed ground re-marks as it is re-checkpointed
            self._marks = {s: m for s, m in self._marks.items()
                           if s <= int(step)}

    # -- reporting -----------------------------------------------------------

    def totals(self) -> Dict[str, float]:
        """Bucket seconds + ``total_s`` + ``goodput_fraction`` (productive
        over total; 0 when nothing elapsed). Settles the clock first so a
        snapshot mid-run is exact."""
        with self._lock:
            if self.running:
                self._settle(time.perf_counter())
            out = {b: round(v, 6) for b, v in self.buckets.items()}
        total = sum(out.values())
        out["total_s"] = round(total, 6)
        out["goodput_fraction"] = (
            round(out["productive_step"] / total, 6) if total > 0 else 0.0)
        return out

    def publish(self) -> None:
        """Push the bucket totals into the metrics registry (gauges
        ``pt_goodput_seconds{bucket=}`` + ``pt_goodput_fraction``)."""
        if not REGISTRY.enabled:
            return
        t = self.totals()
        g = REGISTRY.gauge("pt_goodput_seconds",
                           "wall-time per goodput bucket", "s")
        for b in BUCKETS:
            g.set(t[b], bucket=b)
        REGISTRY.gauge("pt_goodput_fraction",
                       "productive_step / total wall-time").set(
            t["goodput_fraction"])
        REGISTRY.gauge("pt_goodput_total_seconds",
                       "accounted wall-time", "s").set(t["total_s"])


_LEDGER = GoodputLedger()


def ledger() -> GoodputLedger:
    """The process-wide ledger (one training driver per process — same
    single-writer shape as the CheckpointManager)."""
    return _LEDGER
