"""Process-wide metrics registry: Counter / Gauge / Histogram with labels.

Reference analogue: the profiler_statistic + fleet monitor half of the
reference stack (paddle/fluid/platform/profiler's statistics plus the ips
timer) — the *metrics* plane that pairs with our tracing plane
(``profiler.RecordEvent``). Same design discipline as RecordEvent: a metric
mutation while nothing is attached is ONE attribute load + branch, so
instrumented hot paths (serving ticks, trainer log boundaries) cost nothing
in production runs that don't opt in.

The registry is deliberately stdlib-only and pull-based:

* **Instruments** — :class:`Counter` (monotonic), :class:`Gauge`
  (point-in-time), :class:`Histogram` (bucketed counts + sum/count + a
  bounded reservoir for percentile summaries). Label sets are kwargs; each
  distinct label combination is its own series.
* **Collection** — :meth:`MetricsRegistry.collect` snapshots every series
  into plain dicts; exporters (JSONL / Prometheus text / console) render
  the snapshot, they never reach into live state.
* **Flight ring** — when a sample ring is attached (flight recorder), every
  accepted mutation also appends ``(ts, name, labels, value)`` to a bounded
  deque, so a crash dump carries the last few thousand samples.

Threading: one registry lock taken only on the enabled path; mutation off
the hot loop (log/drain/reconcile boundaries) keeps contention irrelevant.
"""

from __future__ import annotations

import math
import threading
import time
import warnings
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "registry", "enabled", "DEFAULT_BUCKETS", "MAX_LABEL_SETS"]

# Prometheus-style default latency buckets (seconds), inf implied
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_RESERVOIR = 1024        # recent observations kept per histogram series

# label-cardinality guard (ISSUE 10 satellite): distinct label sets a
# single metric may hold before new ones fold into the overflow series —
# a buggy per-request label (rid=..., trace_id=...) must not grow
# collect()/export cost without bound in a long-lived process
MAX_LABEL_SETS = 128
_OVERFLOW_LABELS = {"label_overflow": "true"}
_OVERFLOW_KEY = (("label_overflow", "true"),)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """Base: name + help + unit, per-label-set series under the registry
    lock. Subclasses only define the series payload and its mutation."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", unit: str = "",
                 registry: "MetricsRegistry" = None):
        self.name = name
        self.help = help
        self.unit = unit
        self._series: Dict[Tuple, object] = {}
        self._reg = registry
        self._overflow_warned = False

    def _slot(self, labels: Dict[str, str]) -> Tuple[Tuple, Dict]:
        """(series key, effective labels) under the registry lock.
        Existing series always resolve to themselves; a NEW label set
        past the per-metric cap folds into the ``label_overflow="true"``
        series (warned once per metric) so cardinality stays bounded
        while the mutation is still counted somewhere visible."""
        key = _label_key(labels)
        if key in self._series:
            return key, labels
        cap = self._reg.max_label_sets
        if cap is not None and len(self._series) >= cap \
                and key != _OVERFLOW_KEY:
            if not self._overflow_warned:
                self._overflow_warned = True
                warnings.warn(
                    f"metric {self.name!r}: over {cap} distinct label "
                    f"sets — folding new ones into label_overflow="
                    f"\"true\" (check for an unbounded per-request "
                    f"label)", RuntimeWarning, stacklevel=3)
            return _OVERFLOW_KEY, dict(_OVERFLOW_LABELS)
        return key, labels

    def _sample(self, labels: Dict[str, str], value: float) -> None:
        ring = self._reg._ring
        if ring is not None:
            ring.append((time.time(), self.name, labels, value))

    def labels_seen(self) -> List[Dict[str, str]]:
        with self._reg._lock:
            return [dict(k) for k in self._series]

    def clear(self, **labels) -> None:
        """Drop one series. The percentile-publishing contract (ISSUE 10
        satellite audit): a publisher whose source window went empty
        clears its gauge rather than leaving the last value to read as
        current — an absent series is honest (and is what the Staleness
        rule kind watches for), a stale one lies. Disabled plane: no-op
        like every other mutator — disable() disarms but deliberately
        keeps series (reset() is the destructive call)."""
        if not self._reg.enabled:
            return
        with self._reg._lock:
            self._series.pop(_label_key(labels), None)


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        if value < 0:
            raise ValueError(f"counter {self.name}: negative increment")
        with reg._lock:
            key, labels = self._slot(labels)
            self._series[key] = self._series.get(key, 0.0) + value
            self._sample(labels, self._series[key])

    def value(self, **labels) -> float:
        with self._reg._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            key, labels = self._slot(labels)
            self._series[key] = float(value)
            self._sample(labels, float(value))

    def add(self, value: float, **labels) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            key, labels = self._slot(labels)
            self._series[key] = self._series.get(key, 0.0) + float(value)
            self._sample(labels, self._series[key])

    def value(self, **labels) -> float:
        with self._reg._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class _HistSeries:
    __slots__ = ("counts", "sum", "count", "recent")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)   # +1 = +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.recent = deque(maxlen=_RESERVOIR)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "", unit: str = "",
                 registry: "MetricsRegistry" = None,
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, unit, registry)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, value: float, **labels) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        value = float(value)
        with reg._lock:
            key, labels = self._slot(labels)
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.buckets))
            i = 0
            for b in self.buckets:
                if value <= b:
                    break
                i += 1
            s.counts[i] += 1
            s.sum += value
            s.count += 1
            s.recent.append(value)
            self._sample(labels, value)

    def percentile(self, q: float, **labels) -> Optional[float]:
        """Percentile over the bounded reservoir of recent observations
        (summary convenience — the exact data lives in the buckets)."""
        with self._reg._lock:
            s = self._series.get(_label_key(labels))
            if s is None or not s.recent:
                return None
            vals = sorted(s.recent)
        idx = min(len(vals) - 1, max(0, math.ceil(q / 100.0 * len(vals)) - 1))
        return float(vals[idx])


class MetricsRegistry:
    """Named-metric table + the process-wide enable switch.

    ``enabled`` is False until an exporter/flight-ring attaches (or
    :meth:`enable` is called): every instrument mutation short-circuits on
    that one flag, which is what keeps instrumented code near-zero cost in
    runs that never look at metrics."""

    def __init__(self):
        # REENTRANT: the flight recorder's SIGTERM/excepthook handlers run
        # dump() -> collect() on the main thread, possibly interrupting a
        # frame that already holds this lock — a plain Lock would
        # self-deadlock the crash path (a mid-mutation histogram read in
        # that case is an acceptable price for a dump that completes)
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self.enabled = False
        self._ring: Optional[deque] = None
        # per-metric distinct-label-set cap (None disables the guard)
        self.max_label_sets: Optional[int] = MAX_LABEL_SETS

    # -- construction (get-or-create; idempotent by name) -------------------

    def _get(self, cls, name, help, unit, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, unit,
                                              registry=self, **kw)
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{m.kind}, not {cls.kind}")
            return m

    def counter(self, name: str, help: str = "", unit: str = "") -> Counter:
        return self._get(Counter, name, help, unit)

    def gauge(self, name: str, help: str = "", unit: str = "") -> Gauge:
        return self._get(Gauge, name, help, unit)

    def histogram(self, name: str, help: str = "", unit: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, unit, buckets=buckets)

    # -- lifecycle -----------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def attach_ring(self, ring: deque) -> None:
        """Route every accepted sample into ``ring`` (flight recorder);
        implies enable() — samples must flow to be recorded."""
        self._ring = ring
        self.enabled = True

    def detach_ring(self) -> None:
        self._ring = None

    def reset(self) -> None:
        """Drop every series (tests / bench probes). Metric OBJECTS stay
        registered so cached references in instrumented modules stay
        valid."""
        with self._lock:
            for m in self._metrics.values():
                m._series = {}

    # -- collection -----------------------------------------------------------

    def collect(self) -> List[dict]:
        """Snapshot every series as plain dicts (one entry per label set):

        counters/gauges: ``{"name","type","unit","labels","value"}``
        histograms add ``{"count","sum","buckets":[[le,cumcount],...],
        "p50","p99"}``.
        """
        out: List[dict] = []
        with self._lock:
            items = [(m, dict(m._series)) for m in self._metrics.values()]
        for m, series in items:
            if m.kind == "histogram" and not series:
                # stable series set for scrapers (ISSUE 9 satellite): a
                # registered histogram that has observed nothing still
                # exposes its zeroed _bucket/_sum/_count lines — a series
                # that appears only on first observation looks like a
                # target reset to dashboards and breaks rate() queries
                rows = [[le, 0] for le in list(m.buckets) + ["+Inf"]]
                out.append({"name": m.name, "type": m.kind, "unit": m.unit,
                            "labels": {}, "count": 0, "sum": 0.0,
                            "buckets": rows})
                continue
            for key, payload in series.items():
                entry = {"name": m.name, "type": m.kind, "unit": m.unit,
                         "labels": dict(key)}
                if m.kind == "histogram":
                    cum, rows = 0, []
                    for le, c in zip(list(m.buckets) + ["+Inf"],
                                     payload.counts):
                        cum += c
                        rows.append([le, cum])
                    entry.update(count=payload.count,
                                 sum=round(payload.sum, 9), buckets=rows)
                    for q in (50, 99):
                        p = m.percentile(q, **dict(key))
                        if p is not None:
                            entry[f"p{q}"] = round(p, 9)
                else:
                    entry["value"] = payload
                out.append(entry)
        return out


REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry (the moral analogue of RecordEvent's
    process-wide collector)."""
    return REGISTRY


def enabled() -> bool:
    return REGISTRY.enabled
