"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capabilities of PaddlePaddle (reference mounted
at /root/reference — see SURVEY.md), built on JAX/XLA/Pallas/pjit idioms:
functional core, GSPMD parallelism, Pallas hot kernels. The top-level
namespace mirrors ``paddle.*``: tensor functions live here, layers under
``nn``, optimizers under ``optimizer``, parallelism under ``distributed``.
"""

from .core import jax_compat as _jax_compat  # noqa: F401 — installs jax.shard_map shim
from .core import dtype as _dtype_ns
from .core.dtype import (bool_, uint8, int8, int16, int32, int64, float16,
                         bfloat16, float32, float64, complex64, complex128,
                         dtype, finfo, iinfo)
from .core.dtype import bool_ as bool  # noqa: A001 — paddle exports `bool`
from .core.flags import set_flags, get_flags
from .core.rng import seed

from . import amp
from . import autograd
from . import distributed
from . import io
from . import nn
from . import optimizer
from . import ops
from . import tensor
from .linalg import eigvalsh, eigvals, eig  # top-level parity

# paddle-style: every tensor function is also a top-level symbol
from .tensor import *  # noqa: F401,F403

# paddle-style Tensor METHODS on the runtime array type (x.numpy(),
# x.cast(...), x.unsqueeze(...), clear backward() migration error, ...)
from .tensor import methods as _tensor_methods
_tensor_methods.install()
from .tensor import Tensor

from .nn.layer import set_default_dtype, get_default_dtype

from .framework import save, load, set_device, get_device, is_compiled_with_cuda, \
    is_compiled_with_tpu, device_count, no_grad
from .device import (is_compiled_with_rocm, is_compiled_with_xpu,  # noqa: E402
                     is_compiled_with_ipu, is_compiled_with_custom_device)
from .base import (CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace, XPUPlace,
                   IPUPlace, ParamAttr, LazyGuard, DataParallel,
                   in_dynamic_mode, in_dynamic_or_pir_mode, enable_static,
                   disable_static, enable_grad, set_grad_enabled,
                   is_grad_enabled, disable_signal_handler, set_printoptions,
                   get_rng_state, set_rng_state, get_cuda_rng_state,
                   set_cuda_rng_state, create_parameter, create_global_var,
                   check_shape)
from .autograd import grad
from .hapi.summary import flops
from . import jit
from . import static
from . import metric
from . import device
from . import fft
from . import sparse
from . import distribution
from . import vision
from . import quantization
from . import incubate
from . import decomposition
from . import dataset
from . import version
from . import inference
from . import serving_fabric
from . import linalg
from . import resilience
from . import text
from . import audio
from . import geometric
from . import utils
from . import profiler
from . import onnx
from . import reader
from . import regularizer
from . import signal
from . import sysconfig
from . import callbacks
from . import hub
from .reader import batch
from . import hapi
from .hapi import Model
from .hapi.summary import summary

__version__ = version.full_version
