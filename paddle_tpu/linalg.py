"""paddle_tpu.linalg — linear-algebra namespace (reference:
python/paddle/linalg.py re-exporting tensor/linalg.py). Dense decompositions
lower to XLA's native QR/SVD/Eig kernels."""

from __future__ import annotations

import jax.numpy as jnp

from .tensor import (norm, matrix_power, cholesky, inverse as inv, pinv,
                     solve, svd, qr, eigh, det, slogdet, matrix_rank)

__all__ = [
    "norm", "matrix_power", "cholesky", "inv", "pinv", "solve", "svd", "qr",
    "eigh", "det", "slogdet", "matrix_rank", "eig", "eigvals", "eigvalsh",
    "lstsq", "lu", "triangular_solve", "cholesky_solve", "multi_dot", "cov",
    "corrcoef", "matmul", "cross", "dot", "householder_product",
]

inverse = inv


def eig(x, name=None):
    return jnp.linalg.eig(x)


def eigvals(x, name=None):
    return jnp.linalg.eigvals(x)


def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


def lu(x, pivot: bool = True, get_infos: bool = False, name=None):
    import jax.scipy.linalg as jsl
    lu_mat, piv = jsl.lu_factor(x)
    if get_infos:
        return lu_mat, piv, jnp.zeros((), jnp.int32)
    return lu_mat, piv


def triangular_solve(x, y, upper: bool = True, transpose: bool = False,
                     unitriangular: bool = False, name=None):
    import jax.scipy.linalg as jsl
    return jsl.solve_triangular(x, y, lower=not upper, trans=int(transpose),
                                unit_diagonal=unitriangular)


def cholesky_solve(x, y, upper: bool = False, name=None):
    import jax.scipy.linalg as jsl
    return jsl.cho_solve((y, not upper), x)


def multi_dot(arrays, name=None):
    return jnp.linalg.multi_dot(arrays)


def cov(x, rowvar: bool = True, ddof: bool = True, fweights=None,
        aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def corrcoef(x, rowvar: bool = True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


def matmul(x, y, transpose_x: bool = False, transpose_y: bool = False,
           name=None):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def cross(x, y, axis: int = 9, name=None):
    axis = -1 if axis == 9 else axis
    return jnp.cross(x, y, axis=axis)


def dot(x, y, name=None):
    return jnp.dot(x, y)


def householder_product(x, tau, name=None):
    """Q from householder reflectors (geqrf convention)."""
    m, n = x.shape[-2], x.shape[-1]
    q = jnp.eye(m, dtype=x.dtype)
    for i in range(n):
        v = jnp.concatenate([jnp.zeros((i,), x.dtype), jnp.ones((1,), x.dtype),
                             x[..., i + 1:, i]])
        q = q - tau[..., i] * (q @ v[:, None]) @ v[None, :]
    return q[..., :, :n] if m >= n else q
