"""Learning-rate schedulers.

Reference: python/paddle/optimizer/lr.py (~20 schedulers; LRScheduler base
with ``step()``/``get_lr()``/``state_dict()``). Semantics match: ``step()``
advances ``last_epoch`` and recomputes ``last_lr``; optimizers read
``scheduler.get_last_lr()`` each step (host-side scalar — passed into the
jitted update as an argument, so changing lr never retraces).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence


import weakref
_SCHED_REGISTRY = weakref.WeakValueDictionary()  # name -> scheduler
_SCHED_SERIAL = [0]   # names must stay unique after collection


class LRScheduler:
    #: True when :meth:`lr_of` is a pure jnp-traceable function of ``step``
    #: (closed-form schedule) — the Trainer then evaluates the LR *inside*
    #: the compiled step/superstep instead of transferring a host scalar.
    #: May be overridden per-instance (e.g. LinearWarmup wrapping a
    #: non-functional scheduler).
    functional = False

    def __init__(self, learning_rate: float = 0.1, last_epoch: int = -1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.last_lr = learning_rate
        # reference schedulers expose a fetchable name in static mode
        # (Executor.run(fetch_list=[sched.name]) reads the current lr)
        _SCHED_SERIAL[0] += 1
        self.name = f"learning_rate_{_SCHED_SERIAL[0]}"
        _SCHED_REGISTRY[self.name] = self
        self.step()  # paddle initializes by stepping to epoch 0

    def get_lr(self) -> float:
        raise NotImplementedError

    def step(self, epoch: Optional[int] = None) -> None:
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()

    def get_last_lr(self) -> float:
        return self.last_lr

    def lr_of(self, step):
        """Functional view of the schedule: the LR this scheduler applies at
        trainer step ``step`` (i.e. ``get_lr()`` with ``last_epoch=step``),
        WITHOUT mutating scheduler state.

        The base implementation evaluates host-side (works for every
        closed-form scheduler; stateful ones like ReduceOnPlateau simply
        return their current LR for any step). Schedulers with
        ``functional = True`` override it with a jnp-traceable version so a
        compiled (super)step can derive the LR on-device from the step
        counter — zero host→device LR transfers.
        """
        prev_epoch, prev_lr = self.last_epoch, self.last_lr
        try:
            self.last_epoch = int(step)
            return float(self.get_lr())
        finally:
            self.last_epoch, self.last_lr = prev_epoch, prev_lr

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state["last_epoch"]
        self.last_lr = state["last_lr"]

    # paddle compat
    set_dict = set_state_dict
    state_keys = state_dict

    def __call__(self) -> float:
        return self.last_lr


class NoamDecay(LRScheduler):
    def __init__(self, d_model: int, warmup_steps: int, learning_rate: float = 1.0,
                 last_epoch: int = -1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return (self.base_lr * self.d_model ** -0.5 *
                min(step ** -0.5, step * self.warmup_steps ** -1.5))

    functional = True

    def lr_of(self, step):
        import jax.numpy as jnp
        s = jnp.maximum(jnp.asarray(step, jnp.float32), 1.0)
        return jnp.asarray(
            self.base_lr * self.d_model ** -0.5
            * jnp.minimum(s ** -0.5, s * self.warmup_steps ** -1.5),
            jnp.float32)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries: Sequence[int], values: Sequence[float],
                 last_epoch: int = -1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]

    functional = True

    def lr_of(self, step):
        import jax.numpy as jnp
        idx = jnp.searchsorted(jnp.asarray(self.boundaries, jnp.int32),
                               jnp.asarray(step, jnp.int32), side="right")
        return jnp.asarray(self.values, jnp.float32)[idx]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float, last_epoch: int = -1,
                 verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)

    functional = True

    def lr_of(self, step):
        import jax.numpy as jnp
        s = jnp.asarray(step, jnp.float32)
        return jnp.asarray(self.base_lr * jnp.exp(-self.gamma * s),
                           jnp.float32)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float, last_epoch: int = -1,
                 verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch

    functional = True

    def lr_of(self, step):
        import jax.numpy as jnp
        s = jnp.asarray(step, jnp.float32)
        return jnp.asarray(self.base_lr * self.gamma ** s, jnp.float32)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float, last_epoch: int = -1,
                 verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)

    functional = True

    def lr_of(self, step):
        import jax.numpy as jnp
        s = jnp.asarray(step, jnp.float32)
        return jnp.asarray(self.base_lr / (1 + self.gamma * s), jnp.float32)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate: float, decay_steps: int, end_lr: float = 0.0001,
                 power: float = 1.0, cycle: bool = False, last_epoch: int = -1,
                 verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        if self.cycle:
            div = math.ceil(step / self.decay_steps) if step > 0 else 1
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        return ((self.base_lr - self.end_lr) *
                (1 - step / decay_steps) ** self.power + self.end_lr)

    functional = True

    def lr_of(self, step):
        import jax.numpy as jnp
        s = jnp.asarray(step, jnp.float32)
        if self.cycle:
            div = jnp.maximum(jnp.ceil(s / self.decay_steps), 1.0)
            decay = self.decay_steps * div
        else:
            decay = jnp.asarray(self.decay_steps, jnp.float32)
            s = jnp.minimum(s, decay)
        return jnp.asarray(
            (self.base_lr - self.end_lr) * (1 - s / decay) ** self.power
            + self.end_lr, jnp.float32)


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps: int, start_lr: float,
                 end_lr: float, last_epoch: int = -1, verbose=False):
        self.lr_after = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        # functional iff the post-warmup target is (the warmup ramp itself
        # is closed-form; a wrapped stateful scheduler pins us host-side)
        self.functional = (not isinstance(learning_rate, LRScheduler)
                           or getattr(learning_rate, "functional", False))
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * self.last_epoch / max(
                self.warmup_steps, 1) + self.start_lr
        if isinstance(self.lr_after, LRScheduler):
            self.lr_after.step(self.last_epoch - self.warmup_steps)
            return self.lr_after.get_last_lr()
        return self.lr_after

    def lr_of(self, step):
        if not self.functional:
            # host fallback; get_lr() advances the wrapped scheduler, so
            # snapshot+restore its FULL state around the probe —
            # state_dict() alone misses e.g. ReduceOnPlateau's
            # best/num_bad/cooldown_counter, which the probe would corrupt
            inner = {k: (list(v) if isinstance(v, list) else v)
                     for k, v in vars(self.lr_after).items()}
            try:
                return super().lr_of(step)
            finally:
                self.lr_after.__dict__.update(inner)
        import jax.numpy as jnp
        s = jnp.asarray(step, jnp.float32)
        warm = ((self.end_lr - self.start_lr) * s
                / max(self.warmup_steps, 1) + self.start_lr)
        if isinstance(self.lr_after, LRScheduler):
            after = self.lr_after.lr_of(
                jnp.asarray(step, jnp.int32) - self.warmup_steps)
        else:
            after = jnp.asarray(self.lr_after, jnp.float32)
        return jnp.asarray(jnp.where(s < self.warmup_steps, warm, after),
                           jnp.float32)


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate: float, T_max: int, eta_min: float = 0.0,
                 last_epoch: int = -1, verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (self.eta_min + (self.base_lr - self.eta_min) *
                (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2)

    functional = True

    def lr_of(self, step):
        import jax.numpy as jnp
        s = jnp.asarray(step, jnp.float32)
        return jnp.asarray(
            self.eta_min + (self.base_lr - self.eta_min)
            * (1 + jnp.cos(jnp.pi * s / self.T_max)) / 2, jnp.float32)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate: float, step_size: int, gamma: float = 0.1,
                 last_epoch: int = -1, verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)

    functional = True

    def lr_of(self, step):
        import jax.numpy as jnp
        n = (jnp.asarray(step, jnp.int32) // self.step_size).astype(
            jnp.float32)
        return jnp.asarray(self.base_lr * self.gamma ** n, jnp.float32)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate: float, milestones: Sequence[int],
                 gamma: float = 0.1, last_epoch: int = -1, verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma ** n

    functional = True

    def lr_of(self, step):
        import jax.numpy as jnp
        n = jnp.sum(jnp.asarray(step, jnp.int32)
                    >= jnp.asarray(self.milestones, jnp.int32)).astype(
            jnp.float32)
        return jnp.asarray(self.base_lr * self.gamma ** n, jnp.float32)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate: float, lr_lambda, last_epoch: int = -1,
                 verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate: float, mode: str = "min", factor: float = 0.1,
                 patience: int = 10, threshold: float = 1e-4,
                 threshold_mode: str = "rel", cooldown: int = 0, min_lr: float = 0,
                 epsilon: float = 1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.epsilon = epsilon
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.base_lr = learning_rate
        self.last_lr = learning_rate
        self.last_epoch = 0

    def _better(self, a, b):
        if self.mode == "min":
            if self.threshold_mode == "rel":
                return a < b * (1 - self.threshold)
            return a < b - self.threshold
        if self.threshold_mode == "rel":
            return a > b * (1 + self.threshold)
        return a > b + self.threshold

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        m = float(metrics)
        self.last_epoch += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        if self.best is None or self._better(m, self.best):
            self.best = m
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.num_bad > self.patience:
            new_lr = max(self.last_lr * self.factor, self.min_lr)
            if self.last_lr - new_lr > self.epsilon:
                self.last_lr = new_lr
            self.cooldown_counter = self.cooldown
            self.num_bad = 0

    def get_lr(self):
        return self.last_lr


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate: float, total_steps: int,
                 divide_factor: float = 25.0, end_learning_rate: float = 0.0001,
                 phase_pct: float = 0.3, anneal_strategy: str = "cos",
                 three_phase: bool = False, last_epoch: int = -1, verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _anneal(self, start, end, pct):
        if self.anneal == "cos":
            return end + (start - end) / 2.0 * (math.cos(math.pi * pct) + 1)
        return (end - start) * pct + start

    def get_lr(self):
        step = min(self.last_epoch, self.total_steps)
        up_steps = int(self.phase_pct * self.total_steps)
        if step <= up_steps:
            return self._anneal(self.initial_lr, self.max_lr, step / max(up_steps, 1))
        down = (step - up_steps) / max(self.total_steps - up_steps, 1)
        return self._anneal(self.max_lr, self.end_lr, down)


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate: float, max_learning_rate: float,
                 step_size_up: int, step_size_down: Optional[int] = None,
                 mode: str = "triangular", exp_gamma: float = 1.0,
                 scale_fn=None, scale_mode: str = "cycle", last_epoch: int = -1,
                 verbose=False):
        self.base_lr_ = base_learning_rate
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down if step_size_down is not None else step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        total = self.up + self.down
        cycle = math.floor(1 + self.last_epoch / total)
        x = self.last_epoch - (cycle - 1) * total
        pct = x / self.up if x <= self.up else 1 - (x - self.up) / self.down
        scale = 1.0
        if self.mode == "triangular2":
            scale = 1 / (2 ** (cycle - 1))
        elif self.mode == "exp_range":
            scale = self.exp_gamma ** self.last_epoch
        return self.base_lr_ + (self.max_lr - self.base_lr_) * pct * scale


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate: float, T_0: int, T_mult: int = 1,
                 eta_min: float = 0.0, last_epoch: int = -1, verbose=False):
        self.T_0 = T_0
        self.T_mult = T_mult
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = self.last_epoch
        T_i = self.T_0
        while t >= T_i:
            t -= T_i
            T_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * t / T_i)) / 2


# ---------------------------------------------------------------------------
# fluid-era decay FUNCTIONS (reference: optimizer/lr.py:2552-3100 keeps them
# importable; dygraph mode returns the scheduler object — the behavior kept
# here; static lr-variable weaving is subsumed by the scheduler's get_lr()
# read at each Executor train step)
# ---------------------------------------------------------------------------

class _FluidDecay(LRScheduler):
    """Closed-form fluid decay (reference: the static lr ops in
    lr.py:2600+): lr(step) given by ``fn``; advanced automatically per
    Executor train step (_auto_step), like the reference's appended ops."""

    _auto_step = True

    def __init__(self, fn, learning_rate):
        self._fn = fn
        super().__init__(learning_rate)

    def get_lr(self):
        return self._fn(max(self.last_epoch, 0))


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    s = NoamDecay(d_model=d_model, warmup_steps=warmup_steps,
                  learning_rate=learning_rate)
    s._auto_step = True
    return s


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    import math as _m

    def fn(step):
        t = step / float(decay_steps)
        if staircase:
            t = _m.floor(t)
        return learning_rate * (decay_rate ** t)
    return _FluidDecay(fn, learning_rate)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    import math as _m

    def fn(step):
        t = step / float(decay_steps)
        if staircase:
            t = _m.floor(t)
        return learning_rate * _m.exp(-decay_rate * t)
    return _FluidDecay(fn, learning_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    import math as _m

    def fn(step):
        t = step / float(decay_steps)
        if staircase:
            t = _m.floor(t)
        return learning_rate / (1.0 + decay_rate * t)
    return _FluidDecay(fn, learning_rate)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    s = PolynomialDecay(learning_rate=learning_rate,
                        decay_steps=decay_steps,
                        end_lr=end_learning_rate, power=power,
                        cycle=cycle)
    s._auto_step = True   # fluid decays advance per executor step
    return s


def cosine_decay(learning_rate, step_each_epoch, epochs):
    import math as _m

    def fn(step):
        epoch = step // step_each_epoch      # fluid: floor to epochs
        return 0.5 * learning_rate * (_m.cos(epoch * _m.pi / epochs) + 1)
    return _FluidDecay(fn, learning_rate)


def piecewise_decay(boundaries, values):
    s = PiecewiseDecay(boundaries=boundaries, values=values)
    s._auto_step = True
    return s


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    s = LinearWarmup(learning_rate=learning_rate,
                     warmup_steps=warmup_steps, start_lr=start_lr,
                     end_lr=end_lr)
    s._auto_step = True
    return s


class LinearLR(LRScheduler):
    """Linear factor ramp start_factor -> end_factor over total_steps
    (reference: optimizer/lr.py LinearLR)."""

    def __init__(self, learning_rate, total_steps, start_factor=1. / 3,
                 end_factor=1.0, last_epoch=-1, verbose=False):
        self.total_steps = total_steps
        self.start_factor = start_factor
        self.end_factor = end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = min(self.last_epoch, self.total_steps)
        frac = t / float(self.total_steps)
        factor = self.start_factor + (self.end_factor
                                      - self.start_factor) * frac
        return self.base_lr * factor

    functional = True

    def lr_of(self, step):
        import jax.numpy as jnp
        t = jnp.minimum(jnp.asarray(step, jnp.float32),
                        float(self.total_steps))
        factor = (self.start_factor + (self.end_factor - self.start_factor)
                  * t / float(self.total_steps))
        return jnp.asarray(self.base_lr * factor, jnp.float32)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Legacy global step counter variable (reference lr.py:2500). Returns
    a host counter object; the schedulers above own real step state."""
    import numpy as np
    return np.asarray([begin], np.int64)
