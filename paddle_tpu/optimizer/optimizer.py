"""Optimizers.

Reference: python/paddle/optimizer/ (Optimizer base at optimizer.py:103;
adamw.py, adam.py, momentum.py, lamb.py, sgd.py...). Re-designed functionally
for JAX: every optimizer is defined by two pure functions —

    state = opt.init_state(params)                     # params: flat dict
    params, state = opt.apply_gradients(params, grads, state, lr=None)

which jit/shard cleanly (the trainer donates both pytrees). On top of that
sits the paddle-shaped imperative API: ``opt.step(grads)`` updates the bound
``Layer``'s Parameters in place and advances the LR scheduler.

Master-weight handling mirrors the reference's multi_precision kernels
(e.g. paddle/phi/kernels/gpu/adamw_kernel.cu): when a param is bf16/fp16 an
fp32 master copy lives in the optimizer state, moments are fp32, and the
model weight is a cast of the master after each update.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp

from ..nn.layer import Layer, Parameter
from .clip import ClipGradBase, ClipGradByGlobalNorm
from .lr import LRScheduler


def place_opt_state(state: Dict, params: Dict[str, jax.Array], kind: str):
    """Move an optimizer-state tree into memory space ``kind``
    ("pinned_host" / "device") in ONE batched transfer, laying each
    param-shaped slot/master leaf out like ITS PARAM — an offload
    round-trip must not commit a previously-uncommitted leaf to a single
    device while its mesh-sharded param spans the mesh. The host side of
    GroupSharded ``offload=True`` (reference: group_sharded_storage.py);
    used by Optimizer.step and Trainer.train_step."""
    from jax.sharding import NamedSharding, PartitionSpec

    any_sh = next(iter(params.values())).sharding if params else None
    if any_sh is None:
        return state
    rep = (NamedSharding(any_sh.mesh, PartitionSpec())
           if isinstance(any_sh, NamedSharding) else any_sh)

    def sh_of(path_name, leaf):
        base = (params[path_name].sharding
                if path_name in params else rep)
        return base.with_memory_kind(kind)

    shardings = {}
    for k, v in state.items():
        if k in ("slots", "master") and isinstance(v, dict):
            shardings[k] = {
                name: ({sk: sh_of(name, sv) for sk, sv in entry.items()}
                       if isinstance(entry, dict) else sh_of(name, entry))
                for name, entry in v.items()}
        else:
            shardings[k] = jax.tree.map(
                lambda x: rep.with_memory_kind(kind), v)
    return jax.device_put(state, shardings)


def _is_low_precision(x):
    return x.dtype in (jnp.bfloat16, jnp.float16)


class Optimizer:
    def __init__(self, learning_rate: Union[float, LRScheduler] = 0.001,
                 parameters=None, weight_decay: float = 0.0,
                 grad_clip: Optional[ClipGradBase] = None,
                 multi_precision: bool = True,
                 apply_decay_param_fun: Optional[Callable[[str], bool]] = None):
        self._lr = learning_rate
        self._weight_decay = weight_decay if weight_decay is not None else 0.0
        self.grad_clip = grad_clip
        self.multi_precision = multi_precision
        self.apply_decay_param_fun = apply_decay_param_fun
        # imperative binding (list of Parameter or a Layer)
        self._bound_params: Dict[str, Parameter] = {}
        if parameters is not None:
            if isinstance(parameters, Layer):
                self._bound_params = {n: p for n, p in parameters.named_parameters()
                                      if p.trainable}
            else:
                parameters = [p for p in parameters if p.trainable]
                names = [p.name or str(i) for i, p in enumerate(parameters)]
                if len(set(names)) != len(names):
                    dupes = sorted({n for n in names if names.count(n) > 1})
                    raise ValueError(
                        f"list-form parameter binding has colliding names "
                        f"{dupes[:3]} (e.g. lists from several sublayers "
                        f"concatenated, or tied params listed twice) — "
                        f"pass the Layer itself (parameters=model) or one "
                        f"root model.parameters() call, whose names are "
                        f"the unique dotted paths")
                self._bound_params = dict(zip(names, parameters))
        self._state = None

    # -- lr ----------------------------------------------------------------

    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return self._lr.get_last_lr()
        return self._lr

    def set_lr(self, lr: float) -> None:
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = lr

    def set_lr_scheduler(self, scheduler: LRScheduler) -> None:
        """Swap in an LRScheduler (reference: optimizer.py
        set_lr_scheduler:598 — same contract, subsequent get_lr() reads
        the scheduler's current value)."""
        if not isinstance(scheduler, LRScheduler):
            raise TypeError(
                f"scheduler must be an LRScheduler, got "
                f"{type(scheduler).__name__}")
        self._lr = scheduler

    def backward(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None, callbacks=None):
        """Tape-style grads-from-a-loss-value (reference optimizer.py
        backward:1380). This framework keeps no eager tape; differentiate
        the function instead and feed the grads to step()/apply_gradients:

            loss, grads = autograd.layer_grad(model, loss_fn, *inputs)
            opt.step(grads)
        """
        raise NotImplementedError(
            "optimizer.backward(loss) differentiates an eager tape, which "
            "this framework does not keep. Use autograd.layer_grad(model, "
            "loss_fn, *inputs) -> (loss, grads), then opt.step(grads) "
            "(docs/DESIGN_DECISIONS.md eager-tape entry)")

    @property
    def lr_scheduler(self):
        return self._lr if isinstance(self._lr, LRScheduler) else None

    # -- pure functional API ------------------------------------------------

    def init_state(self, params: Dict[str, jax.Array]) -> Dict:
        state = {"step": jnp.zeros([], jnp.int32)}
        if self.multi_precision:
            state["master"] = {k: v.astype(jnp.float32) for k, v in params.items()
                               if _is_low_precision(v)}
        state["slots"] = {k: self._init_slots(v) for k, v in params.items()}
        return state

    def _init_slots(self, p: jax.Array) -> Dict:
        return {}

    def _update(self, name: str, p32: jax.Array, g32: jax.Array, slots: Dict,
                lr, step) -> jax.Array:
        """Return updated fp32 param; mutate slots dict entries by replacing."""
        raise NotImplementedError

    def _decayed(self, name: str) -> bool:
        if self.apply_decay_param_fun is not None:
            return bool(self.apply_decay_param_fun(name))
        return True

    def apply_gradients(self, params: Dict[str, jax.Array],
                        grads: Dict[str, jax.Array], state: Dict,
                        lr=None) -> tuple:
        if lr is None:
            lr = self.get_lr()
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        step = state["step"] + 1
        masters = dict(state.get("master", {}))
        new_params = {}
        new_slots = {}
        for k, p in params.items():
            g = grads.get(k)
            if g is None:
                new_params[k] = p
                new_slots[k] = state["slots"][k]
                continue
            p32 = masters.get(k, p).astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            slots = dict(state["slots"][k])
            p32_new = self._update(k, p32, g32, slots, lr, step)
            new_slots[k] = slots
            if k in masters:
                masters[k] = p32_new
                new_params[k] = p32_new.astype(p.dtype)
            else:
                new_params[k] = p32_new.astype(p.dtype)
        new_state = {"step": step, "slots": new_slots}
        if "master" in state:
            new_state["master"] = masters
        return new_params, new_state

    # -- imperative API (paddle-shaped) -------------------------------------

    def step(self, grads: Optional[Dict[str, jax.Array]] = None) -> None:
        """Apply an update to the bound parameters. ``grads`` is the flat dict
        produced by jax.grad over Layer.raw_parameters() keys."""
        if grads is None:
            raise ValueError(
                "paddle_tpu optimizers need explicit grads: opt.step(grads) — "
                "compute them with paddle_tpu.autograd.grad / jax.grad.")
        params = {k: p.value for k, p in self._bound_params.items()}
        if not params:
            raise RuntimeError(
                "optimizer has no trainable parameters bound (empty list or "
                "all trainable=False) — nothing to update")
        if grads and not (set(grads) & set(params)):
            # apply_gradients skips unmatched keys — a fully-disjoint key
            # set would silently update NOTHING (e.g. grads keyed by dotted
            # paths vs an optimizer bound to a different layer's list)
            raise KeyError(
                f"no gradient key matches any bound parameter: grads use "
                f"{sorted(grads)[:3]}..., optimizer bound "
                f"{sorted(params)[:3]}... — bind the optimizer with "
                f"parameters=<same layer>.parameters() (or the Layer)")
        offload = getattr(self, "_offload_opt_state", False)
        if self._state is None:
            # fresh state is already device-resident; the post-step push
            # parks it — no initial host round trip
            self._state = self.init_state(params)
        elif offload:
            self._state = place_opt_state(self._state, params, "device")
        new_params, self._state = self.apply_gradients(params, grads, self._state)
        if offload:
            self._state = place_opt_state(self._state, params, "pinned_host")
        for k, v in new_params.items():
            self._bound_params[k].value = v

    def clear_grad(self) -> None:  # paddle API parity; grads are external here
        pass

    clear_gradients = clear_grad

    def minimize(self, loss=None, startup_program=None, parameters=None,
                 no_grad_set=None, grads=None):
        """Reference: Optimizer.minimize(optimizer.py). Two modes:

        - STATIC: ``loss`` is a program var (static.data/static.nn chain):
          register this optimizer on the loss's program — the Executor
          then runs forward+backward+update per ``exe.run`` (the classic
          static training loop; see static/__init__.py Executor.run).
        - dynamic: explicit ``grads`` (functional autograd), same as
          ``step(grads)``.
        """
        if loss is not None and hasattr(loss, "_build") \
                and hasattr(loss, "_program"):
            hooks = loss._program.__dict__.setdefault("_opt_hooks", [])
            if not any(h[0] is self for h in hooks):
                hooks.append((self, loss))
            return None, None
        if grads is None:
            raise ValueError(
                "minimize needs a static-program loss var, or explicit "
                "grads (functional autograd): opt.minimize(grads=...) — "
                "compute them with jax.grad / paddle_tpu.autograd.")
        self.step(grads)
        return None, None

    def state_dict(self) -> Dict:
        out = {"state": self._state}
        if isinstance(self._lr, LRScheduler):
            out["lr_scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, sd: Dict) -> None:
        self._state = sd.get("state")
        if "lr_scheduler" in sd and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(sd["lr_scheduler"])


class SGD(Optimizer):
    def _update(self, name, p, g, slots, lr, step):
        if self._weight_decay and self._decayed(name):
            g = g + self._weight_decay * p
        return p - lr * g


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum: float = 0.9, parameters=None,
                 use_nesterov: bool = False, weight_decay=0.0, grad_clip=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _init_slots(self, p):
        return {"velocity": jnp.zeros(p.shape, jnp.float32)}

    def _update(self, name, p, g, slots, lr, step):
        if self._weight_decay and self._decayed(name):
            g = g + self._weight_decay * p
        v = self.momentum * slots["velocity"] + g
        slots["velocity"] = v
        if self.use_nesterov:
            return p - lr * (g + self.momentum * v)
        return p - lr * v


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, parameters=None, weight_decay=0.0,
                 grad_clip=None, multi_precision=True, lazy_mode: bool = False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {"m": jnp.zeros(p.shape, jnp.float32),
                "v": jnp.zeros(p.shape, jnp.float32)}

    def _l2(self, name, p, g):
        # plain Adam folds weight decay into the gradient (L2 reg)
        if self._weight_decay and self._decayed(name):
            return g + self._weight_decay * p
        return g

    def _decoupled(self):
        return False

    def _update(self, name, p, g, slots, lr, step):
        g = self._l2(name, p, g)
        m = self.beta1 * slots["m"] + (1 - self.beta1) * g
        v = self.beta2 * slots["v"] + (1 - self.beta2) * jnp.square(g)
        slots["m"], slots["v"] = m, v
        t = step.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + self.epsilon)
        if self._decoupled() and self._weight_decay and self._decayed(name):
            upd = upd + self._weight_decay * p
        return p - lr * upd


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py —
    ``param -= lr * (update + wd * param)`` with wd NOT in the moments)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay: float = 0.01, grad_clip=None,
                 multi_precision=True, apply_decay_param_fun=None, lr_ratio=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, multi_precision)
        self.apply_decay_param_fun = apply_decay_param_fun

    def _l2(self, name, p, g):
        return g

    def _decoupled(self):
        return True


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.0, grad_clip=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {"m": jnp.zeros(p.shape, jnp.float32),
                "u": jnp.zeros(p.shape, jnp.float32)}

    def _update(self, name, p, g, slots, lr, step):
        if self._weight_decay and self._decayed(name):
            g = g + self._weight_decay * p
        m = self.beta1 * slots["m"] + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * slots["u"], jnp.abs(g))
        slots["m"], slots["u"] = m, u
        t = step.astype(jnp.float32)
        return p - lr / (1 - self.beta1 ** t) * m / (u + self.epsilon)


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon: float = 1e-6, parameters=None,
                 weight_decay=0.0, grad_clip=None, multi_precision=True,
                 initial_accumulator_value: float = 0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.epsilon = epsilon
        self.init_acc = initial_accumulator_value

    def _init_slots(self, p):
        return {"acc": jnp.full(p.shape, self.init_acc, jnp.float32)}

    def _update(self, name, p, g, slots, lr, step):
        if self._weight_decay and self._decayed(name):
            g = g + self._weight_decay * p
        acc = slots["acc"] + jnp.square(g)
        slots["acc"] = acc
        return p - lr * g / (jnp.sqrt(acc) + self.epsilon)


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho: float = 0.95, epsilon: float = 1e-6,
                 momentum: float = 0.0, centered: bool = False, parameters=None,
                 weight_decay=0.0, grad_clip=None, multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.rho, self.epsilon, self.momentum, self.centered = rho, epsilon, momentum, centered

    def _init_slots(self, p):
        s = {"ms": jnp.zeros(p.shape, jnp.float32),
             "mom": jnp.zeros(p.shape, jnp.float32)}
        if self.centered:
            s["mg"] = jnp.zeros(p.shape, jnp.float32)
        return s

    def _update(self, name, p, g, slots, lr, step):
        if self._weight_decay and self._decayed(name):
            g = g + self._weight_decay * p
        ms = self.rho * slots["ms"] + (1 - self.rho) * jnp.square(g)
        slots["ms"] = ms
        if self.centered:
            mg = self.rho * slots["mg"] + (1 - self.rho) * g
            slots["mg"] = mg
            denom = jnp.sqrt(ms - jnp.square(mg) + self.epsilon)
        else:
            denom = jnp.sqrt(ms + self.epsilon)
        mom = self.momentum * slots["mom"] + lr * g / denom
        slots["mom"] = mom
        return p - mom


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon: float = 1e-6, rho: float = 0.95,
                 parameters=None, weight_decay=0.0, grad_clip=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.epsilon, self.rho = epsilon, rho

    def _init_slots(self, p):
        return {"avg_sq_grad": jnp.zeros(p.shape, jnp.float32),
                "avg_sq_update": jnp.zeros(p.shape, jnp.float32)}

    def _update(self, name, p, g, slots, lr, step):
        if self._weight_decay and self._decayed(name):
            g = g + self._weight_decay * p
        asg = self.rho * slots["avg_sq_grad"] + (1 - self.rho) * jnp.square(g)
        upd = jnp.sqrt(slots["avg_sq_update"] + self.epsilon) / jnp.sqrt(
            asg + self.epsilon) * g
        asu = self.rho * slots["avg_sq_update"] + (1 - self.rho) * jnp.square(upd)
        slots["avg_sq_grad"], slots["avg_sq_update"] = asg, asu
        return p - lr * upd


class Lamb(Optimizer):
    """Reference: python/paddle/optimizer/lamb.py — Adam update rescaled by
    trust ratio ||p|| / ||update||."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay: float = 0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip,
                         multi_precision)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.exclude_fn = exclude_from_weight_decay_fn

    def _init_slots(self, p):
        return {"m": jnp.zeros(p.shape, jnp.float32),
                "v": jnp.zeros(p.shape, jnp.float32)}

    def _update(self, name, p, g, slots, lr, step):
        m = self.beta1 * slots["m"] + (1 - self.beta1) * g
        v = self.beta2 * slots["v"] + (1 - self.beta2) * jnp.square(g)
        slots["m"], slots["v"] = m, v
        t = step.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self.epsilon)
        wd = self._weight_decay
        if self.exclude_fn is not None and self.exclude_fn(name):
            wd = 0.0
        r = r + wd * p
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return p - lr * trust * r


class Rprop(Optimizer):
    """Resilient backprop (reference: python/paddle/optimizer/rprop.py):
    sign-based per-parameter step sizes, grown on agreeing signs and shrunk
    with update rollback on sign flips. Full-batch method like the
    reference documents."""

    def __init__(self, learning_rate: float = 0.001,
                 learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate=learning_rate, parameters=parameters,
                         grad_clip=grad_clip,
                         multi_precision=multi_precision)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas
        self._init_lr = learning_rate

    def _init_slots(self, p):
        import jax.numpy as jnp
        return {"step_size": jnp.full(p.shape, self._init_lr, jnp.float32),
                "prev_grad": jnp.zeros(p.shape, jnp.float32)}

    def _update(self, name, p, g, slots, lr, step):
        import jax.numpy as jnp
        sign = jnp.sign(g * slots["prev_grad"])
        grow = sign > 0
        flip = sign < 0
        size = jnp.clip(
            jnp.where(grow, slots["step_size"] * self._eta_pos,
                      jnp.where(flip, slots["step_size"] * self._eta_neg,
                                slots["step_size"])),
            self._lr_min, self._lr_max)
        # on sign flip: zero this step's grad (skip update, reference rule)
        g_eff = jnp.where(flip, 0.0, g)
        slots["step_size"] = size
        slots["prev_grad"] = jnp.where(flip, 0.0, g)
        return p - jnp.sign(g_eff) * size
