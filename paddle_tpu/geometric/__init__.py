"""paddle_tpu.geometric — graph-NN message passing (reference:
python/paddle/geometric/: message_passing/send_recv.py send_u_recv /
send_ue_recv, math.py segment_sum/mean/max/min, sampling/neighbors.py).

TPU-native: segment ops map to jax.ops.segment_* (XLA scatter-reduce);
gather/scatter message passing is dense-indexable so it jits and shards.
Neighbor sampling is host-side (data-dependent shapes don't belong in jit).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "sample_neighbors"]


def segment_sum(data, segment_ids, num_segments: Optional[int] = None,
                name=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_sum(data, segment_ids, num_segments=n)


def segment_mean(data, segment_ids, num_segments: Optional[int] = None,
                 name=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    s = jax.ops.segment_sum(data, segment_ids, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                              segment_ids, num_segments=n)
    return s / jnp.maximum(cnt, 1.0).reshape((-1,) + (1,) * (data.ndim - 1))


def segment_max(data, segment_ids, num_segments: Optional[int] = None,
                name=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_max(data, segment_ids, num_segments=n)


def segment_min(data, segment_ids, num_segments: Optional[int] = None,
                name=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_min(data, segment_ids, num_segments=n)


_REDUCERS = {"sum": segment_sum, "add": segment_sum, "mean": segment_mean,
             "max": segment_max, "min": segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size: Optional[int] = None, name=None):
    """Gather x at src, reduce onto dst (reference:
    message_passing/send_recv.py send_u_recv)."""
    fn = _REDUCERS.get(reduce_op)
    if fn is None:
        raise ValueError(f"reduce_op must be one of {sorted(_REDUCERS)}")
    msgs = x[src_index]
    return fn(msgs, dst_index, num_segments=out_size or x.shape[0])


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size: Optional[int] = None,
                 name=None):
    """Node⊕edge message then reduce (reference send_ue_recv):
    message = x[src] (+|*|-|/) y[edge]."""
    msgs = x[src_index]
    ops = {"add": jnp.add, "mul": jnp.multiply, "sub": jnp.subtract,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(f"message_op must be one of {sorted(ops)}")
    msgs = ops[message_op](msgs, y)
    fn = _REDUCERS.get(reduce_op)
    if fn is None:
        raise ValueError(f"reduce_op must be one of {sorted(_REDUCERS)}")
    return fn(msgs, dst_index, num_segments=out_size or x.shape[0])


def sample_neighbors(row, colptr, input_nodes, sample_size: int = -1,
                     seed: Optional[int] = None):
    """Uniform neighbor sampling over CSC graph storage (reference:
    geometric/sampling/neighbors.py). Host-side numpy — output shapes are
    data-dependent. Returns (edge_src, edge_dst, sample_index)."""
    rs = np.random.RandomState(seed)
    row = np.asarray(row)
    colptr = np.asarray(colptr)
    srcs, dsts = [], []
    for node in np.asarray(input_nodes):
        beg, end = int(colptr[node]), int(colptr[node + 1])
        neigh = row[beg:end]
        if sample_size >= 0 and len(neigh) > sample_size:
            neigh = rs.choice(neigh, size=sample_size, replace=False)
        srcs.extend(int(v) for v in neigh)
        dsts.extend([int(node)] * len(neigh))
    uniq = np.unique(np.concatenate([np.asarray(input_nodes),
                                     np.asarray(srcs, np.int64)])
                     if srcs else np.asarray(input_nodes))
    return (np.asarray(srcs, np.int64), np.asarray(dsts, np.int64), uniq)
