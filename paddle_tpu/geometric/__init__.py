"""paddle_tpu.geometric — graph-NN message passing (reference:
python/paddle/geometric/: message_passing/send_recv.py send_u_recv /
send_ue_recv, math.py segment_sum/mean/max/min, sampling/neighbors.py).

TPU-native: segment ops map to jax.ops.segment_* (XLA scatter-reduce);
gather/scatter message passing is dense-indexable so it jits and shards.
Neighbor sampling is host-side (data-dependent shapes don't belong in jit).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "sample_neighbors"]


def segment_sum(data, segment_ids, num_segments: Optional[int] = None,
                name=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_sum(data, segment_ids, num_segments=n)


def segment_mean(data, segment_ids, num_segments: Optional[int] = None,
                 name=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    s = jax.ops.segment_sum(data, segment_ids, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                              segment_ids, num_segments=n)
    return s / jnp.maximum(cnt, 1.0).reshape((-1,) + (1,) * (data.ndim - 1))


def segment_max(data, segment_ids, num_segments: Optional[int] = None,
                name=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_max(data, segment_ids, num_segments=n)


def segment_min(data, segment_ids, num_segments: Optional[int] = None,
                name=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_min(data, segment_ids, num_segments=n)


_REDUCERS = {"sum": segment_sum, "add": segment_sum, "mean": segment_mean,
             "max": segment_max, "min": segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size: Optional[int] = None, name=None):
    """Gather x at src, reduce onto dst (reference:
    message_passing/send_recv.py send_u_recv)."""
    fn = _REDUCERS.get(reduce_op)
    if fn is None:
        raise ValueError(f"reduce_op must be one of {sorted(_REDUCERS)}")
    msgs = x[src_index]
    return fn(msgs, dst_index, num_segments=out_size or x.shape[0])


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size: Optional[int] = None,
                 name=None):
    """Node⊕edge message then reduce (reference send_ue_recv):
    message = x[src] (+|*|-|/) y[edge]."""
    msgs = x[src_index]
    ops = {"add": jnp.add, "mul": jnp.multiply, "sub": jnp.subtract,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(f"message_op must be one of {sorted(ops)}")
    y = jnp.asarray(y)
    # reference broadcast rule: y's leading dim is the EDGE axis; a
    # lower-rank y gains trailing dims ([E] edge scalars vs [E, F] msgs)
    while y.ndim < msgs.ndim:
        y = y[..., None]
    msgs = ops[message_op](msgs, y)
    fn = _REDUCERS.get(reduce_op)
    if fn is None:
        raise ValueError(f"reduce_op must be one of {sorted(_REDUCERS)}")
    return fn(msgs, dst_index, num_segments=out_size or x.shape[0])


def sample_neighbors(row, colptr, input_nodes, sample_size: int = -1,
                     seed: Optional[int] = None):
    """Uniform neighbor sampling over CSC graph storage (reference:
    geometric/sampling/neighbors.py). Host-side numpy — output shapes are
    data-dependent. Returns (edge_src, edge_dst, sample_index)."""
    rs = np.random.RandomState(seed)
    row = np.asarray(row)
    colptr = np.asarray(colptr)
    srcs, dsts = [], []
    for node in np.asarray(input_nodes):
        beg, end = int(colptr[node]), int(colptr[node + 1])
        neigh = row[beg:end]
        if sample_size >= 0 and len(neigh) > sample_size:
            neigh = rs.choice(neigh, size=sample_size, replace=False)
        srcs.extend(int(v) for v in neigh)
        dsts.extend([int(node)] * len(neigh))
    uniq = np.unique(np.concatenate([np.asarray(input_nodes),
                                     np.asarray(srcs, np.int64)])
                     if srcs else np.asarray(input_nodes))
    return (np.asarray(srcs, np.int64), np.asarray(dsts, np.int64), uniq)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (reference:
    geometric/reindex.py reindex_graph): x = center nodes, neighbors =
    concatenated neighbor lists, count = per-center neighbor counts.
    Returns (reindexed_src, reindexed_dst, out_nodes). Host-side numpy —
    output size is data-dependent (the reference's CPU path likewise)."""
    x = np.asarray(x)
    neighbors = np.asarray(neighbors)
    count = np.asarray(count)
    # local id order: center nodes first, then first-seen unique neighbors
    seen = {int(v): i for i, v in enumerate(x)}
    out_nodes = list(map(int, x))
    for v in neighbors:
        v = int(v)
        if v not in seen:
            seen[v] = len(out_nodes)
            out_nodes.append(v)
    reindex_src = np.asarray([seen[int(v)] for v in neighbors], np.int64)
    dst = np.repeat(np.arange(len(x), dtype=np.int64), count)
    return reindex_src, dst, np.asarray(out_nodes, np.int64)


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: per-edge-type neighbor/count lists share one
    id space (reference: geometric/reindex.py reindex_heter_graph)."""
    x = np.asarray(x)
    neigh_cat = np.concatenate([np.asarray(n) for n in neighbors])
    count_cat = np.concatenate([np.asarray(c) for c in count])
    seen = {int(v): i for i, v in enumerate(x)}
    out_nodes = list(map(int, x))
    for v in neigh_cat:
        v = int(v)
        if v not in seen:
            seen[v] = len(out_nodes)
            out_nodes.append(v)
    reindex_src = np.asarray([seen[int(v)] for v in neigh_cat], np.int64)
    dsts = []
    for c in count:
        dsts.append(np.repeat(np.arange(len(x), dtype=np.int64),
                              np.asarray(c)))
    dst = np.concatenate(dsts)
    return reindex_src, dst, np.asarray(out_nodes, np.int64)


def send_uv(x, y, src_index, dst_index, message_op: str = "add", name=None):
    """Per-edge message from BOTH endpoints (reference:
    geometric/message_passing/send_recv.py send_uv):
    out[e] = x[src[e]] op y[dst[e]] — no reduction."""
    ops = {"add": jnp.add, "mul": jnp.multiply, "sub": jnp.subtract,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(f"message_op must be one of {sorted(ops)}")
    return ops[message_op](jnp.asarray(x)[jnp.asarray(src_index)],
                           jnp.asarray(y)[jnp.asarray(dst_index)])


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size: int = -1, return_eids: bool = False,
                              seed: Optional[int] = None, name=None):
    """Weight-proportional neighbor sampling without replacement
    (reference: geometric/sampling/neighbors.py weighted_sample_neighbors).
    Host-side numpy like sample_neighbors."""
    rs = np.random.RandomState(seed)
    row = np.asarray(row)
    colptr = np.asarray(colptr)
    w = np.asarray(edge_weight, np.float64)
    srcs, dsts, eids = [], [], []
    for node in np.asarray(input_nodes):
        beg, end = int(colptr[node]), int(colptr[node + 1])
        neigh = row[beg:end]
        ids = np.arange(beg, end)
        if sample_size >= 0 and len(neigh) > sample_size:
            p = w[beg:end]
            p = p / p.sum()
            pick = rs.choice(len(neigh), size=sample_size, replace=False,
                             p=p)
            neigh, ids = neigh[pick], ids[pick]
        srcs.extend(int(v) for v in neigh)
        dsts.extend([int(node)] * len(neigh))
        eids.extend(int(e) for e in ids)
    out = (np.asarray(srcs, np.int64), np.asarray(dsts, np.int64))
    if return_eids:
        return out + (np.asarray(eids, np.int64),)
    return out


__all__ += ["reindex_graph", "reindex_heter_graph", "send_uv",
            "weighted_sample_neighbors"]
