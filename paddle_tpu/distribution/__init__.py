"""paddle_tpu.distribution — probability distributions (reference:
python/paddle/distribution/: Distribution base, Normal/Uniform/Bernoulli/
Categorical/Beta/Dirichlet/Gumbel/Laplace/LogNormal/Multinomial/Exponential,
kl_divergence registry, TransformedDistribution).

TPU-native: sampling is explicit-PRNG (jax.random) — ``sample`` draws a key
from the framework's seeded RNG stream when none is given, keeping the
imperative reference API while staying reproducible under jit when a key is
passed. Math uses jax.scipy; everything is jit/vmap-compatible.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import rng as _rng

__all__ = [
    "Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
    "Beta", "Dirichlet", "Exponential", "Gamma", "Geometric", "Gumbel",
    "Laplace", "LogNormal", "Multinomial", "Poisson", "StudentT",
    "kl_divergence", "register_kl",
]


def _next_key(seed: Optional[jax.Array] = None):
    if seed is not None:
        return seed
    return _rng.next_key()


class Distribution:
    """Base class (reference: distribution/distribution.py)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=(), key=None):
        raise NotImplementedError

    def rsample(self, shape=(), key=None):
        """Reparameterized sample; default falls back to sample where the
        pathwise gradient exists naturally (location-scale families)."""
        return self.sample(shape, key=key)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _extend(self, shape):
        return tuple(shape) + self._batch_shape + self._event_shape


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, dtype=jnp.result_type(float))
        self.scale = jnp.asarray(scale, dtype=jnp.result_type(float))
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return jnp.broadcast_to(self.loc, self.batch_shape)

    @property
    def variance(self):
        return jnp.broadcast_to(self.scale ** 2, self.batch_shape)

    @property
    def stddev(self):
        return jnp.broadcast_to(self.scale, self.batch_shape)

    def sample(self, shape=(), key=None):
        eps = jax.random.normal(_next_key(key), self._extend(shape))
        return self.loc + self.scale * eps

    rsample = sample

    def log_prob(self, value):
        var = self.scale ** 2
        return (-((value - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape)

    def cdf(self, value):
        return 0.5 * (1 + jax.scipy.special.erf(
            (value - self.loc) / (self.scale * math.sqrt(2))))

    def icdf(self, q):
        return self.loc + self.scale * math.sqrt(2) * jax.scipy.special.erfinv(
            2 * q - 1)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = jnp.asarray(low, dtype=jnp.result_type(float))
        self.high = jnp.asarray(high, dtype=jnp.result_type(float))
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self):
        return (self.low + self.high) / 2

    @property
    def variance(self):
        return (self.high - self.low) ** 2 / 12

    def sample(self, shape=(), key=None):
        u = jax.random.uniform(_next_key(key), self._extend(shape))
        return self.low + (self.high - self.low) * u

    rsample = sample

    def log_prob(self, value):
        inside = (value >= self.low) & (value < self.high)
        return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

    def entropy(self):
        return jnp.broadcast_to(jnp.log(self.high - self.low), self.batch_shape)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = jnp.asarray(probs, dtype=jnp.result_type(float))
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = jnp.asarray(logits, dtype=jnp.result_type(float))
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1 - self.probs)

    def sample(self, shape=(), key=None):
        return jax.random.bernoulli(_next_key(key), self.probs,
                                    self._extend(shape)).astype(jnp.float32)

    def log_prob(self, value):
        v = jnp.asarray(value)
        return v * jax.nn.log_sigmoid(self.logits) + \
            (1 - v) * jax.nn.log_sigmoid(-self.logits)

    def entropy(self):
        p = self.probs
        return -(p * jnp.log(jnp.clip(p, 1e-12)) +
                 (1 - p) * jnp.log(jnp.clip(1 - p, 1e-12)))


    def cdf(self, value):
        v = jnp.asarray(value)
        return jnp.where(v < 0, 0.0,
                         jnp.where(v < 1, 1.0 - self.probs, 1.0))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if logits is not None:
            self.logits = jnp.asarray(logits, dtype=jnp.result_type(float))
        else:
            self.logits = jnp.log(jnp.clip(
                jnp.asarray(probs, dtype=jnp.result_type(float)), 1e-38))
        self._log_norm = jax.nn.log_softmax(self.logits, axis=-1)
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return jnp.exp(self._log_norm)

    @property
    def mean(self):
        raise NotImplementedError("Categorical has no scalar mean")

    def sample(self, shape=(), key=None):
        return jax.random.categorical(_next_key(key), self.logits,
                                      shape=tuple(shape) + self.batch_shape)

    def log_prob(self, value):
        value = jnp.asarray(value, dtype=jnp.int32)
        return jnp.take_along_axis(self._log_norm, value[..., None],
                                   axis=-1).squeeze(-1)

    def entropy(self):
        p = jnp.exp(self._log_norm)
        return -jnp.sum(p * self._log_norm, axis=-1)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = jnp.asarray(alpha, dtype=jnp.result_type(float))
        self.beta = jnp.asarray(beta, dtype=jnp.result_type(float))
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s ** 2 * (s + 1))

    def sample(self, shape=(), key=None):
        return jax.random.beta(_next_key(key), self.alpha, self.beta,
                               self._extend(shape))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        v = jnp.asarray(value)
        return ((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v)
                - betaln(self.alpha, self.beta))

    def entropy(self):
        from jax.scipy.special import betaln, digamma
        a, b = self.alpha, self.beta
        return (betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = jnp.asarray(concentration,
                                         dtype=jnp.result_type(float))
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return self.concentration / self.concentration.sum(-1, keepdims=True)

    @property
    def variance(self):
        a0 = self.concentration.sum(-1, keepdims=True)
        m = self.concentration / a0
        return m * (1 - m) / (a0 + 1)

    def sample(self, shape=(), key=None):
        return jax.random.dirichlet(_next_key(key), self.concentration,
                                    tuple(shape) + self.batch_shape)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        a = self.concentration
        return (jnp.sum((a - 1) * jnp.log(value), -1)
                + gammaln(a.sum(-1)) - jnp.sum(gammaln(a), -1))

    def entropy(self):
        from jax.scipy.special import gammaln, digamma
        a = self.concentration
        a0 = a.sum(-1)
        k = a.shape[-1]
        return (jnp.sum(gammaln(a), -1) - gammaln(a0)
                + (a0 - k) * digamma(a0) - jnp.sum((a - 1) * digamma(a), -1))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = jnp.asarray(rate, dtype=jnp.result_type(float))
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return 1.0 / self.rate

    @property
    def variance(self):
        return 1.0 / self.rate ** 2

    def sample(self, shape=(), key=None):
        return jax.random.exponential(_next_key(key),
                                      self._extend(shape)) / self.rate

    rsample = sample

    def log_prob(self, value):
        return jnp.log(self.rate) - self.rate * value

    def entropy(self):
        return jnp.broadcast_to(1.0 - jnp.log(self.rate), self.batch_shape)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = jnp.asarray(concentration,
                                         dtype=jnp.result_type(float))
        self.rate = jnp.asarray(rate, dtype=jnp.result_type(float))
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return self.concentration / self.rate

    @property
    def variance(self):
        return self.concentration / self.rate ** 2

    def sample(self, shape=(), key=None):
        return jax.random.gamma(_next_key(key), self.concentration,
                                self._extend(shape)) / self.rate

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        a, r = self.concentration, self.rate
        return (a * jnp.log(r) + (a - 1) * jnp.log(value) - r * value
                - gammaln(a))

    def entropy(self):
        from jax.scipy.special import gammaln, digamma
        a = self.concentration
        return (a - jnp.log(self.rate) + gammaln(a) + (1 - a) * digamma(a))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k in {0,1,...} (reference: distribution/geometric.py)."""

    def __init__(self, probs, name=None):
        self.probs = jnp.asarray(probs, dtype=jnp.result_type(float))
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return (1 - self.probs) / self.probs

    @property
    def variance(self):
        return (1 - self.probs) / self.probs ** 2

    def sample(self, shape=(), key=None):
        u = jax.random.uniform(_next_key(key), self._extend(shape),
                               minval=1e-12)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs))

    def log_prob(self, value):
        return value * jnp.log1p(-self.probs) + jnp.log(self.probs)

    def entropy(self):
        p = self.probs
        q = 1 - p
        return -(q * jnp.log(jnp.clip(q, 1e-12)) +
                 p * jnp.log(jnp.clip(p, 1e-12))) / p


    def pmf(self, k):
        return jnp.exp(self.log_pmf(k))

    def log_pmf(self, k):
        k = jnp.asarray(k)
        return k * jnp.log1p(-self.probs) + jnp.log(self.probs)

    def cdf(self, k):
        k = jnp.asarray(k)
        return 1.0 - jnp.power(1.0 - self.probs, k + 1.0)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, dtype=jnp.result_type(float))
        self.scale = jnp.asarray(scale, dtype=jnp.result_type(float))
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return self.loc + self.scale * 0.5772156649015329

    @property
    def variance(self):
        return (math.pi ** 2 / 6) * self.scale ** 2

    def sample(self, shape=(), key=None):
        return self.loc + self.scale * jax.random.gumbel(
            _next_key(key), self._extend(shape))

    rsample = sample

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def entropy(self):
        return jnp.broadcast_to(jnp.log(self.scale) + 1.5772156649015329,
                                self.batch_shape)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, dtype=jnp.result_type(float))
        self.scale = jnp.asarray(scale, dtype=jnp.result_type(float))
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return jnp.broadcast_to(self.loc, self.batch_shape)

    @property
    def variance(self):
        return 2 * self.scale ** 2

    def sample(self, shape=(), key=None):
        return self.loc + self.scale * jax.random.laplace(
            _next_key(key), self._extend(shape))

    rsample = sample

    def log_prob(self, value):
        return -jnp.abs(value - self.loc) / self.scale - jnp.log(2 * self.scale)

    def entropy(self):
        return jnp.broadcast_to(1 + jnp.log(2 * self.scale), self.batch_shape)

    def cdf(self, value):
        z = (value - self.loc) / self.scale
        return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))

    def icdf(self, q):
        # reference distribution/laplace.py icdf:
        # loc - scale * sign(q - 0.5) * log1p(-2|q - 0.5|)
        a = q - 0.5
        return self.loc - self.scale * jnp.sign(a) * jnp.log1p(
            -2 * jnp.abs(a))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, dtype=jnp.result_type(float))
        self.scale = jnp.asarray(scale, dtype=jnp.result_type(float))
        self._normal = Normal(self.loc, self.scale)
        super().__init__(self._normal.batch_shape)

    @property
    def mean(self):
        return jnp.exp(self.loc + self.scale ** 2 / 2)

    @property
    def variance(self):
        return (jnp.exp(self.scale ** 2) - 1) * jnp.exp(
            2 * self.loc + self.scale ** 2)

    def sample(self, shape=(), key=None):
        return jnp.exp(self._normal.sample(shape, key=key))

    rsample = sample

    def log_prob(self, value):
        return self._normal.log_prob(jnp.log(value)) - jnp.log(value)

    def entropy(self):
        return self._normal.entropy() + self.loc


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = jnp.asarray(probs, dtype=jnp.result_type(float))
        self.probs = self.probs / self.probs.sum(-1, keepdims=True)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1 - self.probs)

    def sample(self, shape=(), key=None):
        k = self.probs.shape[-1]
        draws = jax.random.categorical(
            _next_key(key), jnp.log(jnp.clip(self.probs, 1e-38)),
            shape=(self.total_count,) + tuple(shape) + self.batch_shape)
        return jax.nn.one_hot(draws, k).sum(0)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = jnp.asarray(value)
        return (gammaln(self.total_count + 1.0) - jnp.sum(gammaln(v + 1.0), -1)
                + jnp.sum(v * jnp.log(jnp.clip(self.probs, 1e-38)), -1))


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = jnp.asarray(rate, dtype=jnp.result_type(float))
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=(), key=None):
        return jax.random.poisson(_next_key(key), self.rate,
                                  self._extend(shape)).astype(jnp.float32)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        return value * jnp.log(self.rate) - self.rate - gammaln(value + 1.0)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = jnp.asarray(df, dtype=jnp.result_type(float))
        self.loc = jnp.asarray(loc, dtype=jnp.result_type(float))
        self.scale = jnp.asarray(scale, dtype=jnp.result_type(float))
        super().__init__(jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return jnp.where(self.df > 1, self.loc, jnp.nan)

    @property
    def variance(self):
        return jnp.where(self.df > 2, self.scale ** 2 * self.df / (self.df - 2),
                         jnp.nan)

    def sample(self, shape=(), key=None):
        return self.loc + self.scale * jax.random.t(
            _next_key(key), self.df, self._extend(shape))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        d = self.df
        z = (value - self.loc) / self.scale
        return (gammaln((d + 1) / 2) - gammaln(d / 2)
                - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                - (d + 1) / 2 * jnp.log1p(z ** 2 / d))


# ---------------------------------------------------------------------------
# KL registry (reference: python/paddle/distribution/kl.py register_kl)
# ---------------------------------------------------------------------------

_KL_REGISTRY: dict = {}


def register_kl(p_cls, q_cls):
    def decorator(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return decorator


def kl_divergence(p: Distribution, q: Distribution):
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    return jnp.log((q.high - q.low) / (p.high - p.low))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    a = p.probs * (jnp.log(jnp.clip(p.probs, 1e-12)) -
                   jnp.log(jnp.clip(q.probs, 1e-12)))
    b = (1 - p.probs) * (jnp.log(jnp.clip(1 - p.probs, 1e-12)) -
                         jnp.log(jnp.clip(1 - q.probs, 1e-12)))
    return a + b


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    pp = jnp.exp(p._log_norm)
    return jnp.sum(pp * (p._log_norm - q._log_norm), -1)


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    ratio = q.rate / p.rate
    return jnp.log(p.rate / q.rate) + ratio - 1


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    scale_ratio = p.scale / q.scale
    loc_abs = jnp.abs(p.loc - q.loc) / q.scale
    return (-jnp.log(scale_ratio) + scale_ratio *
            jnp.exp(-loc_abs / scale_ratio) + loc_abs - 1)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    from jax.scipy.special import gammaln, digamma
    a, b = p.concentration, q.concentration
    a0 = a.sum(-1, keepdims=True)
    return (gammaln(a0.squeeze(-1)) - jnp.sum(gammaln(a), -1)
            - gammaln(b.sum(-1)) + jnp.sum(gammaln(b), -1)
            + jnp.sum((a - b) * (digamma(a) - digamma(a0)), -1))


# ---------------------------------------------------------------------------
# round-3 parity batch (reference: python/paddle/distribution/{binomial.py,
# cauchy.py,continuous_bernoulli.py,exponential_family.py,independent.py,
# multivariate_normal.py,transformed_distribution.py,transform.py})
# ---------------------------------------------------------------------------

class ExponentialFamily(Distribution):
    """Base for natural-parameter families (reference:
    distribution/exponential_family.py): entropy via the Bregman identity
    when _log_normalizer is differentiable."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        nat = [jnp.asarray(p) for p in self._natural_parameters]
        lg, grads = jax.value_and_grad(
            lambda ps: jnp.sum(self._log_normalizer(*ps)))(tuple(nat))
        ent = lg - sum(jnp.sum(n * g) for n, g in zip(nat, grads))
        return ent + self._mean_carrier_measure


class Binomial(Distribution):
    """reference: distribution/binomial.py Binomial(total_count, probs)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = jnp.asarray(total_count)
        self.probs = jnp.asarray(probs)
        super().__init__(batch_shape=jnp.broadcast_shapes(
            self.total_count.shape, self.probs.shape))

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1 - self.probs)

    def sample(self, shape=(), key=None):
        n = jnp.broadcast_to(self.total_count, self._extend(shape))
        p = jnp.broadcast_to(self.probs, self._extend(shape))
        return jax.random.binomial(_next_key(key), n.astype(jnp.float32),
                                   p).astype(jnp.int64)

    def log_prob(self, value):
        v = jnp.asarray(value, jnp.float32)
        n = self.total_count.astype(jnp.float32)
        logc = (jax.scipy.special.gammaln(n + 1)
                - jax.scipy.special.gammaln(v + 1)
                - jax.scipy.special.gammaln(n - v + 1))
        eps = 1e-12
        return (logc + v * jnp.log(self.probs + eps)
                + (n - v) * jnp.log1p(-self.probs + eps))

    def entropy(self):
        # sum over the support (reference computes the full enumeration)
        n_max = int(np.max(np.asarray(self.total_count)))
        k = jnp.arange(n_max + 1, dtype=jnp.float32)
        shape = (n_max + 1,) + (1,) * len(self._batch_shape)
        lp = self.log_prob(k.reshape(shape))
        mask = k.reshape(shape) <= self.total_count
        return -jnp.sum(jnp.where(mask, jnp.exp(lp) * lp, 0.0), axis=0)


class Cauchy(Distribution):
    """reference: distribution/cauchy.py Cauchy(loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc)
        self.scale = jnp.asarray(scale)
        super().__init__(batch_shape=jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape))

    def sample(self, shape=(), key=None):
        z = jax.random.cauchy(_next_key(key), self._extend(shape))
        return self.loc + self.scale * z

    rsample = sample

    def log_prob(self, value):
        z = (jnp.asarray(value) - self.loc) / self.scale
        return (-jnp.log(jnp.pi) - jnp.log(self.scale)
                - jnp.log1p(jnp.square(z)))

    def cdf(self, value):
        z = (jnp.asarray(value) - self.loc) / self.scale
        return jnp.arctan(z) / jnp.pi + 0.5

    def entropy(self):
        return jnp.broadcast_to(jnp.log(4 * jnp.pi * self.scale),
                                self._batch_shape)


class ContinuousBernoulli(Distribution):
    """reference: distribution/continuous_bernoulli.py — density
    C(p) p^x (1-p)^(1-x) on [0, 1]."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = jnp.asarray(probs)
        self._lims = lims
        super().__init__(batch_shape=self.probs.shape)

    def _outside_unstable(self):
        lo, hi = self._lims
        return (self.probs < lo) | (self.probs > hi)

    def _log_norm_const(self):
        # C(p) = 2 atanh(1-2p) / (1-2p) for p != 0.5, else 2
        p = self.probs
        safe = jnp.where(self._outside_unstable(), p, 0.4)
        x = 1.0 - 2.0 * safe
        taylor = jnp.log(2.0) + (4.0 / 3.0 + 104.0 / 45.0
                                 * jnp.square(p - 0.5)) * jnp.square(p - 0.5)
        exact = jnp.log(2.0 * jnp.arctanh(x) / x)
        return jnp.where(self._outside_unstable(), exact, taylor)

    @property
    def mean(self):
        p = self.probs
        safe = jnp.where(self._outside_unstable(), p, 0.4)
        exact = safe / (2.0 * safe - 1.0) \
            + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * safe))
        taylor = 0.5 + (p - 0.5) / 3.0
        return jnp.where(self._outside_unstable(), exact, taylor)

    def log_prob(self, value):
        v = jnp.asarray(value)
        eps = 1e-12
        return (self._log_norm_const() + v * jnp.log(self.probs + eps)
                + (1 - v) * jnp.log1p(-self.probs + eps))

    def sample(self, shape=(), key=None):
        # inverse-CDF of the continuous Bernoulli
        u = jax.random.uniform(_next_key(key), self._extend(shape))
        p = self.probs
        safe = jnp.where(self._outside_unstable(), p, 0.4)
        num = (jnp.log1p(u * (2.0 * safe - 1.0) / (1.0 - safe))
               )
        den = jnp.log(safe) - jnp.log1p(-safe)
        icdf = num / den
        return jnp.where(self._outside_unstable(),
                         jnp.clip(icdf, 0.0, 1.0), u)

    rsample = sample

    def entropy(self):
        # -E[log p(X)] with E[X] = self.mean (log p is linear in x)
        return -(self._log_norm_const()
                 + self.mean * jnp.log(self.probs + 1e-12)
                 + (1 - self.mean) * jnp.log1p(-self.probs + 1e-12))


class Independent(Distribution):
    """Reinterpret batch dims as event dims (reference:
    distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank: int):
        self.base = base
        self._rank = reinterpreted_batch_rank
        bs = base.batch_shape
        super().__init__(batch_shape=bs[:len(bs) - self._rank],
                         event_shape=bs[len(bs) - self._rank:]
                         + tuple(base.event_shape))

    def sample(self, shape=(), key=None):
        return self.base.sample(shape, key=key)

    def rsample(self, shape=(), key=None):
        return self.base.rsample(shape, key=key)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return jnp.sum(lp, axis=tuple(range(-self._rank, 0)))

    def entropy(self):
        ent = self.base.entropy()
        return jnp.sum(ent, axis=tuple(range(-self._rank, 0)))


class MultivariateNormal(Distribution):
    """reference: distribution/multivariate_normal.py — parameterized by
    loc + one of covariance/precision/scale_tril; Cholesky-based sampling
    and log_prob (MXU-friendly triangular solves)."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = jnp.asarray(loc)
        if scale_tril is not None:
            self._chol = jnp.asarray(scale_tril)
        elif covariance_matrix is not None:
            self._chol = jnp.linalg.cholesky(jnp.asarray(covariance_matrix))
        elif precision_matrix is not None:
            prec = jnp.asarray(precision_matrix)
            self._chol = jnp.linalg.cholesky(jnp.linalg.inv(prec))
        else:
            raise ValueError("provide covariance_matrix, precision_matrix "
                             "or scale_tril")
        d = self.loc.shape[-1]
        super().__init__(batch_shape=jnp.broadcast_shapes(
            self.loc.shape[:-1], self._chol.shape[:-2]),
            event_shape=(d,))

    @property
    def covariance_matrix(self):
        return self._chol @ jnp.swapaxes(self._chol, -1, -2)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return jnp.sum(jnp.square(self._chol), axis=-1)

    def sample(self, shape=(), key=None):
        z = jax.random.normal(_next_key(key), self._extend(shape))
        return self.loc + jnp.einsum("...ij,...j->...i", self._chol, z)

    rsample = sample

    def log_prob(self, value):
        diff = jnp.asarray(value) - self.loc
        y = jax.scipy.linalg.solve_triangular(self._chol, diff[..., None],
                                              lower=True)[..., 0]
        d = self.loc.shape[-1]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(self._chol, axis1=-2,
                                                   axis2=-1)), axis=-1)
        return (-0.5 * jnp.sum(jnp.square(y), axis=-1)
                - half_logdet - 0.5 * d * jnp.log(2 * jnp.pi))

    def entropy(self):
        d = self.loc.shape[-1]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(self._chol, axis1=-2,
                                                   axis2=-1)), axis=-1)
        return 0.5 * d * (1 + jnp.log(2 * jnp.pi)) + half_logdet


class TransformedDistribution(Distribution):
    """Push a base distribution through invertible transforms (reference:
    distribution/transformed_distribution.py). ``transforms`` expose
    forward / inverse / forward_log_det_jacobian like the reference's
    Transform API."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(batch_shape=base.batch_shape,
                         event_shape=base.event_shape)

    def sample(self, shape=(), key=None):
        x = self.base.sample(shape, key=key)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=(), key=None):
        x = self.base.rsample(shape, key=key)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = jnp.asarray(value)
        lp = 0.0
        for t in reversed(self.transforms):
            x = t.inverse(y)
            lp = lp - t.forward_log_det_jacobian(x)
            y = x
        return lp + self.base.log_prob(y)


class Transform:
    """Invertible map base (reference: distribution/transform.py)."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    # shape maps are identity for elementwise transforms; shape-changing
    # transforms (Reshape) override (reference transform.py forward_shape)
    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc)
        self.scale = jnp.asarray(scale)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class ExpTransform(Transform):
    def forward(self, x):
        return jnp.exp(x)

    def inverse(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian(self, x):
        return jnp.asarray(x)


class SigmoidTransform(Transform):
    def forward(self, x):
        return jax.nn.sigmoid(x)

    def inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class AbsTransform(Transform):
    """y = |x| (reference transform.py AbsTransform — not bijective; the
    inverse returns the positive branch like the reference)."""

    def forward(self, x):
        return jnp.abs(x)

    def inverse(self, y):
        return y

    def forward_log_det_jacobian(self, x):
        return jnp.zeros_like(jnp.asarray(x, jnp.result_type(float)))


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = jnp.asarray(power)

    def forward(self, x):
        return jnp.power(x, self.power)

    def inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            j = t.forward_log_det_jacobian(x)
            total = j if total is None else total + j
            x = t.forward(x)
        return total


class IndependentTransform(Transform):
    """Sums the log-det over the trailing reinterpreted dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        j = self.base.forward_log_det_jacobian(x)
        return jnp.sum(j, axis=tuple(range(-self.rank, 0)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def forward(self, x):
        lead = jnp.shape(x)[:len(jnp.shape(x)) - len(self.in_event_shape)]
        return jnp.reshape(x, lead + self.out_event_shape)

    def inverse(self, y):
        lead = jnp.shape(y)[:len(jnp.shape(y)) - len(self.out_event_shape)]
        return jnp.reshape(y, lead + self.in_event_shape)

    def forward_log_det_jacobian(self, x):
        lead = jnp.shape(x)[:len(jnp.shape(x)) - len(self.in_event_shape)]
        return jnp.zeros(lead)

    def forward_shape(self, shape):
        shape = tuple(shape)
        n = len(self.in_event_shape)
        if shape[len(shape) - n:] != self.in_event_shape:
            raise ValueError(f"trailing dims of {shape} do not match "
                             f"in_event_shape {self.in_event_shape}")
        return shape[:len(shape) - n] + self.out_event_shape

    def inverse_shape(self, shape):
        shape = tuple(shape)
        n = len(self.out_event_shape)
        if shape[len(shape) - n:] != self.out_event_shape:
            raise ValueError(f"trailing dims of {shape} do not match "
                             f"out_event_shape {self.out_event_shape}")
        return shape[:len(shape) - n] + self.in_event_shape


class SoftmaxTransform(Transform):
    """x -> softmax(x) (reference: not bijective; inverse is log)."""

    def forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def inverse(self, y):
        return jnp.log(y)


class StackTransform(Transform):
    """Applies transforms[i] along slices of ``axis``."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, fns, v):
        parts = [fns[i](jnp.take(v, i, axis=self.axis))
                 for i in range(len(self.transforms))]
        return jnp.stack(parts, axis=self.axis)

    def forward(self, x):
        return self._map([t.forward for t in self.transforms], x)

    def inverse(self, y):
        return self._map([t.inverse for t in self.transforms], y)

    def forward_log_det_jacobian(self, x):
        return self._map([t.forward_log_det_jacobian
                          for t in self.transforms], x)


class StickBreakingTransform(Transform):
    """R^K -> K+1 simplex (reference transform.py StickBreakingTransform)."""

    def forward(self, x):
        offset = jnp.arange(x.shape[-1], 0, -1)
        z = jax.nn.sigmoid(x - jnp.log(offset.astype(x.dtype)))
        zpad = jnp.concatenate([z, jnp.ones(x.shape[:-1] + (1,), x.dtype)],
                               axis=-1)
        onepad = jnp.concatenate([jnp.ones(x.shape[:-1] + (1,), x.dtype),
                                  jnp.cumprod(1 - z, axis=-1)], axis=-1)
        return zpad * onepad

    def inverse(self, y):
        y_crop = y[..., :-1]
        rest = 1 - jnp.cumsum(y_crop, axis=-1)
        offset = jnp.arange(y_crop.shape[-1], 0, -1)
        z = y_crop / jnp.concatenate(
            [jnp.ones(y_crop.shape[:-1] + (1,), y.dtype), rest[..., :-1]],
            axis=-1)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(
            offset.astype(y.dtype))


class TanhTransform(Transform):
    def forward(self, x):
        return jnp.tanh(x)

    def inverse(self, y):
        return jnp.arctanh(y)

    def forward_log_det_jacobian(self, x):
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


import numpy as np  # noqa: E402 (Binomial.entropy host-side support bound)

__all__ += ["AbsTransform", "PowerTransform", "ChainTransform",
            "IndependentTransform", "ReshapeTransform", "SoftmaxTransform",
            "StackTransform", "StickBreakingTransform", "TanhTransform"]

__all__ += ["ExponentialFamily", "Binomial", "Cauchy",
            "ContinuousBernoulli", "Independent", "MultivariateNormal",
            "TransformedDistribution", "Transform", "AffineTransform",
            "ExpTransform", "SigmoidTransform"]

from ..utils import register_submodule_aliases as _rsa
import sys as _sys
_self = _sys.modules[__name__]
_rsa(__name__, {n: _self for n in (
    "normal", "uniform", "beta", "bernoulli", "categorical", "cauchy",
    "dirichlet", "exponential", "gamma", "geometric", "gumbel", "laplace",
    "lognormal", "multinomial", "poisson", "binomial", "transform", "kl",
    "distribution", "transformed_distribution", "independent",
    "variable", "constraint")})


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    # the REFERENCE formula (distribution/kl.py _kl_geometric_geometric):
    # p*log(p/q) + (1-p)*log((1-p)/(1-q)) — matched for doctest parity
    return (p.probs * (jnp.log(p.probs) - jnp.log(q.probs))
            + (1 - p.probs) * (jnp.log1p(-p.probs)
                               - jnp.log1p(-q.probs)))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    # standard Dirichlet-family closed form (reference kl.py
    # _kl_beta_beta): lnB(a2,b2) - lnB(a1,b1) + (a1-a2)ψ(a1) +
    # (b1-b2)ψ(b1) + (a2-a1+b2-b1)ψ(a1+b1)
    from jax.scipy.special import betaln, digamma
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    return (betaln(a2, b2) - betaln(a1, b1)
            + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
            + (a2 - a1 + b2 - b1) * digamma(a1 + b1))


@register_kl(Cauchy, Cauchy)
def _kl_cauchy(p, q):
    # closed form (Chyzak & Nielsen 2019; the reference cites the same in
    # distribution/cauchy.py kl_divergence):
    # log[ ((γp+γq)² + (xp−xq)²) / (4 γp γq) ]
    return jnp.log(((p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2)
                   / (4.0 * p.scale * q.scale))
