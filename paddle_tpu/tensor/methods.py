"""Paddle Tensor METHOD surface on jax arrays.

Reference: python/paddle/tensor/__init__.py installs several hundred
methods onto the Tensor class (monkey_patch_tensor / tensor_method_func).
Here the runtime array type is jax's ArrayImpl; this module installs the
paddle method spellings DIRECTLY on that type at import, so reference
code written against Tensor methods (``x.numpy()``, ``x.cast('float32')``,
``x.unsqueeze(0)``, ``x.add(y)``, doctest idioms throughout the reference)
runs verbatim.

Rules, in order of importance:
- NEVER shadow an attribute jax already defines (numpy-style .reshape,
  .astype, .sum, ... keep jax semantics); install only missing names.
- methods delegate to the SAME functions the namespace exposes
  (paddle_tpu.tensor / jnp), so method and function forms cannot diverge.
- tape-era mutators raise the documented migration error
  (``backward``; see autograd/__init__.py) instead of silently no-opping;
  ``stop_gradient`` is an accepted-but-inert property (functional
  autograd takes grads explicitly, there is no tape to stop).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def _migration_error(self, *a, **k):
    raise RuntimeError(
        "Tensor.backward() needs an eager autograd tape, which this "
        "framework does not keep (functional autograd). Migrate:\n"
        "    loss, grads = paddle.autograd.layer_grad(layer, loss_fn, x)\n"
        "or  grads = jax.grad(loss_fn)(params)\n"
        "then optimizer.step(grads). See autograd/__init__.py.")


def _methods():
    import paddle_tpu.tensor as T          # fully loaded before install()
    from ..core.dtype import convert_dtype

    def cast(self, dtype):
        return self.astype(convert_dtype(dtype))

    def numpy(self):
        return np.asarray(self)

    def detach(self):
        return jax.lax.stop_gradient(self)

    def unsqueeze(self, axis):
        return T.unsqueeze(self, axis)

    def t(self):
        if self.ndim > 2:
            raise ValueError(f"t() expects <=2 dims, got {self.ndim}")
        return self if self.ndim < 2 else jnp.swapaxes(self, 0, 1)

    def dim(self):
        return self.ndim

    def numel(self):
        return jnp.asarray(self.size)

    def add(self, y):                 # paddle method spellings of binary
        return jnp.add(self, y)       # ops (x.add(y) etc.)

    def subtract(self, y):
        return jnp.subtract(self, y)

    def multiply(self, y):
        return jnp.multiply(self, y)

    def divide(self, y):
        return jnp.divide(self, y)

    def matmul(self, y, transpose_x=False, transpose_y=False):
        from ..linalg import matmul as _mm
        return _mm(self, y, transpose_x, transpose_y)

    def pow(self, y):
        return jnp.power(self, y)

    def exp(self):
        return jnp.exp(self)

    def log(self):
        return jnp.log(self)

    def sqrt(self):
        return jnp.sqrt(self)

    def rsqrt(self):
        return jax.lax.rsqrt(self)

    def tanh(self):
        return jnp.tanh(self)

    def sigmoid(self):
        return jax.nn.sigmoid(self)

    def abs(self):
        return jnp.abs(self)

    def floor(self):
        return jnp.floor(self)

    def ceil(self):
        return jnp.ceil(self)

    def cpu(self):
        return jax.device_put(self, jax.devices("cpu")[0]) \
            if jax.default_backend() != "cpu" else self

    def cuda(self, *a, **k):          # "to accelerator": already there
        return self

    def pin_memory(self):
        return self

    def clone(self):
        return jnp.array(self, copy=True)

    def norm(self, p=2, axis=None, keepdim=False):
        return T.norm(self, p=p, axis=axis, keepdim=keepdim)

    def scale(self, scale=1.0, bias=0.0, bias_after_scale=True):
        return T.scale(self, scale=scale, bias=bias,
                       bias_after_scale=bias_after_scale)

    def equal_all(self, y):
        return T.equal_all(self, y)

    def allclose(self, y, rtol=1e-05, atol=1e-08, equal_nan=False):
        return T.allclose(self, y, rtol=rtol, atol=atol,
                          equal_nan=equal_nan)

    # second batch: structural/selection methods, thin delegations to the
    # namespace functions (paddle code uses the method spellings heavily)
    def topk(self, k, axis=-1, largest=True, sorted=True):
        return T.topk(self, k, axis=axis, largest=largest, sorted=sorted)

    def tile(self, repeat_times):
        return T.tile(self, repeat_times)

    def expand(self, shape):
        return T.expand(self, shape)

    def gather(self, index, axis=0):
        return T.gather(self, index, axis=axis)

    def index_select(self, index, axis=0):
        return T.index_select(self, index, axis=axis)

    def masked_fill(self, mask, value):
        return T.masked_fill(self, mask, value)

    def flip(self, axis):
        return T.flip(self, axis)

    def roll(self, shifts, axis=None):
        return T.roll(self, shifts, axis=axis)

    def split(self, num_or_sections, axis=0):
        return T.split(self, num_or_sections, axis=axis)

    def chunk(self, chunks, axis=0):
        return T.chunk(self, chunks, axis=axis)

    def bmm(self, y):
        return T.bmm(self, y)

    def unbind(self, axis=0):
        return T.unbind(self, axis=axis)

    def to_sparse_coo(self, sparse_dim=None):
        import paddle_tpu.sparse as _sp
        return _sp.to_sparse_coo(self, sparse_dim=sparse_dim)

    def to_sparse_csr(self):
        import paddle_tpu.sparse as _sp
        return _sp.to_sparse_csr(self)

    def to_dense(self):
        return self                      # already dense

    def fill_(self, value):
        # value-semantics alias of the inplace fill (tensor/inplace.py
        # convention: compute and return)
        return jnp.full_like(self, value)

    def zero_(self):
        return jnp.zeros_like(self)

    def set_value(self, value):
        return jnp.asarray(value, self.dtype).reshape(self.shape)

    def fill_diagonal_tensor(self, y, offset=0, dim1=0, dim2=1):
        return T.diagonal_scatter(self, y, offset=offset, axis1=dim1,
                                  axis2=dim2)

    fill_diagonal_tensor_ = fill_diagonal_tensor

    def nanmedian(self, axis=None, keepdim=False):
        return jnp.nanmedian(self, axis=axis, keepdims=keepdim)

    def diagonal_scatter(self, y, offset=0, axis1=0, axis2=1):
        return T.diagonal_scatter(self, y, offset=offset, axis1=axis1,
                                  axis2=axis2)

    def fill_diagonal_(self, value, offset=0, wrap=False):
        # value-semantics alias of the inplace spelling (tensor/inplace.py
        # convention: compute and return)
        return T.fill_diagonal(self, value, offset=offset, wrap=wrap)

    def softmax(self, axis=-1):
        return jax.nn.softmax(self, axis=axis)

    def log_softmax(self, axis=-1):
        return jax.nn.log_softmax(self, axis=axis)

    # harvest ONLY the methods defined in this scope — imported helpers
    # and future locals must never leak onto the array types
    out = {k: v for k, v in locals().items()
           if getattr(v, "__qualname__", "").startswith("_methods.")}
    out["backward"] = _migration_error
    return out


_WARNED: dict = {}


def install():
    """Install missing method names on the runtime array type AND the
    tracer base (so methods work inside jit/grad traces too). Idempotent;
    existing jax attributes are never overridden.

    MUST NOT trigger backend init (no computations!): multi-host workers
    import paddle_tpu BEFORE jax.distributed.initialize, and any array
    creation here would pin a single-process backend."""
    try:
        from jax._src.array import ArrayImpl as _ArrayImpl
    except ImportError:  # pragma: no cover - jax layout change
        import jaxlib
        _ArrayImpl = jaxlib._jax.ArrayImpl
    targets = [_ArrayImpl, jax.core.Tracer]
    installed = []
    methods = _methods()
    for t in targets:
        for name, fn in methods.items():
            if hasattr(t, name):
                continue             # never shadow jax semantics
            try:
                setattr(t, name, fn)
                installed.append(f"{t.__name__}.{name}")
            except (AttributeError, TypeError):
                break                # immutable type: degrade silently
        if not hasattr(t, "stop_gradient"):
            def _get(self):
                return True          # no tape: nothing flows implicitly

            def _set(self, value):
                # =True is the harmless common case (matches reality);
                # =False signals the user expects implicit tracking —
                # warn ONCE with the migration pointer (the loud error
                # comes from paddle.grad/backward themselves)
                if value is False and not _WARNED.get("sg"):
                    _WARNED["sg"] = True
                    import warnings
                    warnings.warn(
                        "x.stop_gradient = False has no effect: this "
                        "framework uses functional autograd (jax.grad / "
                        "paddle.autograd.layer_grad take grads "
                        "explicitly); there is no tape to enable.",
                        stacklevel=2)
            try:
                t.stop_gradient = property(_get, _set)
                installed.append(f"{t.__name__}.stop_gradient")
            except (AttributeError, TypeError):
                pass
    return installed


__all__ = ["install"]
