"""paddle_tpu.tensor — the paddle-shaped tensor-function surface.

Reference: python/paddle/tensor/ (creation.py, math.py, linalg.py,
manipulation.py, search.py, logic.py, random.py — 31K LoC). Functions are
thin jnp/lax wrappers keeping the reference's names and argument
conventions (e.g. ``axis`` not ``dim``, ``x``/``y`` operands, matmul
transpose flags per tensor/linalg.py:151).

Arrays are plain jax.Array — there is no wrapper Tensor class; XLA owns
layout/placement. Dynamic-shape ops the reference supports via host fallback
(masked_select, nonzero) are provided but documented as jit-unfriendly.
"""

from __future__ import annotations

import builtins

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core.rng import rng_tracker, GLOBAL_STREAM

Tensor = jax.Array

# -- creation (reference: tensor/creation.py) --------------------------------

def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True):
    arr = jnp.asarray(data, dtype=_dt.convert_dtype(dtype) if dtype else None)
    if place is not None:
        arr = jax.device_put(arr, place)
    return arr


def zeros(shape, dtype="float32", name=None):
    return jnp.zeros(shape, _dt.convert_dtype(dtype))


def ones(shape, dtype="float32", name=None):
    return jnp.ones(shape, _dt.convert_dtype(dtype))


def full(shape, fill_value, dtype="float32", name=None):
    return jnp.full(shape, fill_value, _dt.convert_dtype(dtype))


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    """Legacy creation op (reference: tensor/creation.py fill_constant —
    still the idiom throughout test/dygraph_to_static). ``force_cpu``/
    ``out`` are accepted for signature parity; XLA owns placement."""
    return full(shape, value, dtype)


def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=_dt.convert_dtype(dtype) if dtype else None)


def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=_dt.convert_dtype(dtype) if dtype else None)


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=_dt.convert_dtype(dtype) if dtype else None)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype=_dt.convert_dtype(dtype) if dtype else None)


def linspace(start, stop, num, dtype=None):
    # reference accepts a float num (e.g. sr/2 arithmetic) and truncates
    return jnp.linspace(start, stop, int(num),
                        dtype=_dt.convert_dtype(dtype) if dtype else None)


def eye(num_rows, num_columns=None, dtype="float32"):
    return jnp.eye(num_rows, num_columns, dtype=_dt.convert_dtype(dtype))


def empty(shape, dtype="float32"):
    return jnp.zeros(shape, _dt.convert_dtype(dtype))


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def diag(x, offset=0):
    return jnp.diag(x, k=offset)


def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def meshgrid(*args):
    return jnp.meshgrid(*args, indexing="ij")


def clone(x):
    return jnp.array(x, copy=True)


def assign(x, output=None):
    return jnp.asarray(x)


# increment lives in extras.py (dtype-preserving; star-imported below)


# -- random (reference: tensor/random.py; draws from the global RNG tracker) -

def _key():
    # unseeded handling lives in next_key: eager auto-seed (entropy, warn
    # once) / loud error under tracing — seeding HERE with a constant
    # would store a tracer when first touched inside jit (leak)
    return rng_tracker().next_key()


def rand(shape, dtype="float32", name=None):
    return jax.random.uniform(_key(), tuple(shape), _dt.convert_dtype(dtype))


def randn(shape, dtype="float32", name=None):
    return jax.random.normal(_key(), tuple(shape), _dt.convert_dtype(dtype))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(_key(), tuple(shape), low, high,
                              _dt.convert_dtype(dtype))


def uniform(shape, dtype="float32", min=-1.0, max=1.0):
    return jax.random.uniform(_key(), tuple(shape), _dt.convert_dtype(dtype),
                              minval=min, maxval=max)


def normal(mean=0.0, std=1.0, shape=(1,)):
    return jax.random.normal(_key(), tuple(shape)) * std + mean


def randperm(n, dtype="int64"):
    return jax.random.permutation(_key(), n).astype(_dt.convert_dtype(dtype))


def multinomial(x, num_samples=1, replacement=False):
    if not replacement and num_samples > 1:
        raise NotImplementedError("multinomial without replacement > 1 sample")
    logits = jnp.log(jnp.clip(x, 1e-30, None))
    # categorical wants the sample count as leading dims broadcastable
    # against the batch; draw (num_samples, *batch) then move it last
    draws = jax.random.categorical(_key(), logits, axis=-1,
                                   shape=(num_samples, *x.shape[:-1]))
    return jnp.moveaxis(draws, 0, -1).astype(jnp.int64)


def bernoulli(x):
    return jax.random.bernoulli(_key(), x).astype(x.dtype)


# -- math (reference: tensor/math.py) ----------------------------------------

def _pd_sig(f):
    """Paddle call-convention shim over a jnp ufunc: jnp parameters are
    POSITIONAL-ONLY, but the reference's examples call by keyword
    (paddle.sign(x=x), paddle.pow(x=a, y=2)) and pass name=. Program
    vars (static mode) record the op instead of evaluating."""
    import functools as _ft

    @_ft.wraps(f)
    def g(*args, x=None, y=None, name=None, **kw):
        pos = list(args)
        # keyword x/y on top of positionals that already fill those slots
        # must be a loud duplicate-argument error, not a silent operand
        # swap (subtract(a, x=b) computed b - a; round-4 advice)
        if x is not None and args:
            raise TypeError(f"{f.__name__}() got multiple values for "
                            f"argument 'x'")
        if y is not None and len(args) >= 2:
            raise TypeError(f"{f.__name__}() got multiple values for "
                            f"argument 'y'")
        if x is not None:
            pos.insert(0, x)
        if y is not None:
            pos.insert(1 if pos else 0, y)
        # builtins.any: this module defines a paddle `any` reduction that
        # shadows the builtin
        if builtins.any(_is_lazy(a) for a in pos):
            from ..static import lazy_apply
            return lazy_apply(f, *pos, **kw)
        return f(*pos, **kw)
    return g


add = _pd_sig(jnp.add)
subtract = _pd_sig(jnp.subtract)
multiply = _pd_sig(jnp.multiply)
divide = _pd_sig(jnp.divide)
floor_divide = _pd_sig(jnp.floor_divide)
mod = remainder = _pd_sig(jnp.remainder)
pow = _pd_sig(jnp.power)
maximum = _pd_sig(jnp.maximum)
minimum = _pd_sig(jnp.minimum)
exp = _pd_sig(jnp.exp)
expm1 = _pd_sig(jnp.expm1)
log = _pd_sig(jnp.log)
log2 = _pd_sig(jnp.log2)
log10 = _pd_sig(jnp.log10)
log1p = _pd_sig(jnp.log1p)
sqrt = _pd_sig(jnp.sqrt)
square = _pd_sig(jnp.square)
abs = _pd_sig(jnp.abs)
sign = _pd_sig(jnp.sign)
floor = _pd_sig(jnp.floor)
ceil = _pd_sig(jnp.ceil)
round = _pd_sig(jnp.round)
trunc = _pd_sig(jnp.trunc)
sin = _pd_sig(jnp.sin)
cos = _pd_sig(jnp.cos)
tan = _pd_sig(jnp.tan)
asin = _pd_sig(jnp.arcsin)
acos = _pd_sig(jnp.arccos)
atan = _pd_sig(jnp.arctan)
atan2 = _pd_sig(jnp.arctan2)
sinh = _pd_sig(jnp.sinh)
cosh = _pd_sig(jnp.cosh)
tanh = _pd_sig(jnp.tanh)
asinh = _pd_sig(jnp.arcsinh)
acosh = _pd_sig(jnp.arccosh)
atanh = _pd_sig(jnp.arctanh)
erf = _pd_sig(jax.scipy.special.erf)
reciprocal = _pd_sig(jnp.reciprocal)
isnan = _pd_sig(jnp.isnan)
isinf = _pd_sig(jnp.isinf)
isfinite = _pd_sig(jnp.isfinite)
conj = _pd_sig(jnp.conj)
real = _pd_sig(jnp.real)
imag = _pd_sig(jnp.imag)
angle = _pd_sig(jnp.angle)
lerp = lambda x, y, w, name=None: x + w * (y - x)


def rsqrt(x):
    return jax.lax.rsqrt(x)


def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def _is_lazy(x):
    return hasattr(x, "_build") and hasattr(x, "_program")


def sum(x, axis=None, dtype=None, keepdim=False):
    if _is_lazy(x):
        return x._map(lambda v: jnp.sum(
            v, axis=axis, dtype=_dt.convert_dtype(dtype) if dtype else None,
            keepdims=keepdim), "sum")
    return jnp.sum(x, axis=axis, dtype=_dt.convert_dtype(dtype) if dtype else None,
                   keepdims=keepdim)


def mean(x, axis=None, keepdim=False):
    if _is_lazy(x):   # program var (static mode): record, don't eval
        return x._map(lambda v: jnp.mean(v, axis=axis, keepdims=keepdim),
                      "mean")
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False):
    if _is_lazy(x):
        return x._map(lambda v: jnp.max(v, axis=axis, keepdims=keepdim),
                      "max")
    return jnp.max(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False):
    if _is_lazy(x):
        return x._map(lambda v: jnp.min(v, axis=axis, keepdims=keepdim),
                      "min")
    return jnp.min(x, axis=axis, keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=axis, keepdims=keepdim,
                    dtype=_dt.convert_dtype(dtype) if dtype else None)


def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=_dt.convert_dtype(dtype) if dtype else None)


def cumprod(x, dim=None, dtype=None):
    return jnp.cumprod(x, axis=dim, dtype=_dt.convert_dtype(dtype) if dtype else None)


def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def nansum(x, axis=None, keepdim=False):
    return jnp.nansum(x, axis=axis, keepdims=keepdim)


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


def outer(x, y):
    return jnp.outer(x, y)


def inner(x, y):
    return jnp.inner(x, y)


def kron(x, y):
    return jnp.kron(x, y)


def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


# -- logic / compare (reference: tensor/logic.py) ----------------------------

equal = _pd_sig(jnp.equal)
not_equal = _pd_sig(jnp.not_equal)
greater_than = _pd_sig(jnp.greater)
greater_equal = _pd_sig(jnp.greater_equal)
less_than = _pd_sig(jnp.less)
less_equal = _pd_sig(jnp.less_equal)
logical_and = _pd_sig(jnp.logical_and)
logical_or = _pd_sig(jnp.logical_or)
logical_not = _pd_sig(jnp.logical_not)
logical_xor = _pd_sig(jnp.logical_xor)
bitwise_and = _pd_sig(jnp.bitwise_and)
bitwise_or = _pd_sig(jnp.bitwise_or)
bitwise_xor = _pd_sig(jnp.bitwise_xor)
bitwise_not = _pd_sig(jnp.bitwise_not)


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def equal_all(x, y):
    return jnp.array_equal(x, y)


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return jnp.where(condition, x, y)


# -- linalg (reference: tensor/linalg.py; matmul at :151) --------------------

def matmul(x, y, transpose_x: bool = False, transpose_y: bool = False,
           name=None):
    if _is_lazy(x) or _is_lazy(y):
        from ..static import lazy_apply
        return lazy_apply(matmul, x, y, transpose_x=transpose_x,
                          transpose_y=transpose_y, name="matmul")
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim >= 2 else y
    return jnp.matmul(x, y)


mm = matmul


def bmm(x, y):
    return jnp.matmul(x, y)


def dot(x, y):
    return jnp.sum(x * y, axis=-1)


def t(x):
    return jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x


def transpose(x, perm):
    return jnp.transpose(x, perm)


def norm(x, p=2, axis=None, keepdim=False):
    if p == "fro" or p == 2:
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.linalg.norm(x, ord=2 if not isinstance(axis, (tuple, list)) else "fro",
                               axis=axis if not isinstance(axis, list) else tuple(axis),
                               keepdims=keepdim)
    if p == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p)


def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def inverse(x):
    return jnp.linalg.inv(x)


def pinv(x, rcond=1e-15):
    return jnp.linalg.pinv(x, rtol=rcond)


def solve(x, y):
    return jnp.linalg.solve(x, y)


def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    return jnp.linalg.slogdet(x)


def matrix_rank(x, tol=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def histogram(x, bins=100, min=0, max=0):
    if min == 0 and max == 0:
        min, max = float(jnp.min(x)), float(jnp.max(x))
    hist, _ = jnp.histogram(x, bins=bins, range=(min, max))
    return hist


def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


# -- manipulation (reference: tensor/manipulation.py) ------------------------

def reshape(x, shape, name=None):
    # reference semantics (manipulation.py reshape): shape may be a
    # Tensor or contain Tensors, 0 copies the input dim, -1 infers
    if not isinstance(shape, (list, tuple)):
        shape = np.asarray(shape).tolist()
    dims = []
    for i, d in enumerate(shape):
        d = int(np.asarray(d).reshape(())) if not isinstance(d, int) else d
        dims.append(x.shape[i] if d == 0 else d)
    return jnp.reshape(x, dims)


def concat(x, axis=0, name=None):
    if not isinstance(axis, int):
        axis = int(np.asarray(axis).reshape(-1)[0])
    return jnp.concatenate(x, axis=axis)


def stack(x, axis=0):
    return jnp.stack(x, axis=axis)


def split(x, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    sections = list(num_or_sections)
    # paddle allows -1 for "rest"
    if -1 in sections:
        total = x.shape[axis]
        known = builtins.sum(s for s in sections if s != -1)
        sections = [s if s != -1 else total - known for s in sections]
    idx = np.cumsum(sections)[:-1]
    return jnp.split(x, idx, axis=axis)


def chunk(x, chunks, axis=0):
    return jnp.array_split(x, chunks, axis=axis)


def squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis if axis is None else tuple(np.atleast_1d(axis)))


def unsqueeze(x, axis):
    axes = tuple(np.atleast_1d(axis))
    return jnp.expand_dims(x, axes)


def expand(x, shape):
    shape = [x.shape[i - (len(shape) - x.ndim)] if s == -1 and i >= len(shape) - x.ndim
             else s for i, s in enumerate(shape)]
    return jnp.broadcast_to(x, shape)


def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


def broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


def tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


def flatten(x, start_axis=0, stop_axis=-1):
    stop = stop_axis if stop_axis >= 0 else x.ndim + stop_axis
    shape = list(x.shape[:start_axis]) + [-1] + list(x.shape[stop + 1:])
    return x.reshape(shape)


def flip(x, axis):
    return jnp.flip(x, axis=axis)


def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


def gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


def put_along_axis(x, indices, values, axis, reduce="assign"):
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)
    if reduce == "add":
        dim_idx = [jnp.arange(s).reshape([-1 if i == d else 1 for i in range(x.ndim)])
                   for d, s in enumerate(indices.shape)]
        dim_idx[axis] = indices
        return x.at[tuple(dim_idx)].add(jnp.broadcast_to(values, indices.shape))
    raise ValueError(reduce)


def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def index_add(x, index, axis, value):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].add(value)


def scatter(x, index, updates, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def masked_select(x, mask):
    """Dynamic output shape — host-side only; not jittable (reference keeps
    this op on the dygraph path too)."""
    return jnp.asarray(np.asarray(x)[np.asarray(mask)])


def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


def nonzero(x, as_tuple=False):
    idx = np.nonzero(np.asarray(x))
    if as_tuple:
        return tuple(jnp.asarray(i) for i in idx)
    return jnp.stack([jnp.asarray(i) for i in idx], axis=1)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    res = np.unique(np.asarray(x), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if isinstance(res, tuple):
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(res)


def unbind(x, axis=0):
    return [jnp.squeeze(s, axis=axis) for s in jnp.split(x, x.shape[axis], axis=axis)]


def slice(x, axes, starts, ends):
    def _as_int(v):
        # the reference accepts Tensors (0-d or [1]) inside starts/ends
        return v if isinstance(v, int) else int(np.asarray(v).reshape(-1)[0])
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = builtins.slice(_as_int(s), _as_int(e))
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides):
    def _int_list(v):
        """starts/ends/strides arrive as lists of ints OR (0-d/1-elem)
        tensors (the reference passes Tensors); coerce concretes to ints."""
        items = v if isinstance(v, (list, tuple)) else np.asarray(v).tolist()
        if not isinstance(items, (list, tuple)):
            items = [items]
        out = []
        for e in items:
            try:
                out.append(int(np.asarray(e).reshape(())))
            except Exception:
                out.append(e)
        return out
    starts, ends, strides = (_int_list(starts), _int_list(ends),
                             _int_list(strides))
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(s, e, st)
    return x[tuple(idx)]


def cast(x, dtype):
    return x.astype(_dt.convert_dtype(dtype))


def numel(x, name=None):
    # returns a 0-d integer Tensor like the reference (stat.py numel
    # example calls .numpy() on it), not a python int. int64 only when
    # jax x64 is on: with x64 off (the default here) a literal jnp.int64
    # emits a truncation UserWarning on every call (round-4 advice)
    dt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return jnp.asarray(int(np.prod(x.shape)) if x.shape else 1, dt)


def shape(x):
    if _is_lazy(x):    # static program var: record, don't eval
        return x._map(lambda v: jnp.asarray(v.shape, jnp.int32), "shape")
    return jnp.asarray(x.shape, dtype=jnp.int32)


def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(x, axis1, axis2)


def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


# -- search (reference: tensor/search.py) ------------------------------------

def argmax(x, axis=None, keepdim=False, dtype="int64"):
    return jnp.argmax(x, axis=axis, keepdims=keepdim).astype(_dt.convert_dtype(dtype))


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    return jnp.argmin(x, axis=axis, keepdims=keepdim).astype(_dt.convert_dtype(dtype))


def argsort(x, axis=-1, descending=False, stable=False):
    idx = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return idx


def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis, descending=descending)
    return out


def topk(x, k, axis=-1, largest=True, sorted=True):
    if not largest:
        vals, idx = jax.lax.top_k(-jnp.moveaxis(x, axis, -1), k)
        vals = -vals
    else:
        vals, idx = jax.lax.top_k(jnp.moveaxis(x, axis, -1), k)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(jnp.int64)


def kthvalue(x, k, axis=-1, keepdim=False):
    sorted_x = jnp.sort(x, axis=axis)
    idx = jnp.argsort(x, axis=axis)
    val = jnp.take(sorted_x, k - 1, axis=axis)
    ind = jnp.take(idx, k - 1, axis=axis)
    if keepdim:
        val = jnp.expand_dims(val, axis)
        ind = jnp.expand_dims(ind, axis)
    return val, ind


# -- breadth batch 2 (reference: python/paddle/tensor/{math,manipulation,
#    search,stat}.py — long-tail op surface) --------------------------------

def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    seq = jnp.asarray(sorted_sequence)
    vals = jnp.asarray(values)
    if seq.ndim == 1:
        out = jnp.searchsorted(seq, vals, side=side)
    else:
        # reference semantics: N-d sorted_sequence searches row-wise
        # against matching leading dims of values
        if seq.shape[:-1] != vals.shape[:-1]:
            raise ValueError(
                f"searchsorted: leading dims of sorted_sequence "
                f"{seq.shape} and values {vals.shape} must match")
        flat_seq = seq.reshape(-1, seq.shape[-1])
        flat_vals = vals.reshape(-1, vals.shape[-1])
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            flat_seq, flat_vals).reshape(vals.shape)
    return out.astype(jnp.int32) if out_int32 else out.astype(jnp.int64)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(jnp.asarray(x), axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return jnp.quantile(jnp.asarray(x), jnp.asarray(q), axis=axis,
                        keepdims=keepdim, method=interpolation)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    return jnp.nanquantile(jnp.asarray(x), jnp.asarray(q), axis=axis,
                           keepdims=keepdim, method=interpolation)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return jnp.trapezoid(jnp.asarray(y), jnp.asarray(x), axis=axis)
    return jnp.trapezoid(jnp.asarray(y), dx=dx if dx is not None else 1.0,
                         axis=axis)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    arr = jnp.asarray(x)
    if ranges is not None and len(ranges) and not isinstance(
            ranges[0], (list, tuple)):
        # reference passes a FLAT [lo0, hi0, lo1, hi1, ...] list
        ranges = [(ranges[2 * i], ranges[2 * i + 1])
                  for i in range(len(ranges) // 2)]
    h, edges = jnp.histogramdd(arr, bins=bins, range=ranges,
                               density=density, weights=weights)
    return h, edges


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    arr = jnp.asarray(x)
    if axis is not None:
        raise NotImplementedError("unique_consecutive over an axis: flatten "
                                  "first (host-side ragged output)")
    flat = arr.reshape(-1)
    # data-dependent output size — host-side like the reference's CPU path
    import numpy as _np
    a = _np.asarray(flat)
    if a.size == 0:
        outs = [jnp.asarray(a)]
        if return_inverse:
            outs.append(jnp.asarray([], jnp.int64))
        if return_counts:
            outs.append(jnp.asarray([], jnp.int64))
        return tuple(outs) if len(outs) > 1 else outs[0]
    change = _np.concatenate([[True], a[1:] != a[:-1]])
    uniq = a[change]
    outs = [jnp.asarray(uniq)]
    if return_inverse:
        outs.append(jnp.asarray(_np.cumsum(change) - 1, jnp.int64))
    if return_counts:
        idx = _np.flatnonzero(change)
        outs.append(jnp.asarray(_np.diff(_np.append(idx, a.size)), jnp.int64))
    return tuple(outs) if len(outs) > 1 else outs[0]


def index_put(x, indices, value, accumulate=False, name=None):
    x = jnp.asarray(x)
    idx = tuple(jnp.asarray(i) for i in indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    import builtins
    x = jnp.asarray(x)
    n = builtins.min(x.shape[axis1], x.shape[axis2])  # min() op shadows builtin
    i = jnp.arange(n - builtins.abs(offset))
    rows = i if offset >= 0 else i - offset
    cols = i + offset if offset >= 0 else i
    moved = jnp.moveaxis(x, (axis1, axis2), (0, 1))
    moved = moved.at[rows, cols].set(y)
    return jnp.moveaxis(moved, (0, 1), (axis1, axis2))


def select_scatter(x, values, axis, index, name=None):
    import builtins
    x = jnp.asarray(x)
    idx = [builtins.slice(None)] * x.ndim  # module-level slice() op shadows it
    idx[axis] = index
    return x.at[tuple(idx)].set(values)


def cummax(x, axis=None, dtype="int64", name=None):
    arr = jnp.asarray(x)
    if axis is None:
        arr = arr.reshape(-1)
        axis = 0
    vals = jax.lax.associative_scan(jnp.maximum, arr, axis=axis)
    # index of the running argmax
    eq = arr == vals
    pos = jnp.arange(arr.shape[axis]).reshape(
        [-1 if i == (axis % arr.ndim) else 1 for i in range(arr.ndim)])
    idx = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, pos, -1),
                                   axis=axis)
    return vals, idx.astype(dtype)


def cummin(x, axis=None, dtype="int64", name=None):
    arr = jnp.asarray(x)
    if axis is None:
        arr = arr.reshape(-1)
        axis = 0
    vals = jax.lax.associative_scan(jnp.minimum, arr, axis=axis)
    eq = arr == vals
    pos = jnp.arange(arr.shape[axis]).reshape(
        [-1 if i == (axis % arr.ndim) else 1 for i in range(arr.ndim)])
    idx = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, pos, -1),
                                   axis=axis)
    return vals, idx.astype(dtype)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    arr = jnp.asarray(x, dtype=dtype)
    if axis is None:
        arr = arr.reshape(-1)
        axis = 0
    return jax.lax.associative_scan(jnp.logaddexp, arr, axis=axis)


def renorm(x, p, axis, max_norm, name=None):
    arr = jnp.asarray(x)
    moved = jnp.moveaxis(arr, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.linalg.norm(flat, ord=p, axis=1)
    scale = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12),
                      1.0)
    out = flat * scale[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis)


def frexp(x, name=None):
    m, e = jnp.frexp(jnp.asarray(x))
    return m, e.astype(jnp.int32)


def lerp(x, y, weight, name=None):
    x, y = jnp.asarray(x), jnp.asarray(y)
    return x + jnp.asarray(weight) * (y - x)


def heaviside(x, y, name=None):
    return jnp.heaviside(jnp.asarray(x), jnp.asarray(y))


def nextafter(x, y, name=None):
    return jnp.nextafter(jnp.asarray(x), jnp.asarray(y))


def copysign(x, y, name=None):
    return jnp.copysign(jnp.asarray(x), jnp.asarray(y))


def vander(x, n=None, increasing=False, name=None):
    return jnp.vander(jnp.asarray(x), N=n, increasing=increasing)


def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(jnp.asarray(x), rowvar=rowvar)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(jnp.asarray(x), rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return jnp.count_nonzero(jnp.asarray(x), axis=axis, keepdims=keepdim)


def logaddexp(x, y, name=None):
    return jnp.logaddexp(jnp.asarray(x), jnp.asarray(y))


def hypot(x, y, name=None):
    return jnp.hypot(jnp.asarray(x), jnp.asarray(y))


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools as _it
    import numpy as _np
    a = _np.asarray(x).reshape(-1)
    gen = (_it.combinations_with_replacement(range(a.size), r)
           if with_replacement else _it.combinations(range(a.size), r))
    idx = _np.asarray(list(gen), dtype=_np.int64).reshape(-1, r)
    return jnp.asarray(a)[idx]


def unfold(x, axis, size, step, name=None):
    """Sliding windows along axis (reference Tensor.unfold)."""
    arr = jnp.asarray(x)
    n = (arr.shape[axis] - size) // step + 1
    starts = jnp.arange(n) * step
    idx = starts[:, None] + jnp.arange(size)[None, :]      # [n, size]
    out = jnp.take(arr, idx.reshape(-1), axis=axis)
    shape = list(arr.shape)
    shape[axis:axis + 1] = [n, size]
    out = out.reshape(shape)
    # paddle puts the window dim last
    return jnp.moveaxis(out, axis + 1, -1)


def tensordot(x, y, axes=2, name=None):
    return jnp.tensordot(jnp.asarray(x), jnp.asarray(y), axes=axes)


def atleast_1d(*inputs, name=None):
    out = [jnp.atleast_1d(jnp.asarray(a)) for a in inputs]
    return out[0] if len(out) == 1 else out


def atleast_2d(*inputs, name=None):
    out = [jnp.atleast_2d(jnp.asarray(a)) for a in inputs]
    return out[0] if len(out) == 1 else out


def atleast_3d(*inputs, name=None):
    out = [jnp.atleast_3d(jnp.asarray(a)) for a in inputs]
    return out[0] if len(out) == 1 else out


def block_diag(inputs, name=None):
    import jax.scipy.linalg as jsl
    return jsl.block_diag(*[jnp.asarray(a) for a in inputs])


def cartesian_prod(x, name=None):
    arrs = [jnp.asarray(a).reshape(-1) for a in x]
    grids = jnp.meshgrid(*arrs, indexing="ij")
    return jnp.stack([g.reshape(-1) for g in grids], axis=-1)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    arr = jnp.asarray(x)
    offset = int(offset)  # static: shapes derive from it (module-level `abs`
    n = arr.shape[-1] + (offset if offset >= 0 else -offset)  # is jnp.abs)
    out_shape = arr.shape[:-1] + (n, n)
    out = jnp.zeros(out_shape, arr.dtype)
    i = jnp.arange(arr.shape[-1])
    rows = i if offset >= 0 else i - offset
    cols = i + offset if offset >= 0 else i
    out = out.at[..., rows, cols].set(arr)
    if (dim1, dim2) != (-2, -1):
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return out


# -- elementwise long tail (reference: python/paddle/tensor/ops.py,
#    math.py — neg:?, deg2rad, rad2deg, digamma, lgamma, logit, fmax, fmin,
#    sigmoid re-export) --------------------------------------------------

def neg(x, name=None):
    return jnp.negative(jnp.asarray(x))


def sigmoid(x, name=None):
    return jax.nn.sigmoid(jnp.asarray(x))


def deg2rad(x, name=None):
    return jnp.deg2rad(jnp.asarray(x))


def rad2deg(x, name=None):
    return jnp.rad2deg(jnp.asarray(x))


def digamma(x, name=None):
    return jax.scipy.special.digamma(jnp.asarray(x))


def lgamma(x, name=None):
    return jax.scipy.special.gammaln(jnp.asarray(x))


def logit(x, eps=None, name=None):
    arr = jnp.asarray(x)
    if eps is not None:
        arr = jnp.clip(arr, eps, 1.0 - eps)
    return jnp.log(arr) - jnp.log1p(-arr)


def fmax(x, y, name=None):
    return jnp.fmax(jnp.asarray(x), jnp.asarray(y))


def fmin(x, y, name=None):
    return jnp.fmin(jnp.asarray(x), jnp.asarray(y))


# -- long-tail surface (extras) + inplace-spelled aliases --------------------
from .extras import *          # noqa: F401,F403,E402
from .inplace import *         # noqa: F401,F403,E402


# -- legacy tensor-array + var factory (reference: tensor/array.py,
#    tensor/creation.py create_tensor) --------------------------------------
from . import array  # noqa: E402
from . import random  # noqa: E402


def create_tensor(dtype, name=None, persistable=False):
    """Reference: creation.py create_tensor — an empty typed variable."""
    return jnp.zeros((0,), _dt.convert_dtype(dtype))


def tensor_array_to_tensor(input, axis=1, use_stack=False, name=None):
    """Reference: manipulation.py tensor_array_to_tensor — fuse a
    TensorArray into one tensor (+ per-element sizes)."""
    elems = list(input)
    if not elems:
        raise ValueError("empty tensor array")
    if use_stack:
        out = jnp.stack(elems, axis=axis)
        sizes = jnp.asarray([1] * len(elems), jnp.int32)
    else:
        out = jnp.concatenate(elems, axis=axis)
        sizes = jnp.asarray([e.shape[axis] for e in elems], jnp.int32)
    return out, sizes


from . import manipulation  # noqa: E402  (after tensor_array_to_tensor)
import sys as _sys
_sys.modules[__name__ + ".math"] = _sys.modules[__name__]
math = _sys.modules[__name__]      # paddle.tensor.math doctest path

for _n in ("array", "random", "manipulation", "create_tensor",
           "tensor_array_to_tensor"):
    if "__all__" in globals() and _n not in __all__:
        __all__.append(_n)
