"""paddle.tensor.manipulation module path (reference tensor/manipulation.py)
— re-exports the manipulation surface living on the tensor namespace."""

from . import (concat, stack, split, squeeze, unsqueeze, reshape, flatten,
               transpose, roll, flip, tile, expand, gather, scatter,
               strided_slice, tensor_array_to_tensor)

__all__ = ["concat", "stack", "split", "squeeze", "unsqueeze", "reshape",
           "flatten", "transpose", "roll", "flip", "tile", "expand",
           "gather", "scatter", "strided_slice", "tensor_array_to_tensor"]
