"""LoD TensorArray surface (reference: python/paddle/tensor/array.py —
create_array/array_write/array_read/array_length over the legacy
LOD_TENSOR_ARRAY variable).

TPU design: a TensorArray is host-side program STRUCTURE, not device
data — a Python list of arrays fills the contract exactly (the reference
dygraph mode does the same: array_write appends to a Python list).
Static-graph LoD semantics (per-level lengths) are a PS-era non-goal.
"""

from __future__ import annotations

import jax.numpy as jnp


def create_array(dtype="float32", initialized_list=None):
    """Reference: tensor/array.py create_array."""
    out = list(initialized_list) if initialized_list is not None else []
    return out


def array_write(x, i, array=None):
    """Write x at index i (reference array_write; appends when i == len)."""
    idx = int(i) if not hasattr(i, "shape") else int(jnp.reshape(i, ()))
    if array is None:
        array = []
    if idx < len(array):
        array[idx] = x
    elif idx == len(array):
        array.append(x)
    else:
        raise IndexError(
            f"array_write index {idx} beyond array length {len(array)}")
    return array


def array_read(array, i):
    idx = int(i) if not hasattr(i, "shape") else int(jnp.reshape(i, ()))
    return array[idx]


def array_length(array):
    return len(array)


__all__ = ["create_array", "array_write", "array_read", "array_length"]
