"""Long-tail tensor ops completing the ``paddle.*`` top-level surface.

Reference: python/paddle/tensor/{manipulation.py,math.py,creation.py,
random.py,search.py,logic.py,attribute.py} — the functions here are the
remainder of the reference's top-level export list (python/paddle/
__init__.py) not already covered by tensor/__init__.py. Same design: thin
jnp/lax compositions over plain jax.Array, paddle argument conventions
(``x``/``y``, ``axis``, dtype strings).
"""

from __future__ import annotations

import builtins
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core.rng import rng_tracker, GLOBAL_STREAM


def _key():
    return rng_tracker().next_key(GLOBAL_STREAM)


def _conv(dtype):
    return _dt.convert_dtype(dtype) if dtype is not None else None


# -- stacks / splits (reference: tensor/manipulation.py) ---------------------

def hstack(x, name=None):
    return jnp.hstack([jnp.asarray(t) for t in x])


def vstack(x, name=None):
    return jnp.vstack([jnp.asarray(t) for t in x])


def dstack(x, name=None):
    return jnp.dstack([jnp.asarray(t) for t in x])


def column_stack(x, name=None):
    return jnp.column_stack([jnp.asarray(t) for t in x])


def row_stack(x, name=None):
    return jnp.vstack([jnp.asarray(t) for t in x])


def hsplit(x, num_or_indices, name=None):
    return list(jnp.hsplit(jnp.asarray(x), num_or_indices))


def vsplit(x, num_or_indices, name=None):
    return list(jnp.vsplit(jnp.asarray(x), num_or_indices))


def dsplit(x, num_or_indices, name=None):
    return list(jnp.dsplit(jnp.asarray(x), num_or_indices))


def tensor_split(x, num_or_indices, axis=0, name=None):
    return list(jnp.array_split(jnp.asarray(x), num_or_indices, axis=axis))


def unstack(x, axis=0, num=None, name=None):
    arr = jnp.asarray(x)
    n = arr.shape[axis] if num is None else num
    return [jnp.squeeze(t, axis=axis)
            for t in jnp.split(arr, n, axis=axis)]


def reverse(x, axis, name=None):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(jnp.asarray(x), axis=axis)


def unflatten(x, axis, shape, name=None):
    arr = jnp.asarray(x)
    axis = axis % arr.ndim
    shape = tuple(int(s) for s in shape)
    new = arr.shape[:axis] + shape + arr.shape[axis + 1:]
    return arr.reshape(new)


def as_strided(x, shape, stride, offset=0, name=None):
    """View with explicit strides (reference: tensor/manipulation.py
    as_strided). jax arrays have no byte strides; gather the elements."""
    arr = jnp.asarray(x).reshape(-1)
    shape = tuple(int(s) for s in shape)
    stride = tuple(int(s) for s in stride)
    idx = jnp.full((), int(offset), jnp.int32)
    for size, st in zip(shape, stride):
        steps = jnp.arange(size, dtype=jnp.int32) * st
        idx = idx[..., None] + steps
    return arr[idx]


def view(x, shape_or_dtype, name=None):
    """Zero-copy reinterpret (reference: tensor/manipulation.py view).
    Shape view = reshape; dtype view rescales the LAST dim by the width
    ratio like paddle (f32[8] viewed as f16 -> f16[16])."""
    arr = jnp.asarray(x)
    if isinstance(shape_or_dtype, (list, tuple)):
        return arr.reshape(tuple(int(s) for s in shape_or_dtype))
    tgt = _dt.convert_dtype(shape_or_dtype)
    src_w = arr.dtype.itemsize
    tgt_w = jnp.dtype(tgt).itemsize
    if src_w == tgt_w:
        return jax.lax.bitcast_convert_type(arr, tgt)
    if src_w > tgt_w:
        # widening element count: bitcast adds a trailing [ratio] axis; fold
        out = jax.lax.bitcast_convert_type(arr, tgt)
        return out.reshape(*arr.shape[:-1], arr.shape[-1] * (src_w // tgt_w))
    ratio = tgt_w // src_w
    if arr.shape[-1] % ratio:
        raise ValueError(
            f"view: last dim {arr.shape[-1]} not divisible by the dtype "
            f"width ratio {ratio}")
    grouped = arr.reshape(*arr.shape[:-1], arr.shape[-1] // ratio, ratio)
    return jax.lax.bitcast_convert_type(grouped, tgt)


def view_as(x, other, name=None):
    return jnp.asarray(x).reshape(jnp.asarray(other).shape)


def crop(x, shape=None, offsets=None, name=None):
    arr = jnp.asarray(x)
    shape = list(arr.shape if shape is None else shape)
    shape = [arr.shape[i] if s in (-1, None) else int(s)
             for i, s in enumerate(shape)]
    offsets = [0] * arr.ndim if offsets is None else [int(o) for o in offsets]
    return jax.lax.dynamic_slice(arr, offsets, shape)


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors (reference: tensor/math.py
    multiplex): out[i] = inputs[index[i]][i]."""
    stacked = jnp.stack([jnp.asarray(t) for t in inputs], axis=0)  # [n, b, ...]
    idx = jnp.asarray(index).reshape(-1).astype(jnp.int32)         # [b]
    rows = jnp.arange(stacked.shape[1], dtype=jnp.int32)
    return stacked[idx, rows]


def index_sample(x, index):
    """Per-row gather: out[i, j] = x[i, index[i, j]] (reference:
    tensor/search.py index_sample)."""
    return jnp.take_along_axis(jnp.asarray(x), jnp.asarray(index), axis=1)


def index_fill(x, index, axis, value, name=None):
    arr = jnp.asarray(x)
    idx = jnp.asarray(index).astype(jnp.int32)
    moved = jnp.moveaxis(arr, axis, 0)
    moved = moved.at[idx].set(value)
    return jnp.moveaxis(moved, 0, axis)


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions from ``value``'s leading elements in row-major
    order (reference: tensor/manipulation.py masked_scatter)."""
    arr = jnp.asarray(x)
    m = jnp.broadcast_to(jnp.asarray(mask, jnp.bool_), arr.shape).reshape(-1)
    src = jnp.asarray(value, arr.dtype).reshape(-1)
    # paddle errors when value has fewer elements than mask Trues; the count
    # is data-dependent, so the check can only run on concrete (non-traced)
    # masks — under jit the documented clamp behavior applies
    try:
        trues = int(jnp.sum(m))
        if src.shape[0] < trues:
            raise ValueError(
                f"masked_scatter: value has {src.shape[0]} elements but "
                f"mask selects {trues}")
    except jax.errors.ConcretizationTypeError:
        pass
    # k-th True consumes src[k]
    slot = jnp.cumsum(m.astype(jnp.int32)) - 1
    take = src[jnp.clip(slot, 0, src.shape[0] - 1)]
    return jnp.where(m, take, arr.reshape(-1)).reshape(arr.shape)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    arr = jnp.asarray(x)
    idx = [builtins.slice(None)] * arr.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(int(st), int(en), int(sd))
    return arr.at[tuple(idx)].set(jnp.asarray(value, arr.dtype))


def scatter_nd(index, updates, shape, name=None):
    out = jnp.zeros(tuple(int(s) for s in shape),
                    jnp.asarray(updates).dtype)
    idx = jnp.asarray(index)
    return out.at[tuple(jnp.moveaxis(idx, -1, 0))].add(updates)


def shard_index(input=None, index_num=None, nshards=None, shard_id=None,
                ignore_value=-1, x=None):
    """Relabel global ids into a shard-local range (reference:
    tensor/manipulation.py shard_index; used by dist embedding).
    First arg is named ``input`` like the reference; ``x`` kept for
    callers of the old spelling."""
    x = input if input is not None else x
    arr = jnp.asarray(x)
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    inside = (arr >= lo) & (arr < lo + shard_size)
    return jnp.where(inside, arr - lo, ignore_value)


def take(x, index, mode="raise", name=None):
    arr = jnp.asarray(x).reshape(-1)
    idx = jnp.asarray(index)
    n = arr.shape[0]
    if mode == "wrap":
        idx = ((idx % n) + n) % n
    elif mode == "clip":
        idx = jnp.clip(idx, -n, n - 1)
    idx = jnp.where(idx < 0, idx + n, idx)
    return arr[idx]


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return jnp.stack([r, c]).astype(_conv(dtype))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return jnp.stack([r, c]).astype(_conv(dtype))


def diagflat(x, offset=0, name=None):
    return jnp.diagflat(jnp.asarray(x), k=offset)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.diagonal(jnp.asarray(x), offset=offset, axis1=axis1,
                        axis2=axis2)


# -- shape / predicate helpers (reference: tensor/attribute.py, logic.py) ----

def rank(input, name=None):
    return jnp.asarray(jnp.asarray(input).ndim, jnp.int32)


def is_tensor(x):
    return isinstance(x, (jax.Array, np.ndarray))


def is_complex(x):
    return jnp.iscomplexobj(jnp.asarray(x))


def is_floating_point(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer)


def is_empty(x, name=None):
    return jnp.asarray(jnp.asarray(x).size == 0)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def broadcast_tensors(input, name=None):
    arrs = [jnp.asarray(t) for t in input]
    shape = np.broadcast_shapes(*[a.shape for a in arrs])
    return [jnp.broadcast_to(a, shape) for a in arrs]


def increment(x, value=1.0, name=None):
    # dtype-preserving (reference increment keeps the tensor's dtype; a
    # bare python-float add would promote int counters to float)
    x = jnp.asarray(x)
    return x + jnp.asarray(value).astype(x.dtype)


def tolist(x):
    return np.asarray(x).tolist()


# -- math long tail (reference: tensor/math.py) ------------------------------

def add_n(inputs, name=None):
    arrs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return functools.reduce(jnp.add, [jnp.asarray(a) for a in arrs])


def gcd(x, y, name=None):
    return jnp.gcd(jnp.asarray(x), jnp.asarray(y))


def lcm(x, y, name=None):
    return jnp.lcm(jnp.asarray(x), jnp.asarray(y))


def ldexp(x, y, name=None):
    return jnp.ldexp(jnp.asarray(x), jnp.asarray(y))


def frac(x, name=None):
    arr = jnp.asarray(x)
    return arr - jnp.trunc(arr)


def sgn(x, name=None):
    """sign for real; unit-modulus phase for complex (tensor/math.py sgn)."""
    arr = jnp.asarray(x)
    if jnp.iscomplexobj(arr):
        mod = jnp.abs(arr)
        return jnp.where(mod == 0, 0, arr / jnp.where(mod == 0, 1, mod))
    return jnp.sign(arr)


def signbit(x, name=None):
    return jnp.signbit(jnp.asarray(x))


def floor_mod(x, y, name=None):
    return jnp.mod(jnp.asarray(x), jnp.asarray(y))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return jnp.nan_to_num(jnp.asarray(x), nan=nan, posinf=posinf,
                          neginf=neginf)


def erfinv(x, name=None):
    return jax.scipy.special.erfinv(jnp.asarray(x))


def i0(x, name=None):
    return jax.scipy.special.i0(jnp.asarray(x))


def i0e(x, name=None):
    return jax.scipy.special.i0e(jnp.asarray(x))


def i1(x, name=None):
    return jax.scipy.special.i1(jnp.asarray(x))


def i1e(x, name=None):
    return jax.scipy.special.i1e(jnp.asarray(x))


def polygamma(x, n, name=None):
    return jax.scipy.special.polygamma(n, jnp.asarray(x))


def multigammaln(x, p, name=None):
    return jax.scipy.special.multigammaln(jnp.asarray(x), p)


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return jnp.logspace(start, stop, int(num), base=base,
                        dtype=_conv(dtype) or jnp.float32)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * jnp.asarray(x))


def polar(abs, angle, name=None):
    a = jnp.asarray(abs)
    return (a * jnp.cos(angle) + 1j * a * jnp.sin(angle)).astype(
        jnp.complex64 if a.dtype == jnp.float32 else jnp.complex128)


def complex(real, imag, name=None):
    r = jnp.asarray(real)
    i = jnp.asarray(imag, r.dtype)
    r, i = jnp.broadcast_arrays(r, i)   # reference broadcasts rank too
    return jax.lax.complex(r, i)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    yarr = jnp.asarray(y)
    n = yarr.shape[axis]
    y0 = jax.lax.slice_in_dim(yarr, 0, n - 1, axis=axis)
    y1 = jax.lax.slice_in_dim(yarr, 1, n, axis=axis)
    if x is not None:
        xarr = jnp.asarray(x)
        if xarr.ndim == 1:
            shape = [1] * yarr.ndim
            shape[axis] = xarr.shape[0]
            xarr = xarr.reshape(shape)
        d = (jax.lax.slice_in_dim(xarr, 1, xarr.shape[axis], axis=axis)
             - jax.lax.slice_in_dim(xarr, 0, xarr.shape[axis] - 1, axis=axis))
    else:
        d = 1.0 if dx is None else dx
    return jnp.cumsum((y0 + y1) * 0.5 * d, axis=axis)


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis, with its (last-occurrence) index
    (reference: tensor/search.py mode). Sort-based, jit-friendly."""
    arr = jnp.asarray(x)
    axis = axis % arr.ndim
    moved = jnp.moveaxis(arr, axis, -1)
    srt = jnp.sort(moved, axis=-1)
    n = srt.shape[-1]
    # run-length via "same as previous" prefix count
    same = jnp.concatenate(
        [jnp.zeros(srt.shape[:-1] + (1,), jnp.int32),
         (srt[..., 1:] == srt[..., :-1]).astype(jnp.int32)], axis=-1)
    def scan_run(carry, s):
        run = jnp.where(s > 0, carry + 1, 0)
        return run, run
    _, runs = jax.lax.scan(scan_run,
                           jnp.zeros(srt.shape[:-1], jnp.int32),
                           jnp.moveaxis(same, -1, 0))
    runs = jnp.moveaxis(runs, 0, -1)
    best = jnp.argmax(runs, axis=-1)                     # end of longest run
    values = jnp.take_along_axis(srt, best[..., None], axis=-1)[..., 0]
    # paddle returns the index of an occurrence in the ORIGINAL tensor; use
    # the last occurrence (matches paddle's choice for duplicated values)
    eq = moved == values[..., None]
    pos = jnp.arange(n)
    idx = jnp.max(jnp.where(eq, pos, -1), axis=-1)
    if keepdim:
        values = jnp.expand_dims(values, axis)
        idx = jnp.expand_dims(idx, axis)
    return values, idx.astype(jnp.int64)


# -- distance (reference: tensor/linalg.py cdist/dist, nn/functional pdist) --

def dist(x, y, p=2.0, name=None):
    diff = jnp.asarray(x) - jnp.asarray(y)
    flat = diff.reshape(-1)
    if p == float("inf"):
        return jnp.max(jnp.abs(flat))
    if p == float("-inf"):
        return jnp.min(jnp.abs(flat))
    if p == 0:
        return jnp.sum(flat != 0).astype(diff.dtype)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(flat), p)), 1.0 / p)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    xa = jnp.asarray(x)[..., :, None, :]
    ya = jnp.asarray(y)[..., None, :, :]
    # the |x|^2+|y|^2-2xy form cancels badly in fp32; like the reference's
    # "if_necessary" mode, only take the MXU path when the feature dim is
    # large enough that the O(n*m*d) direct broadcast would dominate
    use_mm = (compute_mode == "use_mm_for_euclid_dist"
              or (compute_mode == "use_mm_for_euclid_dist_if_necessary"
                  and jnp.asarray(x).shape[-1] > 25))
    if p == 2.0 and use_mm:
        # |x-y|^2 = |x|^2 + |y|^2 - 2<x,y> — MXU-friendly form
        x2 = jnp.sum(jnp.asarray(x) ** 2, -1)[..., :, None]
        y2 = jnp.sum(jnp.asarray(y) ** 2, -1)[..., None, :]
        xy = jnp.matmul(jnp.asarray(x), jnp.swapaxes(jnp.asarray(y), -1, -2))
        return jnp.sqrt(jnp.maximum(x2 + y2 - 2 * xy, 0.0))
    diff = jnp.abs(xa - ya)
    if p == float("inf"):
        return jnp.max(diff, axis=-1)
    if p == 0:
        return jnp.sum(diff != 0, axis=-1).astype(diff.dtype)
    return jnp.power(jnp.sum(jnp.power(diff, p), axis=-1), 1.0 / p)


def pdist(x, p=2.0, name=None):
    arr = jnp.asarray(x)
    n = arr.shape[0]
    full = cdist(arr, arr, p=p)
    iu, ju = jnp.triu_indices(n, k=1)
    return full[iu, ju]


def mv(x, vec, name=None):
    return jnp.matmul(jnp.asarray(x), jnp.asarray(vec))


# -- random long tail (reference: tensor/random.py) --------------------------

def standard_normal(shape, dtype="float32", name=None):
    return jax.random.normal(_key(), tuple(shape), _conv(dtype))


def randint_like(x, low, high=None, dtype=None, name=None):
    arr = jnp.asarray(x)
    if high is None:
        low, high = 0, low
    return jax.random.randint(_key(), arr.shape, low, high,
                              _conv(dtype) or arr.dtype)


def poisson(x, name=None):
    arr = jnp.asarray(x)
    return jax.random.poisson(_key(), arr).astype(arr.dtype)


def binomial(count, prob, name=None):
    c = jnp.asarray(count)
    p = jnp.broadcast_to(jnp.asarray(prob, jnp.float32),
                         np.broadcast_shapes(c.shape, jnp.shape(prob)))
    return jax.random.binomial(_key(), c.astype(jnp.float32), p).astype(
        jnp.int64)


def normal_(x, mean=0.0, std=1.0, name=None):
    arr = jnp.asarray(x)
    return mean + std * jax.random.normal(_key(), arr.shape,
                                          dtype=arr.dtype)


def cauchy_(x, loc=0, scale=1, name=None):
    arr = jnp.asarray(x)
    return loc + scale * jax.random.cauchy(_key(), arr.shape,
                                           dtype=arr.dtype)


def geometric_(x, probs, name=None):
    arr = jnp.asarray(x)
    p = jnp.broadcast_to(jnp.asarray(probs, arr.dtype), arr.shape)
    u = jax.random.uniform(_key(), arr.shape, dtype=jnp.float32)
    return (jnp.floor(jnp.log1p(-u) / jnp.log1p(-p.astype(jnp.float32)))
            + 1.0).astype(arr.dtype)


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """Fill the main diagonal (reference: tensor/manipulation.py
    fill_diagonal_): 2-D with optional row wrap, or the all-equal-index
    diagonal for >2-D."""
    arr = jnp.asarray(x)
    if arr.ndim == 2:
        n, m = arr.shape
        # flat-storage stride m+1, like numpy/torch fill_diagonal_: with
        # wrap=True on tall matrices the diagonal restarts after a blank
        # row; offset shifts the starting flat position
        start = offset if offset >= 0 else -offset * m
        if wrap:
            flat_idx = np.arange(start, n * m, m + 1)
        else:
            count = min(n, m - offset) if offset >= 0 else min(n + offset, m)
            flat_idx = start + np.arange(max(0, count)) * (m + 1)
        flat = arr.reshape(-1).at[jnp.asarray(flat_idx)].set(value)
        return flat.reshape(n, m)
    k = min(arr.shape)
    idx = jnp.arange(k)
    return arr.at[tuple(idx for _ in range(arr.ndim))].set(value)


__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and getattr(_v, "__module__", None) == __name__]
