"""paddle.tensor.random module path (reference: tensor/random.py) — the
random ops live on the tensor namespace; this module re-exports them so
`from paddle.tensor import random` / `paddle.tensor.random.xxx` work."""

from . import (bernoulli, multinomial, normal, poisson, rand, randint,
               randint_like, randn, randperm, standard_normal, uniform)

try:  # optional long-tail names
    from . import exponential_, uniform_, normal_  # noqa: F401
except ImportError:  # pragma: no cover
    pass

__all__ = ["bernoulli", "multinomial", "normal", "poisson", "rand",
           "randint", "randint_like", "randn", "randperm",
           "standard_normal", "uniform"]


def gaussian_(x, mean=0.0, std=1.0, seed=0, name=None):
    """Value-semantics alias of the inplace gaussian fill (reference
    tensor/random.py:469): returns a fresh normal draw shaped like x."""
    import jax
    import jax.numpy as jnp
    from ..core.rng import rng_tracker
    key = (jax.random.key(seed) if seed else rng_tracker().next_key())
    return mean + std * jax.random.normal(key, jnp.shape(x),
                                          jnp.asarray(x).dtype)


def uniform_random_batch_size_like(input, shape, input_dim_idx=0,
                                   output_dim_idx=0, min=-1.0, max=1.0,
                                   seed=0, dtype="float32", name=None):
    """Reference tensor/random.py:297 — shape[output_dim_idx] follows
    input.shape[input_dim_idx]."""
    import jax
    import jax.numpy as jnp
    from ..core.dtype import convert_dtype
    from ..core.rng import rng_tracker
    shape = list(shape)
    in_shape = getattr(input, "shape", None)
    if in_shape is None:
        in_shape = jnp.shape(input)
    shape[output_dim_idx] = in_shape[input_dim_idx]
    key = (jax.random.key(seed) if seed else rng_tracker().next_key())
    return jax.random.uniform(key, tuple(int(s) for s in shape),
                              convert_dtype(dtype), min, max)


__all__ += ["gaussian_", "uniform_random_batch_size_like"]
