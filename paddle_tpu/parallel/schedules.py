"""Pipeline schedules: explicit 1F1B and interleaved (circular/VPP).

Reference analogue: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py — forward_backward_pipeline (1F1B, :440), the
interleaved "virtual pipeline" scheduler (:906) and FThenB (:1489), plus
the static pass python/paddle/distributed/passes/pipeline_scheduler_pass.py
(:47-465). Those drive per-rank actor runtimes exchanging P2P sends; here
the whole schedule is ONE jitted SPMD program over the stage-stacked
representation of parallel/pipeline.py (stage axis sharded over "pp",
stage-to-stage movement = jnp.roll → CollectivePermute on ICI).

1F1B (``pipeline_1f1b``)
------------------------
Slot mapping — tick t, stage s:

  F-slot: forward microbatch  m_f = t - s            (mask: 0 <= m_f < M)
  B-slot: backward microbatch m_b = t - (2S-2-s)     (mask: 0 <= m_b < M)

so stage S-1 runs B(m) in the same tick as F(m) — the defining 1F1B
property; the backward wave then walks down one stage per tick. The
T = M + 2(S-1) ticks are executed as THREE scans sharing one carry, so
fill/drain ticks only pay for the slot that can be live:

  fill   t in [0, S-1):         F-cell only (no B-slot is valid yet)
  steady t in [S-1, M+S-1):     F-cell + loss head + B-cell
  drain  t in [M+S-1, M+2S-2):  B-cell only (no F-slot is valid)

Per-tick cost is therefore (S-1)·tF + M·(tF+tB) + (S-1)·tB — i.e. the
classic (S-1)-bubble of the reference's 1F1B runtime
(pipeline_parallel.py:440-580), not the 2(S-1) a single full-slot lockstep
loop would pay. The two opposite-direction jnp.rolls in the steady body
(F-activations s->s+1, B-cotangents s->s-1) lower to a pair of
CollectivePermutes with no data dependence, which XLA schedules
concurrently over the bidirectional ICI links — the SPMD analogue of the
reference's fused ``send_forward_recv_backward`` pairs
(pipeline_parallel.py:521,:544).

Activation memory: stage INPUTS (``remat=True``, default) or full vjp
RESIDUALS (``remat=False``) are saved in a ring of R = min(M, 2S-1) slots,
so the live set is O(S), independent of M, versus M for
GPipe-through-jax.grad. With ``remat=True`` the B-cell replays the stage
forward under jax.vjp (the reference's recompute interval); with
``remat=False`` the saved residuals are applied directly — no recompute,
at 2S-1 microbatches of residual memory per stage (use when HBM allows,
mirroring the reference's optional recompute).

The loss head (final norm/projection + loss) runs once per tick,
un-vmapped, on stage S-1's F-slot output (its B-slot microbatch equals its
same-tick F-slot microbatch), so stage S-1 starts backward immediately and
a heavy vocab projection costs 1× per tick, not S×.

Interleaved / circular VPP (``pipeline_interleaved``)
-----------------------------------------------------
Megatron's virtual-pipeline: each physical stage holds V model chunks
(params [V, S, ...]); microbatch m passes chunk 0 through stages 0..S-1,
wraps back to stage 0 for chunk 1, etc. The wraparound IS jnp.roll's
circularity, so the data motion is identical to the plain pipeline; only
the per-stage chunk index varies by tick. Schedule: microbatches grouped
S at a time; group g, local microbatch i, chunk v runs on stage s at tick
t = g·VS + vS + i + s — dense (every stage busy every tick once full) and
conflict-free (unique (i,v) per (s,t)). Total ticks MV + S - 1 of
CHUNK-sized work vs the non-interleaved (M + S - 1) ticks of STAGE-sized
(=V chunks) work: the fill/drain bubble shrinks from (S-1)·V to (S-1)
chunk-times — the V× bubble reduction VPP exists for
(pipeline_parallel.py:906). Differentiable; backward is FThenB through
the scan (remat per chunk call).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def _tree_add(a, b):
    return jax.tree.map(jnp.add, a, b)


def _tree_zeros(t):
    return jax.tree.map(lambda x: jnp.zeros(x.shape, x.dtype), t)


def pipeline_1f1b(stage_fn: Callable, stacked_params, x_mb, targets_mb,
                  loss_head_fn: Callable, head_params, *, num_stages: int,
                  remat: bool = True, return_dx: bool = False,
                  weighted_loss: bool = False):
    """Fused forward+backward 1F1B pipeline step.

    stage_fn(params_slice, h) -> h                      one stage's compute
    stacked_params: pytree, leaves [S, ...] (sharded over "pp")
    x_mb:       [M, mb, ...] stage-0 inputs (e.g. embedded hiddens)
    targets_mb: [M, mb, ...] labels for the loss head
    loss_head_fn(head_params, h, target) -> scalar mean loss per microbatch,
        or, with ``weighted_loss=True``, a (loss_sum, weight) pair (e.g.
        token-summed cross entropy + valid-token count) so the result is
        the single GLOBAL weighted mean over all microbatches — identical
        math to the unpipelined model even when padding (ignore_index) is
        spread unevenly across microbatches.
    head_params: pytree (replicated over pp), e.g. final norm + projection

    The loss head runs ONCE per tick, un-vmapped: stage S-1 backwards
    microbatch m in the very tick that forwarded it, so the head consumes
    the F-slot output directly instead of being computed (masked) on every
    stage — a heavy vocab projection costs 1×, not S×, per tick.

    Returns (mean_loss, stacked_param_grads, head_grads); with
    ``return_dx`` also the [M, mb, ...] fp32 cotangent of x_mb (already
    mean-scaled), so the caller can continue backprop into the embedding.
    This IS the backward — do not wrap in jax.grad.
    """
    S = num_stages
    M = x_mb.shape[0]
    if M < 1:
        raise ValueError("need at least one microbatch")
    R = min(M, 2 * S - 1)
    sidx = jnp.arange(S)

    if weighted_loss:
        head2 = loss_head_fn
    else:
        head2 = lambda hp, h, tgt: (loss_head_fn(hp, h, tgt),
                                    jnp.float32(1.0))

    fin0 = jnp.zeros((S,) + x_mb.shape[1:], x_mb.dtype)
    # the carry holds the UN-rolled backward cotangent (dh, stage-local);
    # the boundary exchange (roll = pp CollectivePermute) is posted at the
    # TOP of the consuming tick — double-buffered sends (ISSUE 14): the
    # permute's start->done window then spans the tick's forward compute
    # instead of sitting exposed at the body tail, where XLA's
    # latency-hiding scheduler cannot reach across the scan iteration.
    # roll(zeros) == zeros, so dh0 reproduces the old bcot0 bit-exactly.
    dh0 = jnp.zeros((S,) + x_mb.shape[1:], jnp.float32)
    dx0 = jnp.zeros(x_mb.shape, jnp.float32)

    # ---- F-cell: forward one stage, saving what backward will need ------
    _stash = {}

    def _fcell_res(p_s, h_s):
        out, vjp_fn = jax.vjp(stage_fn, p_s, h_s)
        leaves, td = jax.tree.flatten(vjp_fn)
        _stash["td"] = td
        _stash["out_dtype"] = out.dtype
        return out, leaves

    saved_td = saved_out_dtype = None
    if remat:
        # ring stores stage INPUTS; backward replays the stage under vjp
        ring0 = [jnp.zeros((S, R) + x_mb.shape[1:], x_mb.dtype)]
    else:
        # ring stores vjp RESIDUALS (jax.vjp's pytree-registered closure,
        # flattened); backward applies them with no recompute
        _, leaf_sh = jax.eval_shape(
            lambda P, H: jax.vmap(_fcell_res)(P, H), stacked_params, fin0)
        saved_td = _stash["td"]          # trace-static closure structure
        saved_out_dtype = _stash["out_dtype"]
        ring0 = [jnp.zeros((s.shape[0], R) + tuple(s.shape[1:]), s.dtype)
                 for s in leaf_sh]

    carry0 = (fin0, dh0, ring0, dx0, _tree_zeros(stacked_params),
              _tree_zeros(head_params), jnp.float32(0.0), jnp.float32(0.0))

    def ring_write(ring_s, h_s, idx, valid):
        old = jax.lax.dynamic_index_in_dim(ring_s, idx, 0, keepdims=False)
        new = jnp.where(valid, h_s, old)
        return jax.lax.dynamic_update_index_in_dim(ring_s, new, idx, 0)

    def ring_read(ring_s, idx):
        return jax.lax.dynamic_index_in_dim(ring_s, idx, 0, keepdims=False)

    def f_cell(fin, ring, t):
        """Inject stage-0 input, run all stages forward, save backward
        state into the ring. Returns (out_f, ring)."""
        m_f = t - sidx                                   # [S]
        valid_f = (m_f >= 0) & (m_f < M)
        inj = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        fin = fin.at[0].set(inj)
        slot = jnp.mod(m_f, R)
        if remat:
            ring = [jax.vmap(ring_write)(ring[0], fin, slot, valid_f)]
            out_f = jax.vmap(stage_fn)(stacked_params, fin)
        else:
            out_f, leaves = jax.vmap(_fcell_res)(stacked_params, fin)
            ring = [jax.vmap(ring_write)(r, l, slot, valid_f)
                    for r, l in zip(ring, leaves)]
        return out_f, ring

    def bslot_remat(p_s, h_s, g):
        """One stage's backward cell: recompute fwd under vjp, pull the
        stage back along the (pre-masked) cotangent g."""
        out, vjp_fn = jax.vjp(stage_fn, p_s, h_s)
        dp, dh = vjp_fn(g.astype(out.dtype))
        return dp, dh.astype(jnp.float32)

    def bslot_saved(leaves_s, g):
        vjp_fn = jax.tree.unflatten(saved_td, list(leaves_s))
        dp, dh = vjp_fn(g.astype(saved_out_dtype))
        return dp, dh.astype(jnp.float32)

    def b_cell(bcot, ring, dx, gacc, t, g_loss=None):
        """Run all stages backward along the (masked) cotangents; stage 0's
        input-grad lands in dx. Returns (dh, dx, gacc)."""
        m_b = t - (2 * S - 2 - sidx)                     # [S]
        valid_b = (m_b >= 0) & (m_b < M)
        slot = jnp.mod(m_b, R)
        g = bcot if g_loss is None else bcot.at[S - 1].set(
            g_loss.astype(jnp.float32))
        g = g * valid_b.astype(jnp.float32).reshape(
            (S,) + (1,) * (g.ndim - 1))
        if remat:
            h_b = jax.vmap(ring_read)(ring[0], slot)
            dparams, dh = jax.vmap(bslot_remat)(stacked_params, h_b, g)
        else:
            leaves_b = [jax.vmap(ring_read)(r, slot) for r in ring]
            dparams, dh = jax.vmap(bslot_saved)(leaves_b, g)
        gacc = _tree_add(gacc, dparams)
        # stage 0's input-grad is d x_mb[m_b[0]] — record for the caller
        m0 = jnp.clip(m_b[0], 0, M - 1)
        prev = jax.lax.dynamic_index_in_dim(dx, m0, 0, keepdims=False)
        dx = jax.lax.dynamic_update_index_in_dim(
            dx, jnp.where(valid_b[0], dh[0], prev), m0, 0)
        return dh, dx, gacc

    # ---- fill: t in [0, S-1) — only F-slots can be live -----------------
    def fill_tick(carry, t):
        fin, dh, ring, dx, gacc, hacc, lacc, wacc = carry
        out_f, ring = f_cell(fin, ring, t)
        fin = jnp.roll(out_f, 1, axis=0)    # stage s -> s+1
        return (fin, dh, ring, dx, gacc, hacc, lacc, wacc), None

    # ---- steady: t in [S-1, M+S-1) — one F and one B per tick -----------
    def steady_tick(carry, t):
        fin, dh, ring, dx, gacc, hacc, lacc, wacc = carry
        # double-buffered boundary exchange: post the backward permute
        # FIRST — b_cell (its only consumer) runs after the forward cell
        # and the loss head, so the transfer rides behind them. Same
        # values the old tail-roll produced, one tick later by carry.
        bcot = jnp.roll(dh, -1, axis=0)     # stage s -> s-1
        out_f, ring = f_cell(fin, ring, t)
        # forward permute posted right after the F-cell: its consumer is
        # the NEXT tick's f_cell, so the head + backward below are its
        # in-window compute. The two opposite-direction permutes remain
        # independent — XLA runs them concurrently over bidirectional ICI
        # (reference's send_forward_recv_backward pairing).
        fin = jnp.roll(out_f, 1, axis=0)    # stage s -> s+1
        # loss head (once, un-vmapped): stage S-1 backwards microbatch m in
        # the very tick that forwarded it, so the head consumes this tick's
        # F-slot output directly. m_b[S-1] = t-(S-1) is always valid here.
        tgt = jax.lax.dynamic_index_in_dim(
            targets_mb, jnp.clip(t - (S - 1), 0, M - 1), 0, keepdims=False)
        (lsum, w), (g_head, g_loss) = jax.value_and_grad(
            lambda hp, h: head2(hp, h, tgt), argnums=(0, 1),
            has_aux=True)(head_params, out_f[S - 1])
        lacc = lacc + lsum
        wacc = wacc + w
        hacc = _tree_add(hacc, g_head)
        dh, dx, gacc = b_cell(bcot, ring, dx, gacc, t, g_loss)
        return (fin, dh, ring, dx, gacc, hacc, lacc, wacc), None

    # ---- drain: t in [M+S-1, M+2S-2) — only B-slots can be live ---------
    def drain_tick(carry, t):
        fin, dh, ring, dx, gacc, hacc, lacc, wacc = carry
        bcot = jnp.roll(dh, -1, axis=0)
        dh, dx, gacc = b_cell(bcot, ring, dx, gacc, t)
        return (fin, dh, ring, dx, gacc, hacc, lacc, wacc), None

    carry, _ = jax.lax.scan(fill_tick, carry0, jnp.arange(S - 1))
    carry, _ = jax.lax.scan(steady_tick, carry, jnp.arange(S - 1, M + S - 1))
    carry, _ = jax.lax.scan(drain_tick, carry,
                            jnp.arange(M + S - 1, M + 2 * S - 2))
    (_, _, _, dx, gacc, hacc, lacc, wacc) = carry
    inv_w = 1.0 / jnp.maximum(wacc, 1e-9)
    scale = lambda t: jax.tree.map(lambda x: x * inv_w, t)
    if return_dx:
        return lacc * inv_w, scale(gacc), scale(hacc), dx * inv_w
    return lacc * inv_w, scale(gacc), scale(hacc)


def schedule_ticks(num_stages: int, num_microbatches: int) -> dict:
    """Per-phase tick counts of ``pipeline_1f1b`` — the bubble math.

    fill and drain each cost only ONE slot (tF resp. tB), so the bubble is
    (S-1)(tF+tB) — the reference 1F1B's (S-1), not the 2(S-1) of a
    uniform-tick lockstep loop."""
    S, M = num_stages, num_microbatches
    return {"fill": S - 1, "steady": M, "drain": S - 1,
            "total": M + 2 * (S - 1),
            "bubble_slot_pairs": S - 1}


def pipeline_interleaved(stage_fn: Callable, stacked_params, x_mb, *,
                         num_stages: int, num_chunks: int,
                         remat: bool = True):
    """Circular (interleaved/VPP) pipeline forward. Differentiable.

    stage_fn(params_slice, h) -> h                   ONE chunk's compute
    stacked_params: pytree, leaves [V, S, ...]; chunk v on stage s is the
        virtual stage v*S + s (Megatron VPP placement).
    x_mb: [M, mb, ...] with M a multiple of S.

    Returns [M, mb, ...] outputs of the last virtual stage.
    """
    S, V = num_stages, num_chunks
    M = x_mb.shape[0]
    if M % S:
        raise ValueError(f"interleaved schedule needs microbatches ({M}) "
                         f"divisible by num_stages ({S})")
    fwd = jax.checkpoint(stage_fn) if remat else stage_fn
    sidx = jnp.arange(S)
    G = V * S                      # ticks one group occupies per stage
    # [V, S, ...] -> [S, V, ...] so the per-stage chunk gather is leading
    p_sv = jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), stacked_params)

    def chunk_params(P, v):
        # per-stage gather of chunk v_s: P [S, V, ...] -> [S, ...]
        return jax.vmap(
            lambda Ps, vi: jax.lax.dynamic_index_in_dim(
                Ps, vi, 0, keepdims=False))(P, v)

    def tick(carry, t):
        h, outs = carry
        u = t - sidx                                     # local time [S]
        r = jnp.mod(u, G)
        v = jnp.clip(r // S, 0, V - 1)                   # chunk per stage
        valid = (u >= 0) & (u < M * V)
        # inject at stage 0 when it starts chunk 0 of a new microbatch
        r0 = jnp.mod(t, G)
        inj_m = (t // G) * S + r0
        do_inj = (r0 < S) & (inj_m < M)
        inj = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(inj_m, 0, M - 1), 0, keepdims=False)
        h = h.at[0].set(jnp.where(do_inj, inj, h[0]))
        pv = jax.tree.map(lambda P: chunk_params(P, v), p_sv)
        out = jax.vmap(fwd)(pv, h)
        # mask invalid lanes so garbage never propagates into live ones
        out = jnp.where(valid.reshape((S,) + (1,) * (out.ndim - 1)), out, h)
        # drain stage S-1 when it finishes chunk V-1
        uS = t - (S - 1)
        rS = jnp.mod(uS, G)
        m_d = (uS // G) * S + (rS - (V - 1) * S)
        do_d = (uS >= 0) & (rS >= (V - 1) * S) & (m_d < M)
        m_dc = jnp.clip(m_d, 0, M - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, m_dc, 0, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(do_d, out[-1], prev), m_dc, 0)
        h = jnp.roll(out, 1, axis=0)   # wraps S-1 -> 0: chunk v -> v+1
        return (h, outs), None

    h0 = jnp.zeros((S,) + x_mb.shape[1:], x_mb.dtype)
    outs0 = jnp.zeros_like(x_mb)
    T = M * V + S - 1
    (_, outs), _ = jax.lax.scan(tick, (h0, outs0), jnp.arange(T))
    return outs


def interleaved_ticks(num_stages: int, num_chunks: int,
                      num_microbatches: int) -> Tuple[int, int]:
    """(ticks, non_interleaved_chunk_ticks) — the bubble-reduction math."""
    t = num_microbatches * num_chunks + num_stages - 1
    t_plain = (num_microbatches + num_stages - 1) * num_chunks
    return t, t_plain


__all__ = ["pipeline_1f1b", "pipeline_interleaved", "interleaved_ticks",
           "schedule_ticks"]
