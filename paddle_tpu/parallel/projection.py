"""North-star performance projection: Llama-3-8B pretrain on TPU v5p-64.

BASELINE.json's metric is "Llama-3-8B pretrain >= 40% MFU on v5p-64" — a
configuration this environment cannot run (one tunneled v5e chip). Round-4's
verdict required the projection be DERIVED from measurements instead of
asserted: every input here is either measured on-chip at the real 8B layer
shapes (tools/bench_8b_layer.py) or a cited public hardware constant, and
the combining math is this module, recomputed by tests/test_projection.py
against the committed artifact.

Reference analogue: the reference has no projection machinery (it publishes
no numbers at all, BASELINE.md); its closest relative is the auto-tuner's
cost model (python/paddle/distributed/auto_tuner/prune.py). This module is
the TPU-side counterpart built on measured per-layer times + the 1F1B
bubble math (parallel/schedules.py:268) + the FSDP comm model of the
scaling playbook (jax-ml.github.io/scaling-book: compute/comm roofline per
mesh axis).

Hardware constants (public specs):
- v5e peak bf16 197 TFLOP/s, HBM 16 GB @ 819 GB/s   (cloud.google.com/tpu/docs/v5e)
- v5p peak bf16 459 TFLOP/s, HBM 95 GB @ 2765 GB/s  (cloud.google.com/tpu/docs/v5p)
- v5p ICI 4800 Gbit/s/chip aggregate (600 GB/s)      (Google TPU v5p launch spec)

The projection is CONSERVATIVE in three places:
1. kernel efficiency is assumed to TRANSFER at a 10% penalty
   (``xfer_derate``) even though v5p has MORE HBM bandwidth per flop than
   v5e (2765/459 = 6.0 B/flop vs 819/197 = 4.2 B/flop), so memory-bound
   fractions shrink on v5p;
2. ICI is used at 50% of spec (``ici_efficiency``);
3. collectives are only overlapped against the SAME layer's compute
   (max(0, t_comm - t_compute) exposes the remainder), although XLA's
   latency-hiding scheduler can prefetch across layers.
"""

from __future__ import annotations

from typing import Dict

PEAK_BF16 = {"v5e": 197e12, "v5p": 459e12}
HBM_BW = {"v5e": 819e9, "v5p": 2765e9}          # bytes/s
ICI_AGG = {"v5p": 600e9}                        # bytes/s per chip, aggregate


def _llama_counts(v, h, m, L, n_h, n_kv, hd, seq_len) -> Dict[str, float]:
    """Shared analytic accounting (matches LlamaForCausalLM's own
    num_params()/flops_per_token() — asserted by tests/test_projection)."""
    layer = (h * (n_h + 2 * n_kv) * hd      # fused qkv
             + n_h * hd * h                 # o
             + h * 2 * m                    # fused gate+up
             + m * h                        # down
             + 2 * h)                       # 2 rms norms
    params = L * layer + 2 * v * h + h      # + embed + lm_head + final norm
    n_matmul = params - v * h               # embedding table is gather-only
    attn = 12 * L * h * seq_len             # PaLM convention, non-causal
    return {"params": params, "layer_params": layer,
            "flops_per_token": 6 * n_matmul + attn,
            "flops_per_token_causal": 6 * n_matmul
            + attn * (seq_len + 1) / (2 * seq_len),
            "layer_flops_per_token": 6 * layer + attn / L,
            "head_flops_per_token": 6 * v * h,
            "vocab": v, "hidden": h, "num_layers": L,
            "seq_len": seq_len}


def _fsdp_roofline(c, t_layer, t_head, t_embed, n_chips, ici_efficiency):
    """Shared fsdp-axis comm/optimizer roofline: per-layer 2xAG + RS of
    bf16 weights overlapped against the SAME layer's compute, the two
    v*h tables likewise against head+embed, HBM-bound optimizer update.
    Returns (t_step, parts dict)."""
    L = c["num_layers"]
    ici = ICI_AGG["v5p"] * ici_efficiency
    layer_bytes = c["layer_params"] * 2
    ag_rs = 3 * layer_bytes * (n_chips - 1) / n_chips
    t_comm_layer = ag_rs / ici
    exposed = max(0.0, t_comm_layer - t_layer)
    head_embed_bytes = 3 * (2 * c["vocab"] * c["hidden"] * 2) \
        * (n_chips - 1) / n_chips
    exposed_he = max(0.0, head_embed_bytes / ici - (t_head + t_embed))
    opt_bytes = c["params"] / n_chips * 16 * 2
    t_opt = opt_bytes / HBM_BW["v5p"]
    t_step = L * (t_layer + exposed) + t_head + t_embed + exposed_he + t_opt
    return t_step, {"t_comm_layer_s": t_comm_layer,
                    "t_comm_exposed_per_layer_s": exposed,
                    "t_opt_s": t_opt}


def llama3_8b_counts(seq_len: int = 8192) -> Dict[str, float]:
    """Analytic parameter/FLOP accounting for Llama-3-8B (no weights)."""
    return _llama_counts(128256, 4096, 14336, 32, 32, 8, 128, seq_len)


def project_llama3_8b_v5p64(measured: Dict[str, float], *,
                            n_chips: int = 64,
                            seq_len: int = 8192,
                            microbatch: int = 1,
                            xfer_derate: float = 1.10,
                            ici_efficiency: float = 0.5) -> Dict:
    """Project v5p-64 Llama-3-8B step time + MFU from v5e measurements.

    ``measured`` (from tools/bench_8b_layer.py, all on v5e, b=1, s=8192,
    bf16, flash kernel):
      layer_us           one decoder layer fwd+bwd, no remat
      layer_remat_us     same under jax.checkpoint (for the 1F1B plan)
      head_us_per_token  lm_head matmul + fp32 CE fwd+bwd, per token
      embed_us           embedding gather fwd+bwd at s=8192

    Plan A (headline): pure FSDP over all 64 chips (ZeRO-3 layout the
    model's GSPMD annotations already express), local batch 1x8192, no
    remat — the plan parallel/scale.py shows fits v5p HBM with room.
    Plan B (alternative): pp=8 x fsdp=8 1F1B with full remat, bubble from
    schedule_ticks.
    """
    c = llama3_8b_counts(seq_len)
    peak_ratio = PEAK_BF16["v5e"] / PEAK_BF16["v5p"]
    tokens = microbatch * seq_len

    # --- compute times scaled v5e -> v5p (assumption 1) ---
    t_layer = measured["layer_us"] * 1e-6 * peak_ratio * xfer_derate
    t_layer_remat = (measured["layer_remat_us"] * 1e-6 * peak_ratio
                     * xfer_derate)
    t_head = (measured["head_us_per_token"] * 1e-6 * tokens * peak_ratio
              * xfer_derate)
    t_embed = measured["embed_us"] * 1e-6 * peak_ratio * xfer_derate

    L = 32
    ici = ICI_AGG["v5p"] * ici_efficiency

    # --- plan A: fsdp=64 (shared roofline: per-layer 2xAG + RS
    # overlapped same-layer, assumption 3) ---
    t_step_a, parts_a = _fsdp_roofline(c, t_layer, t_head, t_embed,
                                       n_chips, ici_efficiency)
    t_comm_layer = parts_a["t_comm_layer_s"]
    exposed = parts_a["t_comm_exposed_per_layer_s"]
    t_opt = parts_a["t_opt_s"]
    mfu_a = tokens * c["flops_per_token"] / (t_step_a * PEAK_BF16["v5p"])

    # --- plan B: pp=8 x fsdp=8, 1F1B, full remat, M=2*S microbatches ---
    # Each microbatch is 8192 tokens per chip of its fsdp-8 group (global
    # microbatch 8x8192). 1F1B wall time = (M + S - 1) fwd+bwd slot pairs
    # of the slowest stage (schedule_ticks: fill/drain add S-1 pairs to
    # the M steady ticks); the last stage is slowest (its 4 layers + the
    # CE head every microbatch).
    S, M = 8, 16
    layers_per_stage = L // S
    from .schedules import schedule_ticks
    ticks = schedule_ticks(S, M)
    slot_pairs = ticks["steady"] + ticks["bubble_slot_pairs"]  # M + S - 1
    t_tick = layers_per_stage * t_layer_remat + t_head + t_embed
    # fsdp=8 comm inside the stage group, overlapped per layer as in plan A
    ag_rs8 = 3 * (c["layer_params"] * 2) * 7 / 8
    exposed8 = max(0.0, ag_rs8 / ici - t_layer_remat)
    t_step_b = slot_pairs * t_tick + M * layers_per_stage * exposed8 + t_opt
    tokens_b = M * 8 * tokens          # M microbatches x fsdp-8 x 8192
    # MFU = total executed model flops / (wall time * all chips * peak)
    mfu_b = (tokens_b * c["flops_per_token"]
             / (t_step_b * n_chips * PEAK_BF16["v5p"]))

    return {
        "counts": c,
        "inputs": dict(measured),
        "assumptions": {
            "peak_bf16_v5e": PEAK_BF16["v5e"],
            "peak_bf16_v5p": PEAK_BF16["v5p"],
            "hbm_bw_v5p": HBM_BW["v5p"],
            "ici_aggregate_v5p": ICI_AGG["v5p"],
            "ici_efficiency": ici_efficiency,
            "xfer_derate": xfer_derate,
            "overlap": "collectives overlap same-layer compute only",
            "sources": [
                "cloud.google.com/tpu/docs/v5e (197 TF bf16, 819 GB/s HBM)",
                "cloud.google.com/tpu/docs/v5p (459 TF bf16, 95 GB, 2765 GB/s)",
                "TPU v5p launch spec: 4800 Gbps ICI per chip",
                "jax-ml.github.io/scaling-book (FSDP comm roofline model)",
            ],
        },
        "plan_a_fsdp64": {
            "mesh": {"fsdp": 64},
            "local_batch": [microbatch, seq_len],
            "t_layer_v5p_s": t_layer,
            "t_comm_layer_s": t_comm_layer,
            "t_comm_exposed_per_layer_s": exposed,
            "t_head_s": t_head,
            "t_opt_s": t_opt,
            "t_step_s": t_step_a,
            "tokens_per_step_per_chip": tokens,
            "projected_mfu": mfu_a,
            "projected_tokens_per_sec_per_chip": tokens / t_step_a,
        },
        "plan_b_pp8_fsdp8_1f1b": {
            "mesh": {"pp": 8, "fsdp": 8},
            "microbatches": M,
            "bubble_slot_pairs": ticks["bubble_slot_pairs"],
            "t_step_s": t_step_b,
            "projected_mfu": mfu_b,
        },
        "north_star": {
            "target_mfu": 0.40,
            "meets_target": bool(mfu_a >= 0.40),
            "headline_plan": "plan_a_fsdp64",
        },
    }


def llama3_70b_counts(seq_len: int = 8192) -> Dict[str, float]:
    """Analytic accounting for Llama-3-70B (h=8192, ffn=28672, 80 layers,
    64/8 GQA heads, vocab 128256) — same conventions as the 8B counts."""
    return _llama_counts(128256, 8192, 28672, 80, 64, 8, 128, seq_len)


def project_llama3_70b_v5p64(measured: Dict[str, float], *,
                             n_chips: int = 64,
                             seq_len: int = 8192,
                             microbatch: int = 1,
                             xfer_derate: float = 1.10,
                             ici_efficiency: float = 0.5) -> Dict:
    """Project v5p-64 Llama-3-70B pretraining from v5e measurements.

    ``measured`` (tools/bench_8b_layer.py --config llama3_70b; the layer
    is measured at a SHORTER sequence and scaled: per-token layer cost =
    matmul part (seq-independent) + attention part (linear in s under
    the causal kernel's per-token average)):
      layer_remat_us     one 70B layer fwd+bwd UNDER jax.checkpoint at
                         ``layer_seq`` tokens (70B on v5p-64 needs full
                         remat — parallel/scale.py: no-remat activations
                         are ~2.3 GB/layer x 80 at s=8192)
      layer_seq          the sequence length the layer was measured at
      head_us_per_token  lm_head + fp32 CE slope at vocab=128256, h=8192
      embed_us           embedding fwd+bwd (at layer_seq; amortized)

    Plan: fsdp=64 (params/grads/opt 70e9*16/64 = 17.5 GB/chip), full
    remat, local batch 1 x seq_len. Same conservative assumptions as the
    8B projection (cited peaks, ICI at 50%, same-layer-only overlap)."""
    c = llama3_70b_counts(seq_len)
    peak_ratio = PEAK_BF16["v5e"] / PEAK_BF16["v5p"]
    tokens = microbatch * seq_len

    # split the measured layer time into seq-independent matmul work and
    # seq-scaled attention work, then rebuild at the target seq_len
    ls = int(measured["layer_seq"])
    c_ls = llama3_70b_counts(ls)
    # conservative guard: a grad-of-checkpoint microbench can measure
    # FASTER than the plain layer (XLA DCEs part of the re-forward);
    # real remat is never cheaper, so take the slower of the two
    t_meas = max(measured["layer_remat_us"],
                 measured.get("layer_us", 0.0)) * 1e-6
    attn_frac = (c_ls["layer_flops_per_token"] - 6 * c_ls["layer_params"]) \
        / c_ls["layer_flops_per_token"]
    t_matmul_tok = t_meas * (1 - attn_frac) / ls
    t_attn_tok_ls = t_meas * attn_frac / ls          # at avg ctx ls/2
    t_layer = (t_matmul_tok + t_attn_tok_ls * (seq_len / ls)) * tokens \
        * peak_ratio * xfer_derate
    t_head = (measured["head_us_per_token"] * 1e-6 * tokens * peak_ratio
              * xfer_derate)
    t_embed = measured["embed_us"] * 1e-6 * peak_ratio * xfer_derate

    t_step, parts = _fsdp_roofline(c, t_layer, t_head, t_embed,
                                   n_chips, ici_efficiency)
    exposed = parts["t_comm_exposed_per_layer_s"]
    t_opt = parts["t_opt_s"]
    mfu = tokens * c["flops_per_token"] / (t_step * PEAK_BF16["v5p"])
    return {
        "counts": c,
        "inputs": dict(measured),
        "assumptions": {
            "peak_bf16_v5e": PEAK_BF16["v5e"],
            "peak_bf16_v5p": PEAK_BF16["v5p"],
            "ici_aggregate_v5p": ICI_AGG["v5p"],
            "ici_efficiency": ici_efficiency,
            "xfer_derate": xfer_derate,
            "seq_scaling": "matmul part seq-independent; attention part "
                           "linear in s (causal per-token average). The "
                           "time split weights attention NON-causally — "
                           "conservative: over-attributes measured time "
                           "to the part that grows with s",
            "plan": "fsdp=64, full remat, local batch 1 x seq_len",
        },
        "plan_fsdp64_remat": {
            "mesh": {"fsdp": 64},
            "t_layer_v5p_s": t_layer,
            "t_comm_exposed_per_layer_s": exposed,
            "t_head_s": t_head,
            "t_opt_s": t_opt,
            "t_step_s": t_step,
            "tokens_per_step_per_chip": tokens,
            "projected_mfu": mfu,
            "projected_tokens_per_sec_per_chip": tokens / t_step,
        },
        "north_star": {"target_mfu": 0.40,
                       "meets_target": bool(mfu >= 0.40)},
    }


__all__ = ["llama3_8b_counts", "llama3_70b_counts",
           "project_llama3_8b_v5p64", "project_llama3_70b_v5p64",
           "PEAK_BF16", "HBM_BW", "ICI_AGG"]
