"""North-star performance projection: Llama-3-8B pretrain on TPU v5p-64.

BASELINE.json's metric is "Llama-3-8B pretrain >= 40% MFU on v5p-64" — a
configuration this environment cannot run (one tunneled v5e chip). Round-4's
verdict required the projection be DERIVED from measurements instead of
asserted: every input here is either measured on-chip at the real 8B layer
shapes (tools/bench_8b_layer.py) or a cited public hardware constant, and
the combining math is this module, recomputed by tests/test_projection.py
against the committed artifact.

Reference analogue: the reference has no projection machinery (it publishes
no numbers at all, BASELINE.md); its closest relative is the auto-tuner's
cost model (python/paddle/distributed/auto_tuner/prune.py). This module is
the TPU-side counterpart built on measured per-layer times + the 1F1B
bubble math (parallel/schedules.py:268) + the FSDP comm model of the
scaling playbook (jax-ml.github.io/scaling-book: compute/comm roofline per
mesh axis).

Hardware constants (public specs):
- v5e peak bf16 197 TFLOP/s, HBM 16 GB @ 819 GB/s   (cloud.google.com/tpu/docs/v5e)
- v5p peak bf16 459 TFLOP/s, HBM 95 GB @ 2765 GB/s  (cloud.google.com/tpu/docs/v5p)
- v5p ICI 4800 Gbit/s/chip aggregate (600 GB/s)      (Google TPU v5p launch spec)

The projection is CONSERVATIVE in three places:
1. kernel efficiency is assumed to TRANSFER at a 10% penalty
   (``xfer_derate``) even though v5p has MORE HBM bandwidth per flop than
   v5e (2765/459 = 6.0 B/flop vs 819/197 = 4.2 B/flop), so memory-bound
   fractions shrink on v5p;
2. ICI is used at 50% of spec (``ici_efficiency``);
3. collectives are only overlapped against the SAME layer's compute
   (max(0, t_comm - t_compute) exposes the remainder), although XLA's
   latency-hiding scheduler can prefetch across layers.
"""

from __future__ import annotations

from typing import Dict

PEAK_BF16 = {"v5e": 197e12, "v5p": 459e12}
HBM_BW = {"v5e": 819e9, "v5p": 2765e9}          # bytes/s
ICI_AGG = {"v5p": 600e9}                        # bytes/s per chip, aggregate


def llama3_8b_counts(seq_len: int = 8192) -> Dict[str, float]:
    """Analytic parameter/FLOP accounting for Llama-3-8B (no weights).

    Matches LlamaForCausalLM.num_params()/flops_per_token() for
    LlamaConfig.llama3_8b() — asserted by tests/test_projection.py."""
    v, h, m, L = 128256, 4096, 14336, 32
    n_h, n_kv, hd = 32, 8, 128
    layer = (h * (n_h + 2 * n_kv) * hd      # fused qkv
             + n_h * hd * h                 # o
             + h * 2 * m                    # fused gate+up
             + m * h                        # down
             + 2 * h)                       # 2 rms norms
    params = L * layer + 2 * v * h + h      # + embed + lm_head + final norm
    n_matmul = params - v * h               # embedding table is gather-only
    attn = 12 * L * h * seq_len             # PaLM convention, non-causal
    return {"params": params, "layer_params": layer,
            "flops_per_token": 6 * n_matmul + attn,
            "flops_per_token_causal": 6 * n_matmul
            + attn * (seq_len + 1) / (2 * seq_len),
            "layer_flops_per_token": 6 * layer + attn / L,
            "head_flops_per_token": 6 * v * h,
            "seq_len": seq_len}


def project_llama3_8b_v5p64(measured: Dict[str, float], *,
                            n_chips: int = 64,
                            seq_len: int = 8192,
                            microbatch: int = 1,
                            xfer_derate: float = 1.10,
                            ici_efficiency: float = 0.5) -> Dict:
    """Project v5p-64 Llama-3-8B step time + MFU from v5e measurements.

    ``measured`` (from tools/bench_8b_layer.py, all on v5e, b=1, s=8192,
    bf16, flash kernel):
      layer_us           one decoder layer fwd+bwd, no remat
      layer_remat_us     same under jax.checkpoint (for the 1F1B plan)
      head_us_per_token  lm_head matmul + fp32 CE fwd+bwd, per token
      embed_us           embedding gather fwd+bwd at s=8192

    Plan A (headline): pure FSDP over all 64 chips (ZeRO-3 layout the
    model's GSPMD annotations already express), local batch 1x8192, no
    remat — the plan parallel/scale.py shows fits v5p HBM with room.
    Plan B (alternative): pp=8 x fsdp=8 1F1B with full remat, bubble from
    schedule_ticks.
    """
    c = llama3_8b_counts(seq_len)
    peak_ratio = PEAK_BF16["v5e"] / PEAK_BF16["v5p"]
    tokens = microbatch * seq_len

    # --- compute times scaled v5e -> v5p (assumption 1) ---
    t_layer = measured["layer_us"] * 1e-6 * peak_ratio * xfer_derate
    t_layer_remat = (measured["layer_remat_us"] * 1e-6 * peak_ratio
                     * xfer_derate)
    t_head = (measured["head_us_per_token"] * 1e-6 * tokens * peak_ratio
              * xfer_derate)
    t_embed = measured["embed_us"] * 1e-6 * peak_ratio * xfer_derate

    L = 32
    ici = ICI_AGG["v5p"] * ici_efficiency

    # --- plan A: fsdp=64 ---
    # per-layer collectives (bf16): all-gather params in fwd, all-gather
    # again in bwd (ZeRO-3 re-gather), reduce-scatter grads — each moves
    # (n-1)/n of the layer's bytes through each chip's ICI.
    layer_bytes = c["layer_params"] * 2
    ag_rs = 3 * layer_bytes * (n_chips - 1) / n_chips
    t_comm_layer = ag_rs / ici
    exposed = max(0.0, t_comm_layer - t_layer)      # assumption 3
    # lm_head + embedding tables get the same 2xAG + RS treatment
    # (8B is untied: two v*h tables)
    head_embed_bytes = 3 * (2 * 128256 * 4096 * 2) * (n_chips - 1) / n_chips
    t_comm_he = head_embed_bytes / ici
    exposed_he = max(0.0, t_comm_he - (t_head + t_embed))
    # optimizer update: HBM-bound read+write of fp32 master+m+v (12B) +
    # bf16 param+grad (4B) per local param
    opt_bytes = c["params"] / n_chips * 16 * 2
    t_opt = opt_bytes / HBM_BW["v5p"]

    t_step_a = (L * (t_layer + exposed) + t_head + t_embed + exposed_he
                + t_opt)
    mfu_a = tokens * c["flops_per_token"] / (t_step_a * PEAK_BF16["v5p"])

    # --- plan B: pp=8 x fsdp=8, 1F1B, full remat, M=2*S microbatches ---
    # Each microbatch is 8192 tokens per chip of its fsdp-8 group (global
    # microbatch 8x8192). 1F1B wall time = (M + S - 1) fwd+bwd slot pairs
    # of the slowest stage (schedule_ticks: fill/drain add S-1 pairs to
    # the M steady ticks); the last stage is slowest (its 4 layers + the
    # CE head every microbatch).
    S, M = 8, 16
    layers_per_stage = L // S
    from .schedules import schedule_ticks
    ticks = schedule_ticks(S, M)
    slot_pairs = ticks["steady"] + ticks["bubble_slot_pairs"]  # M + S - 1
    t_tick = layers_per_stage * t_layer_remat + t_head + t_embed
    # fsdp=8 comm inside the stage group, overlapped per layer as in plan A
    ag_rs8 = 3 * layer_bytes * 7 / 8
    exposed8 = max(0.0, ag_rs8 / ici - t_layer_remat)
    t_step_b = slot_pairs * t_tick + M * layers_per_stage * exposed8 + t_opt
    tokens_b = M * 8 * tokens          # M microbatches x fsdp-8 x 8192
    # MFU = total executed model flops / (wall time * all chips * peak)
    mfu_b = (tokens_b * c["flops_per_token"]
             / (t_step_b * n_chips * PEAK_BF16["v5p"]))

    return {
        "counts": c,
        "inputs": dict(measured),
        "assumptions": {
            "peak_bf16_v5e": PEAK_BF16["v5e"],
            "peak_bf16_v5p": PEAK_BF16["v5p"],
            "hbm_bw_v5p": HBM_BW["v5p"],
            "ici_aggregate_v5p": ICI_AGG["v5p"],
            "ici_efficiency": ici_efficiency,
            "xfer_derate": xfer_derate,
            "overlap": "collectives overlap same-layer compute only",
            "sources": [
                "cloud.google.com/tpu/docs/v5e (197 TF bf16, 819 GB/s HBM)",
                "cloud.google.com/tpu/docs/v5p (459 TF bf16, 95 GB, 2765 GB/s)",
                "TPU v5p launch spec: 4800 Gbps ICI per chip",
                "jax-ml.github.io/scaling-book (FSDP comm roofline model)",
            ],
        },
        "plan_a_fsdp64": {
            "mesh": {"fsdp": 64},
            "local_batch": [microbatch, seq_len],
            "t_layer_v5p_s": t_layer,
            "t_comm_layer_s": t_comm_layer,
            "t_comm_exposed_per_layer_s": exposed,
            "t_head_s": t_head,
            "t_opt_s": t_opt,
            "t_step_s": t_step_a,
            "tokens_per_step_per_chip": tokens,
            "projected_mfu": mfu_a,
            "projected_tokens_per_sec_per_chip": tokens / t_step_a,
        },
        "plan_b_pp8_fsdp8_1f1b": {
            "mesh": {"pp": 8, "fsdp": 8},
            "microbatches": M,
            "bubble_slot_pairs": ticks["bubble_slot_pairs"],
            "t_step_s": t_step_b,
            "projected_mfu": mfu_b,
        },
        "north_star": {
            "target_mfu": 0.40,
            "meets_target": bool(mfu_a >= 0.40),
            "headline_plan": "plan_a_fsdp64",
        },
    }


__all__ = ["llama3_8b_counts", "project_llama3_8b_v5p64", "PEAK_BF16",
           "HBM_BW", "ICI_AGG"]
