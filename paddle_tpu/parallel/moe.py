"""Mixture-of-Experts layer with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
(MoELayer: gate → count_by_gate → MoEScatter(global_scatter all-to-all) →
per-expert FFN loop → MoEGather), gates under moe/gate/{naive,gshard,switch}
_gate.py, kernels paddle/fluid/operators/collective/global_scatter_op.cu.

TPU-native redesign, round 3 (SURVEY.md A.2 translation): the reference's
index-select + ragged all-to-all becomes a SORT-BASED dispatch — token
assignments are sorted by expert id (one XLA sort of t*k int32 keys), each
assignment's slot in the [experts, capacity, d] layout is its rank within
its expert's run, and dispatch/combine are pure GATHERS through a slot
index. Routing memory is O(t·k + e·c·d): the round-2 one-hot GShard
[t, e, c] dispatch/combine tensors (O(t·e·c) — OOM at DeepSeekMoE's 64+
experts) are gone. The experts still run as ONE batched einsum on the MXU.

Dropless mode (``capacity_factor=None``): no token is ever dropped — the
sorted assignments feed a grouped matmul over per-expert group sizes, the
TPU analogue of the reference's exact-count global_scatter path
(moe/utils.py count_by_gate). Round-5 on-chip A/B at DeepSeekMoE scale
(e=64, d=2048, f=1408, k=6, v5e): XLA's native ``lax.ragged_dot`` runs the
same grouped matmul 1.7x faster than the bundled megablox Pallas gmm with
bit-identical output, so ragged_dot is the primary path (gmm remains the
fallback for jax builds without ragged_dot); the capacity-factor dense
path is ~4x faster still at this scale but DROPS overflow tokens — the
measured trade is recorded in ops/pallas/tune_db.json (moe_grouped_mm).

Expert weights are sharded over the ("dp","fsdp") submesh — the "ep" axis
aliases the data-parallel devices the way the reference reuses comm groups
(HybridMesh.build's ep degree) — and the dispatched [e, c, d] tensor is
sharding-constrained to the same axes, so GSPMD materializes the
global_scatter/global_gather all-to-alls between the token-sharded and
expert-sharded layouts.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from .mesh import current_mesh


def _aux_loss(probs, e):
    """GShard eq.4 load-balance loss: e * sum_e(mean_t(gate) * mean_t(frac))."""
    top1 = jnp.argmax(probs, axis=-1)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0)
    return jnp.sum(me * ce) * e


def top_k_routing(gate_logits, k: int, capacity: int,
                  jitter_eps: float = 0.0, key=None):
    """Sort-based top-k routing with capacity.

    Returns (slot [t, k] int32, gates [t, k] f32, aux_loss scalar):
    ``slot[i, j]`` is the flat position of token i's j-th assignment in the
    [e * capacity] expert-slot space, or e*capacity when the assignment was
    dropped (its expert full). Capacity priority is choice-major (every
    token's 1st choice outranks any 2nd choice), token-ascending — the
    fill-counter semantics of the reference's limit_by_capacity
    (moe/utils.py:74) without materializing anything O(t·e).
    """
    t, e = gate_logits.shape
    gate_logits = gate_logits.astype(jnp.float32)
    if jitter_eps > 0.0 and key is not None:
        noise = jax.random.uniform(key, gate_logits.shape, jnp.float32,
                                   1.0 - jitter_eps, 1.0 + jitter_eps)
        gate_logits = gate_logits * noise
    probs = jax.nn.softmax(gate_logits, axis=-1)              # [t, e]
    gates, ids = jax.lax.top_k(probs, k)                      # [t, k]

    # choice-major assignment stream: all 1st choices (token asc), then all
    # 2nd choices, ... — the stable sort by expert then ranks assignments
    # within each expert in exactly that priority order
    flat_e = ids.T.reshape(-1)                                # [k*t]
    order = jnp.argsort(flat_e, stable=True)
    sorted_e = flat_e[order]
    starts = jnp.searchsorted(sorted_e, jnp.arange(e))        # [e]
    pos = jnp.arange(k * t, dtype=jnp.int32) - starts[sorted_e]
    keep = pos < capacity
    slot_sorted = jnp.where(keep, sorted_e * capacity + pos,
                            e * capacity).astype(jnp.int32)
    # scatter slots back to choice-major stream order, then to [t, k]
    slot_cm = jnp.zeros((k * t,), jnp.int32).at[order].set(slot_sorted)
    slot = slot_cm.reshape(k, t).T                            # [t, k]
    return slot, gates, _aux_loss(probs, e)


def dispatch_tokens(flat, slot, num_experts: int, capacity: int):
    """Gather tokens into the dense [e, c, d] expert layout (empty slots
    zero). flat: [t, d]; slot: [t, k] from top_k_routing."""
    t, d = flat.shape
    k = slot.shape[1]
    ec = num_experts * capacity
    # slot -> token index (choice-major flatten matches top_k_routing)
    slot_token = jnp.full((ec + 1,), t, jnp.int32)
    slot_token = slot_token.at[slot.T.reshape(-1)].set(
        jnp.tile(jnp.arange(t, dtype=jnp.int32), k), mode="drop")
    padded = jnp.concatenate([flat, jnp.zeros((1, d), flat.dtype)])
    return padded[slot_token[:ec]].reshape(num_experts, capacity, d)


def combine_tokens(ye, slot, gates, renormalize: bool):
    """Weighted gather back to tokens. ye: [e, c, d]; slot/gates: [t, k].
    Dropped assignments (slot == e*c) contribute zero."""
    e, c, d = ye.shape
    padded = jnp.concatenate(
        [ye.reshape(e * c, d),
         jnp.zeros((1, d), ye.dtype)])                        # trash row
    y = padded[slot]                                          # [t, k, d]
    kept = (slot < e * c).astype(gates.dtype)
    g = gates * kept
    if renormalize:
        g = g / jnp.maximum(jnp.sum(g, axis=-1, keepdims=True), 1e-9)
    return jnp.sum(g[..., None].astype(y.dtype) * y, axis=1)  # [t, d]


# -- legacy one-hot formulation kept as the parity oracle --------------------

def top_k_gating(gate_logits, k: int, capacity: int,
                 jitter_eps: float = 0.0, key=None):
    """GShard one-hot gating (dispatch [t,e,c] bool, combine [t,e,c] float,
    aux_loss). O(t·e·c) — superseded by top_k_routing for real configs;
    retained as the test oracle for the sort-based path."""
    t, e = gate_logits.shape
    gate_logits = gate_logits.astype(jnp.float32)
    if jitter_eps > 0.0 and key is not None:
        noise = jax.random.uniform(key, gate_logits.shape, jnp.float32,
                                   1.0 - jitter_eps, 1.0 + jitter_eps)
        gate_logits = gate_logits * noise
    probs = jax.nn.softmax(gate_logits, axis=-1)  # [t,e]
    aux_loss = _aux_loss(probs, e)

    combine = jnp.zeros((t, e, capacity), jnp.float32)
    dispatch = jnp.zeros((t, e, capacity), bool)
    remaining = probs
    fill = jnp.zeros((e,), jnp.int32)
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                      # [t]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)          # [t,e]
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1 + fill) * onehot
        pos = jnp.sum(pos_in_expert, axis=-1)                     # [t]
        fits = pos < capacity
        gate_val = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        pos_oh = jax.nn.one_hot(jnp.where(fits, pos, capacity), capacity,
                                dtype=jnp.float32)                # [t,c]
        contrib = (onehot.astype(jnp.float32)[:, :, None] * pos_oh[:, None, :])
        combine = combine + gate_val[:, None, None] * contrib * fits[:, None, None]
        dispatch = dispatch | (contrib > 0) & fits[:, None, None]
        fill = fill + jnp.sum(onehot * fits[:, None].astype(jnp.int32), axis=0)
        remaining = remaining * (1.0 - onehot.astype(jnp.float32))
    if k > 1:
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
    return dispatch, combine, aux_loss


class MoEMLP(Layer):
    """Experts as batched weights [E, ...] — one einsum, not a python loop."""

    def __init__(self, num_experts: int, hidden_size: int, ffn_size: int,
                 dtype=None):
        super().__init__()
        std = 0.02
        self.w_gate_up = self.create_parameter(
            [num_experts, hidden_size, 2 * ffn_size], dtype=dtype,
            initializer=I.Normal(0.0, std), sharding=(("dp", "fsdp"), None, "tp"))
        self.w_down = self.create_parameter(
            [num_experts, ffn_size, hidden_size], dtype=dtype,
            initializer=I.Normal(0.0, std), sharding=(("dp", "fsdp"), "tp", None))

    def forward(self, x):
        # x: [e, c, d] -> [e, c, d]
        gu = jnp.einsum("ecd,edf->ecf", x, self.w_gate_up.astype(x.dtype))
        g, u = jnp.split(gu, 2, axis=-1)
        h = F.silu(g) * u
        return jnp.einsum("ecf,efd->ecd", h, self.w_down.astype(x.dtype))


def _constrain_experts(xe):
    """Shard the [e, c, d] dispatched tensor's expert dim over the ep
    (= dp×fsdp) submesh — this boundary is where GSPMD emits the
    global_scatter/global_gather all-to-alls."""
    hm = current_mesh()
    if hm is None or not isinstance(xe, jax.core.Tracer):
        return xe
    axes = tuple(a for a in ("dp", "fsdp") if hm.axis_size(a) > 1)
    if not axes:
        return xe
    if xe.shape[0] % int(np.prod([hm.axis_size(a) for a in axes])) != 0:
        return xe
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.lax.with_sharding_constraint(
        xe, NamedSharding(hm.mesh, P(axes, *([P.UNCONSTRAINED] * (xe.ndim - 1)))))


def _grouped_matmul(xs, w, group_sizes):
    """Ragged grouped matmul: rows of ``xs`` [m, k] are split by
    ``group_sizes`` [g] and each run multiplies its own ``w[g] `` [k, n].

    lax.ragged_dot when this jax ships it (XLA-native; the round-5 v5e
    A/B measured it 1.7x faster than megablox gmm with max|diff|=0 at
    e=64, d=2048, f=1408); otherwise the bundled megablox Pallas kernel
    (interpret mode off-TPU)."""
    if hasattr(jax.lax, "ragged_dot"):
        return jax.lax.ragged_dot(xs, w, group_sizes,
                                  preferred_element_type=jnp.float32)
    from jax.experimental.pallas.ops.tpu.megablox import gmm
    from ..ops.registry import backend_kind

    def tiling(m, kk, n):
        # largest power-of-two tile <= 128 dividing each dim (gmm
        # requires exact tiling; real configs are 128-multiples, tiny
        # test shapes degrade gracefully)
        g_ = lambda x: math.gcd(x, 128)
        return (g_(m), g_(kk), g_(n))

    return gmm(xs, w, group_sizes, preferred_element_type=jnp.float32,
               tiling=tiling(xs.shape[0], w.shape[1], w.shape[2]),
               interpret=backend_kind() != "tpu")


class MoELayer(Layer):
    """Top-k routed MoE block (reference: MoELayer, moe_layer.py:263).

    forward(x: [b, s, d]) -> (out [b, s, d], aux_loss scalar)

    ``capacity_factor=None`` selects DROPLESS routing via grouped matmul
    (megablox gmm): exact per-expert counts, no token ever dropped.
    """

    def __init__(self, hidden_size: int, ffn_size: int, num_experts: int,
                 top_k: int = 2, capacity_factor: Optional[float] = 1.25,
                 dtype=None, gate: str = "gshard"):
        super().__init__()
        if top_k > num_experts:
            raise ValueError(f"top_k={top_k} > num_experts={num_experts}")
        self.num_experts = num_experts
        self.top_k = 1 if gate == "switch" else top_k
        self.capacity_factor = capacity_factor
        self.gate_weight = self.create_parameter(
            [hidden_size, num_experts], dtype="float32",
            initializer=I.Normal(0.0, 0.02))
        self.experts = MoEMLP(num_experts, hidden_size, ffn_size, dtype=dtype)

    def forward(self, x):
        b, s, d = x.shape
        t = b * s
        e = self.num_experts
        flat = x.reshape(t, d)
        logits = jnp.matmul(flat.astype(jnp.float32), self.gate_weight)

        if self.capacity_factor is None:
            out, aux = self._forward_dropless(flat, logits)
            return out.reshape(b, s, d), aux

        capacity = int(math.ceil(t * self.top_k / e * self.capacity_factor))
        slot, gates, aux = top_k_routing(logits, self.top_k, capacity)
        xe = dispatch_tokens(flat, slot, e, capacity)         # [e, c, d]
        xe = _constrain_experts(xe)
        ye = self.experts(xe)
        ye = _constrain_experts(ye)
        out = combine_tokens(ye, slot, gates,
                             renormalize=self.top_k > 1)
        return out.reshape(b, s, d), aux

    def _forward_dropless(self, flat, logits):
        """Grouped-matmul experts over exact per-expert counts — the
        dropless path (reference analogue: global_scatter's exact
        count_by_gate split sizes). Grouped matmul = lax.ragged_dot
        (XLA-native; measured 1.7x faster than megablox gmm at
        DeepSeekMoE-64 scale on v5e, identical numerics), megablox gmm
        as fallback."""
        t, d = flat.shape
        e, k = self.num_experts, self.top_k
        probs = jax.nn.softmax(logits, axis=-1)
        gates, ids = jax.lax.top_k(probs, k)                  # [t, k]
        flat_e = ids.T.reshape(-1)                            # [k*t]
        order = jnp.argsort(flat_e, stable=True)
        sorted_e = flat_e[order]
        group_sizes = jnp.bincount(sorted_e, length=e).astype(jnp.int32)
        xs = flat[order % t]                                  # [k*t, d]

        w_gu = self.experts.w_gate_up.astype(flat.dtype)      # [e, d, 2f]
        w_dn = self.experts.w_down.astype(flat.dtype)         # [e, f, d2]

        gu = _grouped_matmul(xs, w_gu, group_sizes).astype(flat.dtype)
        g, u = jnp.split(gu, 2, axis=-1)
        h = F.silu(g) * u
        ys = _grouped_matmul(h, w_dn, group_sizes).astype(flat.dtype)

        # unsort to choice-major, weight, reduce over k
        y_cm = jnp.zeros_like(ys).at[order].set(ys).reshape(k, t, d)
        g_km = gates.T                                        # [k, t]
        if k > 1:
            g_km = g_km / jnp.maximum(jnp.sum(g_km, 0, keepdims=True), 1e-9)
        out = jnp.sum(g_km[..., None].astype(ys.dtype) * y_cm, axis=0)
        return out, _aux_loss(probs, e)
