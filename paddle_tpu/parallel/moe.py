"""Mixture-of-Experts layer with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
(MoELayer: gate → count_by_gate → MoEScatter(global_scatter all-to-all) →
per-expert FFN loop → MoEGather), gates under moe/gate/{naive,gshard,switch}
_gate.py, kernels paddle/fluid/operators/collective/global_scatter_op.cu.

TPU-native redesign (SURVEY.md A.2 translation): instead of index-select +
ragged all-to-all + a python loop over experts, tokens are dispatched into a
dense [experts, capacity, d] layout with one-hot combine/dispatch tensors
(GShard formulation) and the experts run as ONE batched einsum on the MXU.
Expert weights are sharded over the ("dp","fsdp") submesh (expert parallel
reuses the data-parallel devices, as the reference reuses comm groups); the
all-to-all appears in the compiled program from GSPMD's resharding between
token-sharded and expert-sharded layouts.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from .mesh import current_mesh


def top_k_gating(gate_logits, k: int, capacity: int,
                 jitter_eps: float = 0.0, key=None):
    """GShard top-k gating with capacity. Returns (dispatch [t,e,c] bool,
    combine [t,e,c] float, aux_loss scalar).

    Reference: gshard_gate.py / switch_gate.py (k=1) + limit_by_capacity
    (moe/utils.py:74)."""
    t, e = gate_logits.shape
    gate_logits = gate_logits.astype(jnp.float32)
    if jitter_eps > 0.0 and key is not None:
        # GShard routing jitter (reference: gshard_gate.py noise on logits):
        # multiplicative uniform noise in [1-eps, 1+eps] for load-balance
        # exploration; disabled (deterministic) when no key is passed.
        noise = jax.random.uniform(key, gate_logits.shape, jnp.float32,
                                   1.0 - jitter_eps, 1.0 + jitter_eps)
        gate_logits = gate_logits * noise
    probs = jax.nn.softmax(gate_logits, axis=-1)  # [t,e]

    # aux load-balancing loss (GShard eq.4): e * sum_e(mean_t(gates) * mean_t(frac))
    top1 = jnp.argmax(probs, axis=-1)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0)
    aux_loss = jnp.sum(me * ce) * e

    combine = jnp.zeros((t, e, capacity), jnp.float32)
    dispatch = jnp.zeros((t, e, capacity), bool)
    remaining = probs
    # running per-expert fill count across the k choices
    fill = jnp.zeros((e,), jnp.int32)
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                      # [t]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)          # [t,e]
        # position of each token within its chosen expert's capacity
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1 + fill) * onehot  # [t,e]
        pos = jnp.sum(pos_in_expert, axis=-1)                     # [t]
        fits = pos < capacity
        gate_val = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        pos_oh = jax.nn.one_hot(jnp.where(fits, pos, capacity), capacity,
                                dtype=jnp.float32)                # [t,c]
        contrib = (onehot.astype(jnp.float32)[:, :, None] * pos_oh[:, None, :])
        combine = combine + gate_val[:, None, None] * contrib * fits[:, None, None]
        dispatch = dispatch | (contrib > 0) & fits[:, None, None]
        fill = fill + jnp.sum(onehot * fits[:, None].astype(jnp.int32), axis=0)
        remaining = remaining * (1.0 - onehot.astype(jnp.float32))
    if k > 1:
        # renormalize combine weights over the (non-dropped) selected experts;
        # k=1 (switch) keeps the raw gate prob as the multiplier so the router
        # receives gradient through the task loss (Switch-Transformer semantics)
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
    return dispatch, combine, aux_loss


class MoEMLP(Layer):
    """Experts as batched weights [E, ...] — one einsum, not a python loop."""

    def __init__(self, num_experts: int, hidden_size: int, ffn_size: int,
                 dtype=None):
        super().__init__()
        std = 0.02
        self.w_gate_up = self.create_parameter(
            [num_experts, hidden_size, 2 * ffn_size], dtype=dtype,
            initializer=I.Normal(0.0, std), sharding=(("dp", "fsdp"), None, "tp"))
        self.w_down = self.create_parameter(
            [num_experts, ffn_size, hidden_size], dtype=dtype,
            initializer=I.Normal(0.0, std), sharding=(("dp", "fsdp"), "tp", None))

    def forward(self, x):
        # x: [e, c, d] -> [e, c, d]
        gu = jnp.einsum("ecd,edf->ecf", x, self.w_gate_up.astype(x.dtype))
        g, u = jnp.split(gu, 2, axis=-1)
        h = F.silu(g) * u
        return jnp.einsum("ecf,efd->ecd", h, self.w_down.astype(x.dtype))


class MoELayer(Layer):
    """Top-k routed MoE block (reference: MoELayer, moe_layer.py:263).

    forward(x: [b, s, d]) -> (out [b, s, d], aux_loss scalar)
    """

    def __init__(self, hidden_size: int, ffn_size: int, num_experts: int,
                 top_k: int = 2, capacity_factor: float = 1.25, dtype=None,
                 gate: str = "gshard"):
        super().__init__()
        if top_k > num_experts:
            raise ValueError(f"top_k={top_k} > num_experts={num_experts}")
        self.num_experts = num_experts
        self.top_k = 1 if gate == "switch" else top_k
        self.capacity_factor = capacity_factor
        self.gate_weight = self.create_parameter(
            [hidden_size, num_experts], dtype="float32",
            initializer=I.Normal(0.0, 0.02))
        self.experts = MoEMLP(num_experts, hidden_size, ffn_size, dtype=dtype)

    def forward(self, x):
        b, s, d = x.shape
        t = b * s
        e = self.num_experts
        capacity = int(math.ceil(t * self.top_k / e * self.capacity_factor))
        flat = x.reshape(t, d)
        logits = jnp.matmul(flat.astype(jnp.float32), self.gate_weight)
        dispatch, combine, aux = top_k_gating(logits, self.top_k, capacity)
        # dispatch tokens into the dense expert layout (einsum → MXU; the
        # reference's global_scatter all-to-all comes from GSPMD resharding)
        xe = jnp.einsum("td,tec->ecd", flat, dispatch.astype(flat.dtype))
        ye = self.experts(xe)
        out = jnp.einsum("ecd,tec->td", ye, combine.astype(ye.dtype))
        return out.reshape(b, s, d), aux
