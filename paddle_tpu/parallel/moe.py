"""Mixture-of-Experts layer with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
(MoELayer: gate → count_by_gate → MoEScatter(global_scatter all-to-all) →
per-expert FFN loop → MoEGather), gates under moe/gate/{naive,gshard,switch}
_gate.py, kernels paddle/fluid/operators/collective/global_scatter_op.cu.

TPU-native redesign, round 3 (SURVEY.md A.2 translation): the reference's
index-select + ragged all-to-all becomes a SORT-BASED dispatch — token
assignments are sorted by expert id (one XLA sort of t*k int32 keys), each
assignment's slot in the [experts, capacity, d] layout is its rank within
its expert's run, and dispatch/combine are pure GATHERS through a slot
index. Routing memory is O(t·k + e·c·d): the round-2 one-hot GShard
[t, e, c] dispatch/combine tensors (O(t·e·c) — OOM at DeepSeekMoE's 64+
experts) are gone. The experts still run as ONE batched einsum on the MXU.

Dropless mode (``capacity_factor=None``): no token is ever dropped — the
sorted assignments feed a grouped matmul over per-expert group sizes, the
TPU analogue of the reference's exact-count global_scatter path
(moe/utils.py count_by_gate). Round-5 on-chip A/B at DeepSeekMoE scale
(e=64, d=2048, f=1408, k=6, v5e): XLA's native ``lax.ragged_dot`` runs the
same grouped matmul 1.7x faster than the bundled megablox Pallas gmm with
bit-identical output, so ragged_dot is the primary path (gmm remains the
fallback for jax builds without ragged_dot); the capacity-factor dense
path is ~4x faster still at this scale but DROPS overflow tokens — the
measured trade is recorded in ops/pallas/tune_db.json (moe_grouped_mm).

Expert parallelism (ISSUE 20): expert weights shard their expert dim over
the ("ep","dp","fsdp") submesh — "ep" is a REAL mesh axis carved out of
the data ranks (HybridMesh.build's ep degree; _clean_spec drops it on
ep==1 meshes so pre-EP plans stay byte-identical). On an ep>1 mesh the
dispatch/combine run as a shard_map'd ``lax.all_to_all`` over the "ep"
axis in both capacity and DROPLESS variants — dropless keeps the exact
per-expert counts as the (logical) a2a split sizes inside a statically
bounded slot buffer, since this jax ships no ragged_all_to_all. On
jaxlib <0.6 HYBRID meshes, where manual-subgroup collectives abort in
the partial-manual shard_map lowering (the ring-attention gate,
parallel/ring_attention.py), the layer falls back to pure-GSPMD
dispatch: the dispatched [e, c, d] tensor is sharding-constrained to the
expert axes and XLA materializes the global_scatter/global_gather
all-to-alls itself.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from .mesh import current_mesh


def _aux_loss(probs, e):
    """GShard eq.4 load-balance loss: e * sum_e(mean_t(gate) * mean_t(frac))."""
    top1 = jnp.argmax(probs, axis=-1)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0)
    return jnp.sum(me * ce) * e


def routing_stats(gate_logits, k: int):
    """(aux_loss, router_z, per-expert token counts) for one routing
    batch. The counts vector is the MEASURED histogram the planner's
    entropy-priced all-to-all consumes (``price_config(...,
    moe_histogram=counts)``); router_z is the ST-MoE z-loss
    ``mean(logsumexp(logits)^2)``."""
    t, e = gate_logits.shape
    logits = gate_logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    _, ids = jax.lax.top_k(probs, k)
    counts = jnp.bincount(ids.reshape(-1), length=e).astype(jnp.int32)
    z = jnp.mean(jax.scipy.special.logsumexp(logits, axis=-1) ** 2)
    return _aux_loss(probs, e), z, counts


def publish_moe_metrics(aux_loss=None, router_z=None, expert_counts=None):
    """Publish MoE routing health through the metrics registry (the PR 4
    vocabulary): ``pt_moe_*`` counters for routed token assignments per
    expert plus gauges for the aux loss, router z-loss and the
    load-balance factor (``e × max expert share``; 1.0 = balanced — the
    same bottleneck statistic the planner's a2a entropy pricing uses).

    Host-side only: traced values are skipped silently, so call it from
    the training loop with concrete step outputs (``routing_stats`` of a
    logged step), never from inside jit."""
    from ..observability.metrics import REGISTRY
    if not REGISTRY.enabled:
        return
    tracer = lambda v: isinstance(v, jax.core.Tracer)
    if aux_loss is not None and not tracer(aux_loss):
        REGISTRY.gauge("pt_moe_aux_loss",
                       "GShard load-balance aux loss").set(float(aux_loss))
    if router_z is not None and not tracer(router_z):
        REGISTRY.gauge("pt_moe_router_z",
                       "router z-loss mean(logsumexp^2)").set(
            float(router_z))
    if expert_counts is not None and not tracer(expert_counts):
        c = np.asarray(expert_counts, dtype=float).ravel()
        tot = float(c.sum())
        ctr = REGISTRY.counter("pt_moe_expert_tokens_total",
                               "routed token assignments per expert")
        for i, v in enumerate(c):
            ctr.inc(float(v), expert=str(i))
        REGISTRY.counter("pt_moe_dispatch_total",
                         "MoE routing batches published").inc()
        if tot > 0:
            REGISTRY.gauge(
                "pt_moe_load_imbalance",
                "e * max expert share (1.0 = perfectly balanced)").set(
                float(c.max() * c.size / tot))


def top_k_routing(gate_logits, k: int, capacity: int,
                  jitter_eps: float = 0.0, key=None):
    """Sort-based top-k routing with capacity.

    Returns (slot [t, k] int32, gates [t, k] f32, aux_loss scalar):
    ``slot[i, j]`` is the flat position of token i's j-th assignment in the
    [e * capacity] expert-slot space, or e*capacity when the assignment was
    dropped (its expert full). Capacity priority is choice-major (every
    token's 1st choice outranks any 2nd choice), token-ascending — the
    fill-counter semantics of the reference's limit_by_capacity
    (moe/utils.py:74) without materializing anything O(t·e).
    """
    t, e = gate_logits.shape
    gate_logits = gate_logits.astype(jnp.float32)
    if jitter_eps > 0.0 and key is not None:
        noise = jax.random.uniform(key, gate_logits.shape, jnp.float32,
                                   1.0 - jitter_eps, 1.0 + jitter_eps)
        gate_logits = gate_logits * noise
    probs = jax.nn.softmax(gate_logits, axis=-1)              # [t, e]
    gates, ids = jax.lax.top_k(probs, k)                      # [t, k]

    # choice-major assignment stream: all 1st choices (token asc), then all
    # 2nd choices, ... — the stable sort by expert then ranks assignments
    # within each expert in exactly that priority order
    flat_e = ids.T.reshape(-1)                                # [k*t]
    order = jnp.argsort(flat_e, stable=True)
    sorted_e = flat_e[order]
    starts = jnp.searchsorted(sorted_e, jnp.arange(e))        # [e]
    pos = jnp.arange(k * t, dtype=jnp.int32) - starts[sorted_e]
    keep = pos < capacity
    slot_sorted = jnp.where(keep, sorted_e * capacity + pos,
                            e * capacity).astype(jnp.int32)
    # scatter slots back to choice-major stream order, then to [t, k]
    slot_cm = jnp.zeros((k * t,), jnp.int32).at[order].set(slot_sorted)
    slot = slot_cm.reshape(k, t).T                            # [t, k]
    return slot, gates, _aux_loss(probs, e)


def dispatch_tokens(flat, slot, num_experts: int, capacity: int):
    """Gather tokens into the dense [e, c, d] expert layout (empty slots
    zero). flat: [t, d]; slot: [t, k] from top_k_routing."""
    t, d = flat.shape
    k = slot.shape[1]
    ec = num_experts * capacity
    # slot -> token index (choice-major flatten matches top_k_routing)
    slot_token = jnp.full((ec + 1,), t, jnp.int32)
    slot_token = slot_token.at[slot.T.reshape(-1)].set(
        jnp.tile(jnp.arange(t, dtype=jnp.int32), k), mode="drop")
    padded = jnp.concatenate([flat, jnp.zeros((1, d), flat.dtype)])
    return padded[slot_token[:ec]].reshape(num_experts, capacity, d)


def combine_tokens(ye, slot, gates, renormalize: bool):
    """Weighted gather back to tokens. ye: [e, c, d]; slot/gates: [t, k].
    Dropped assignments (slot == e*c) contribute zero."""
    e, c, d = ye.shape
    padded = jnp.concatenate(
        [ye.reshape(e * c, d),
         jnp.zeros((1, d), ye.dtype)])                        # trash row
    y = padded[slot]                                          # [t, k, d]
    kept = (slot < e * c).astype(gates.dtype)
    g = gates * kept
    if renormalize:
        g = g / jnp.maximum(jnp.sum(g, axis=-1, keepdims=True), 1e-9)
    return jnp.sum(g[..., None].astype(y.dtype) * y, axis=1)  # [t, d]


# -- legacy one-hot formulation kept as the parity oracle --------------------

def top_k_gating(gate_logits, k: int, capacity: int,
                 jitter_eps: float = 0.0, key=None):
    """GShard one-hot gating (dispatch [t,e,c] bool, combine [t,e,c] float,
    aux_loss). O(t·e·c) — superseded by top_k_routing for real configs;
    retained as the test oracle for the sort-based path."""
    t, e = gate_logits.shape
    gate_logits = gate_logits.astype(jnp.float32)
    if jitter_eps > 0.0 and key is not None:
        noise = jax.random.uniform(key, gate_logits.shape, jnp.float32,
                                   1.0 - jitter_eps, 1.0 + jitter_eps)
        gate_logits = gate_logits * noise
    probs = jax.nn.softmax(gate_logits, axis=-1)  # [t,e]
    aux_loss = _aux_loss(probs, e)

    combine = jnp.zeros((t, e, capacity), jnp.float32)
    dispatch = jnp.zeros((t, e, capacity), bool)
    remaining = probs
    fill = jnp.zeros((e,), jnp.int32)
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                      # [t]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)          # [t,e]
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1 + fill) * onehot
        pos = jnp.sum(pos_in_expert, axis=-1)                     # [t]
        fits = pos < capacity
        gate_val = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        pos_oh = jax.nn.one_hot(jnp.where(fits, pos, capacity), capacity,
                                dtype=jnp.float32)                # [t,c]
        contrib = (onehot.astype(jnp.float32)[:, :, None] * pos_oh[:, None, :])
        combine = combine + gate_val[:, None, None] * contrib * fits[:, None, None]
        dispatch = dispatch | (contrib > 0) & fits[:, None, None]
        fill = fill + jnp.sum(onehot * fits[:, None].astype(jnp.int32), axis=0)
        remaining = remaining * (1.0 - onehot.astype(jnp.float32))
    if k > 1:
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
    return dispatch, combine, aux_loss


class MoEMLP(Layer):
    """Experts as batched weights [E, ...] — one einsum, not a python loop."""

    def __init__(self, num_experts: int, hidden_size: int, ffn_size: int,
                 dtype=None):
        super().__init__()
        std = 0.02
        # the expert dim shards over ep first (real expert parallelism),
        # then the dp/fsdp data axes; _clean_spec drops "ep" on ep==1
        # meshes so pre-EP placements stay byte-identical
        self.w_gate_up = self.create_parameter(
            [num_experts, hidden_size, 2 * ffn_size], dtype=dtype,
            initializer=I.Normal(0.0, std),
            sharding=(("ep", "dp", "fsdp"), None, "tp"))
        self.w_down = self.create_parameter(
            [num_experts, ffn_size, hidden_size], dtype=dtype,
            initializer=I.Normal(0.0, std),
            sharding=(("ep", "dp", "fsdp"), "tp", None))

    def forward(self, x):
        # x: [e, c, d] -> [e, c, d]
        gu = jnp.einsum("ecd,edf->ecf", x, self.w_gate_up.astype(x.dtype))
        g, u = jnp.split(gu, 2, axis=-1)
        h = F.silu(g) * u
        return jnp.einsum("ecf,efd->ecd", h, self.w_down.astype(x.dtype))


def _constrain_experts(xe):
    """Shard the [e, c, d] dispatched tensor's expert dim over the
    ep×dp×fsdp submesh — this boundary is where GSPMD emits the
    global_scatter/global_gather all-to-alls (and the whole of the
    pure-GSPMD ep fallback on legacy jaxlib hybrid meshes)."""
    hm = current_mesh()
    if hm is None or not isinstance(xe, jax.core.Tracer):
        return xe
    axes = tuple(a for a in ("ep", "dp", "fsdp") if hm.axis_size(a) > 1)
    if not axes:
        return xe
    if xe.shape[0] % int(np.prod([hm.axis_size(a) for a in axes])) != 0:
        return xe
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.lax.with_sharding_constraint(
        xe, NamedSharding(hm.mesh, P(axes, *([P.UNCONSTRAINED] * (xe.ndim - 1)))))


def _grouped_matmul(xs, w, group_sizes):
    """Ragged grouped matmul: rows of ``xs`` [m, k] are split by
    ``group_sizes`` [g] and each run multiplies its own ``w[g]`` [k, n].

    This is the dispatch SEAM (ISSUE 20): ops/pallas/grouped_matmul
    owns the implementation choice — the TuneDB-gated Pallas kernel on
    TPU, XLA ``lax.ragged_dot`` (the round-5 v5e A/B measured it 1.7x
    faster than megablox gmm with max|diff|=0 at e=64, d=2048, f=1408)
    or megablox gmm elsewhere."""
    from ..ops.pallas.grouped_matmul import grouped_matmul
    return grouped_matmul(xs, w, group_sizes)


def _expert_ffn(xe, w_gu, w_dn):
    """The per-expert SwiGLU on a dense [e_local, slots, d] layout —
    MoEMLP.forward's math on raw (shard_map-local) weight shards."""
    gu = jnp.einsum("ecd,edf->ecf", xe, w_gu)
    g, u = jnp.split(gu, 2, axis=-1)
    return jnp.einsum("ecf,efd->ecd", F.silu(g) * u, w_dn)


def _aux_loss_ep(probs, e):
    """GShard aux loss inside an ep shard_map body: the two token-means
    are pmean'd over the ranks BEFORE the product, which IS the global
    estimator (mean of equal-sized shard means = global mean), so ep>1
    training loss stays at parity with the replicated path — a
    pmean-of-per-rank-aux would average products of local means
    instead and drift by O(routing skew)."""
    top1 = jnp.argmax(probs, axis=-1)
    me = jax.lax.pmean(jnp.mean(probs, axis=0), "ep")
    ce = jax.lax.pmean(
        jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0), "ep")
    return jnp.sum(me * ce) * e


def _ep_shard_map_ok(mesh) -> bool:
    """Legacy jaxlib (< 0.6) cannot lower subgroup collectives inside a
    partially-manual shard_map when ANOTHER mesh axis has size > 1 (the
    ring-attention gate, parallel/ring_attention.py) — those hybrid
    meshes take the pure-GSPMD dispatch instead."""
    if jax.__version_info__ < (0, 6):
        return not any(mesh.shape[a] > 1
                       for a in mesh.axis_names if a != "ep")
    return True


class MoELayer(Layer):
    """Top-k routed MoE block (reference: MoELayer, moe_layer.py:263).

    forward(x: [b, s, d]) -> (out [b, s, d], aux_loss scalar)

    ``capacity_factor=None`` selects DROPLESS routing via grouped matmul
    (megablox gmm): exact per-expert counts, no token ever dropped.
    """

    def __init__(self, hidden_size: int, ffn_size: int, num_experts: int,
                 top_k: int = 2, capacity_factor: Optional[float] = 1.25,
                 dtype=None, gate: str = "gshard"):
        super().__init__()
        if top_k > num_experts:
            raise ValueError(f"top_k={top_k} > num_experts={num_experts}")
        self.num_experts = num_experts
        self.top_k = 1 if gate == "switch" else top_k
        self.capacity_factor = capacity_factor
        self.gate_weight = self.create_parameter(
            [hidden_size, num_experts], dtype="float32",
            initializer=I.Normal(0.0, 0.02))
        self.experts = MoEMLP(num_experts, hidden_size, ffn_size, dtype=dtype)

    def routing_histogram(self, x):
        """Measured per-expert token counts for ``x`` — the histogram
        the planner's entropy-priced all-to-all consumes
        (``price_config(..., moe_histogram=...)``)."""
        flat = x.reshape(-1, x.shape[-1])
        logits = jnp.matmul(flat.astype(jnp.float32), self.gate_weight)
        return routing_stats(logits, self.top_k)[2]

    def forward(self, x):
        b, s, d = x.shape
        t = b * s
        e = self.num_experts
        flat = x.reshape(t, d)

        # expert-parallel path: a real "ep" mesh axis routes through the
        # shard_map'd all-to-all when the lowering supports it (pure-ep
        # mesh, or modern jax); legacy hybrid meshes and ep==1 fall
        # through to the GSPMD paths below
        hm = current_mesh()
        ep = hm.axis_size("ep") if hm is not None else 1
        if (ep > 1 and t % ep == 0 and e % ep == 0 and (t // ep) > 0
                and _ep_shard_map_ok(hm.mesh)):
            if self.capacity_factor is None:
                out, aux = self._forward_dropless_ep(flat, hm.mesh, ep)
            else:
                out, aux = self._forward_capacity_ep(flat, hm.mesh, ep)
            return out.reshape(b, s, d), aux

        logits = jnp.matmul(flat.astype(jnp.float32), self.gate_weight)

        if self.capacity_factor is None:
            out, aux = self._forward_dropless(flat, logits)
            return out.reshape(b, s, d), aux

        capacity = int(math.ceil(t * self.top_k / e * self.capacity_factor))
        slot, gates, aux = top_k_routing(logits, self.top_k, capacity)
        xe = dispatch_tokens(flat, slot, e, capacity)         # [e, c, d]
        xe = _constrain_experts(xe)
        ye = self.experts(xe)
        ye = _constrain_experts(ye)
        out = combine_tokens(ye, slot, gates,
                             renormalize=self.top_k > 1)
        return out.reshape(b, s, d), aux

    def _forward_capacity_ep(self, flat, mesh_, ep: int):
        """shard_map'd expert-parallel capacity routing. Each ep rank
        routes its LOCAL tokens into the full [e, c_local, d] slot
        layout; the tiled all-to-all splits the expert dim over ranks
        while concatenating every rank's slot block, local expert
        shards run one dense SwiGLU over [e/ep, c_local*ep, d], and the
        reverse all-to-all returns each rank's slots for the local
        combine. The aux loss is the pmean over ranks (same estimator
        as dp-averaged gradients)."""
        t, d = flat.shape
        e, k = self.num_experts, self.top_k
        t_l = t // ep
        cap = int(math.ceil(t_l * k / e * self.capacity_factor))
        renorm = k > 1
        gw = self.gate_weight.astype(jnp.float32)
        w_gu = self.experts.w_gate_up.astype(flat.dtype)
        w_dn = self.experts.w_down.astype(flat.dtype)

        def body(xl, gw_, wgu, wdn):
            logits = jnp.matmul(xl.astype(jnp.float32), gw_)
            slot, gates, _ = top_k_routing(logits, k, cap)
            aux = _aux_loss_ep(jax.nn.softmax(logits, axis=-1), e)
            xe = dispatch_tokens(xl, slot, e, cap)            # [e, c, d]
            xe = jax.lax.all_to_all(xe, "ep", split_axis=0,
                                    concat_axis=1, tiled=True)
            ye = _expert_ffn(xe, wgu, wdn)                # [e/ep, c*ep, d]
            ye = jax.lax.all_to_all(ye, "ep", split_axis=1,
                                    concat_axis=0, tiled=True)
            out = combine_tokens(ye, slot, gates, renormalize=renorm)
            return out, aux

        fn = shard_map(body, mesh=mesh_, axis_names=frozenset({"ep"}),
                       in_specs=(P("ep", None), P(None, None),
                                 P("ep", None, None),
                                 P("ep", None, None)),
                       out_specs=(P("ep", None), P()),
                       check_vma=False)
        return fn(flat, gw, w_gu, w_dn)

    def _forward_dropless_ep(self, flat, mesh_, ep: int):
        """shard_map'd DROPLESS expert parallelism. Each rank sorts its
        local assignments by expert and scatters them into a
        statically-bounded [e, t_local, d] slot buffer (an expert can
        receive at most t_local distinct local tokens, so nothing is
        ever dropped); the exact per-expert counts are the a2a split
        sizes in the logical sense — they define slot occupancy inside
        the bound, because this jax ships no ragged_all_to_all. The
        grouped matmul then runs over the received slot blocks through
        the ops/pallas seam, and the reverse all-to-all + unsort
        restores token order."""
        t, d = flat.shape
        e, k = self.num_experts, self.top_k
        t_l = t // ep
        e_l = e // ep
        cap = t_l          # static per-(rank, expert) bound: top_k ids
        renorm = k > 1     # are distinct, so counts[e] <= t_local
        gw = self.gate_weight.astype(jnp.float32)
        w_gu = self.experts.w_gate_up.astype(flat.dtype)
        w_dn = self.experts.w_down.astype(flat.dtype)

        def body(xl, gw_, wgu, wdn):
            logits = jnp.matmul(xl.astype(jnp.float32), gw_)
            probs = jax.nn.softmax(logits, axis=-1)
            gates, ids = jax.lax.top_k(probs, k)              # [t_l, k]
            flat_e = ids.T.reshape(-1)                        # [k*t_l]
            order = jnp.argsort(flat_e, stable=True)
            sorted_e = flat_e[order]
            starts = jnp.searchsorted(sorted_e, jnp.arange(e))
            pos = jnp.arange(k * t_l, dtype=jnp.int32) - starts[sorted_e]
            dest = sorted_e * cap + pos                       # exact, no drop
            xs = xl[order % t_l]
            buf = jnp.zeros((e * cap, d), xl.dtype).at[dest].set(xs)
            buf = jax.lax.all_to_all(buf.reshape(e, cap, d), "ep",
                                     split_axis=0, concat_axis=1,
                                     tiled=True)          # [e_l, ep*cap, d]
            rows = buf.reshape(e_l * ep * cap, d)
            gsz = jnp.full((e_l,), ep * cap, jnp.int32)
            gu = _grouped_matmul(rows, wgu, gsz).astype(xl.dtype)
            g, u = jnp.split(gu, 2, axis=-1)
            ys = _grouped_matmul(F.silu(g) * u, wdn,
                                 gsz).astype(xl.dtype)
            ybuf = jax.lax.all_to_all(ys.reshape(e_l, ep * cap, d), "ep",
                                      split_axis=1, concat_axis=0,
                                      tiled=True)             # [e, cap, d]
            ysr = ybuf.reshape(e * cap, d)[dest]              # sorted order
            y_cm = jnp.zeros_like(ysr).at[order].set(ysr).reshape(
                k, t_l, d)
            g_km = gates.T                                    # [k, t_l]
            if renorm:
                g_km = g_km / jnp.maximum(
                    jnp.sum(g_km, 0, keepdims=True), 1e-9)
            out = jnp.sum(g_km[..., None].astype(ysr.dtype) * y_cm,
                          axis=0)
            return out, _aux_loss_ep(probs, e)

        fn = shard_map(body, mesh=mesh_, axis_names=frozenset({"ep"}),
                       in_specs=(P("ep", None), P(None, None),
                                 P("ep", None, None),
                                 P("ep", None, None)),
                       out_specs=(P("ep", None), P()),
                       check_vma=False)
        return fn(flat, gw, w_gu, w_dn)

    def _forward_dropless(self, flat, logits):
        """Grouped-matmul experts over exact per-expert counts — the
        dropless path (reference analogue: global_scatter's exact
        count_by_gate split sizes). Grouped matmul = lax.ragged_dot
        (XLA-native; measured 1.7x faster than megablox gmm at
        DeepSeekMoE-64 scale on v5e, identical numerics), megablox gmm
        as fallback."""
        t, d = flat.shape
        e, k = self.num_experts, self.top_k
        probs = jax.nn.softmax(logits, axis=-1)
        gates, ids = jax.lax.top_k(probs, k)                  # [t, k]
        flat_e = ids.T.reshape(-1)                            # [k*t]
        order = jnp.argsort(flat_e, stable=True)
        sorted_e = flat_e[order]
        group_sizes = jnp.bincount(sorted_e, length=e).astype(jnp.int32)
        xs = flat[order % t]                                  # [k*t, d]

        w_gu = self.experts.w_gate_up.astype(flat.dtype)      # [e, d, 2f]
        w_dn = self.experts.w_down.astype(flat.dtype)         # [e, f, d2]

        gu = _grouped_matmul(xs, w_gu, group_sizes).astype(flat.dtype)
        g, u = jnp.split(gu, 2, axis=-1)
        h = F.silu(g) * u
        ys = _grouped_matmul(h, w_dn, group_sizes).astype(flat.dtype)

        # unsort to choice-major, weight, reduce over k
        y_cm = jnp.zeros_like(ys).at[order].set(ys).reshape(k, t, d)
        g_km = gates.T                                        # [k, t]
        if k > 1:
            g_km = g_km / jnp.maximum(jnp.sum(g_km, 0, keepdims=True), 1e-9)
        out = jnp.sum(g_km[..., None].astype(ys.dtype) * y_cm, axis=0)
        return out, _aux_loss(probs, e)
