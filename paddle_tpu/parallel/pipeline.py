"""Pipeline parallelism, TPU-native (SPMD collective-permute pipelining).

Reference analogue: python/paddle/distributed/fleet/meta_parallel/
  parallel_layers/pp_layers.py (LayerDesc:56, SharedLayerDesc:76,
  SegmentLayers:92, PipelineLayer:237) and pipeline_parallel.py (1F1B
  forward_backward_pipeline:440, interleave :906) — an actor-style runtime
  exchanging activations over NCCL P2P with fused send/recv pairs
  (SURVEY.md A.1).

TPU-first redesign: there is no per-rank runtime and no P2P endpoint. The
whole pipeline is ONE jitted SPMD program:

- every stage's parameters are *stacked* along a leading stage axis that is
  sharded over the mesh's "pp" axis, so each pp group of devices holds one
  stage's slice;
- one pipeline "tick" applies all stages in parallel via ``jax.vmap`` over
  the stage axis (each stage binds its own parameter slice);
- activations advance stage→stage+1 with ``jnp.roll`` along the sharded
  stage axis, which XLA lowers to a CollectivePermute over ICI — the
  equivalent of the reference's fused ``send_forward_recv_backward`` pairs
  (pipeline_parallel.py:520), inserted and overlapped by the compiler;
- microbatches are scanned with ``lax.scan``: tick t injects microbatch t
  into stage 0 and drains microbatch t-(S-1) from stage S-1; total
  M + S - 1 ticks (the GPipe/FThenB schedule, bubble (S-1)/(M+S-1));
- backward needs no schedule at all: ``jax.grad`` differentiates through
  scan + roll (the transpose of a collective-permute is the reverse
  permute), giving the B-phase of FThenB for free; 1F1B's *memory* benefit
  is recovered with ``jax.checkpoint`` on the stage function (remat per
  microbatch ≈ holding one microbatch's activations per stage).

Non-goals kept as documented design decisions: the reference's
interceptor/carrier actor runtime (fleet_executor) has no TPU counterpart —
XLA's static schedule replaces the message bus.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..nn.layer import Layer, Parameter
from .mesh import HybridMesh, current_mesh


# ---------------------------------------------------------------------------
# Layer descriptors (API parity with pp_layers.py)
# ---------------------------------------------------------------------------

class LayerDesc:
    """Deferred layer construction (reference: pp_layers.py LayerDesc:56)."""

    def __init__(self, layer_cls, *args, **kwargs):
        if not issubclass(layer_cls, Layer):
            raise TypeError(f"LayerDesc expects a Layer subclass, got {layer_cls}")
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """A layer whose parameters are shared across stages (reference:
    pp_layers.py SharedLayerDesc:76 — tied embeddings across first/last
    stage). In SPMD pipelining the tie is expressed by *reusing the same
    parameter tree* outside the pipelined stack (embedding/head run GSPMD-
    replicated over pp), so this desc only records the tie key."""

    def __init__(self, key: str, layer_cls, *args,
                 forward_func: Optional[Callable] = None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class SegmentLayers:
    """Partition a list of layer descs into pipeline stages.

    Reference: pp_layers.py SegmentLayers:92 — methods "uniform" (even by
    count) and "layer:<ClassName>" (even by occurrences of a class, e.g.
    decoder blocks, keeping pre/post layers with the first/last stage).
    """

    def __init__(self, layers: Sequence, num_parts: int, method: str = "uniform"):
        self.layers = list(layers)
        self.num_parts = num_parts
        self.method = method
        if len(self.layers) < num_parts:
            raise ValueError(f"cannot split {len(self.layers)} layers into "
                             f"{num_parts} stages")

    def do_segment(self) -> List[int]:
        """Return stage boundaries: list of len num_parts+1."""
        if self.method == "uniform":
            return self._uniform(len(self.layers), self.num_parts)
        if self.method.startswith("layer:"):
            name = self.method.split(":", 1)[1]
            marks = [i for i, l in enumerate(self.layers)
                     if self._type_name(l) == name]
            if len(marks) < self.num_parts:
                raise ValueError(f"only {len(marks)} '{name}' layers for "
                                 f"{self.num_parts} stages")
            part = self._uniform(len(marks), self.num_parts)
            bounds = [0] * (self.num_parts + 1)
            for p in range(1, self.num_parts):
                bounds[p] = marks[part[p]]
            bounds[self.num_parts] = len(self.layers)
            return bounds
        raise ValueError(f"unknown segment method {self.method!r}")

    @staticmethod
    def _uniform(n: int, parts: int) -> List[int]:
        base, rem = divmod(n, parts)
        bounds = [0]
        for p in range(parts):
            bounds.append(bounds[-1] + base + (1 if p < rem else 0))
        return bounds

    @staticmethod
    def _type_name(l) -> str:
        if isinstance(l, LayerDesc):
            return l.layer_cls.__name__
        return type(l).__name__


# ---------------------------------------------------------------------------
# The SPMD pipeline engine
# ---------------------------------------------------------------------------

def _stack_trees(trees: List[Dict[str, jax.Array]]) -> Dict[str, jax.Array]:
    out = {}
    for name in trees[0]:
        out[name] = jnp.stack([t[name] for t in trees])
    return out


def pipeline_spmd(stage_fn: Callable, stacked_params, x_microbatches,
                  *, num_stages: int, remat: bool = True,
                  extras: Tuple = ()):
    """Run the SPMD pipeline over M microbatches.

    stage_fn(params_slice, h, *extras) -> h        (one stage's computation)
    stacked_params: pytree with leading stage axis S (sharded over "pp")
    x_microbatches: [M, mb, ...] stage-0 inputs (e.g. embedded hiddens)

    Returns [M, mb, ...] stage-(S-1) outputs. Differentiable.
    """
    S = num_stages
    M = x_microbatches.shape[0]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    vstage = jax.vmap(fn, in_axes=(0, 0) + (None,) * len(extras))

    state0 = jnp.zeros((S,) + x_microbatches.shape[1:],
                       dtype=x_microbatches.dtype)
    out0 = jnp.zeros_like(x_microbatches)

    def tick(carry, t):
        state, outputs = carry
        # inject microbatch t into stage 0 (ticks >= M recycle the last one;
        # its result is never drained)
        inj = jax.lax.dynamic_index_in_dim(x_microbatches,
                                           jnp.clip(t, 0, M - 1), 0,
                                           keepdims=False)
        state = state.at[0].set(inj)
        out = vstage(stacked_params, state, *extras)
        # advance the pipe: stage s feeds stage s+1 (CollectivePermute on
        # pp). Posted immediately after the stage compute — before the
        # output-drain bookkeeping below — so the permute's start->done
        # window spans the drain's gather/scatter instead of sitting
        # exposed at the scan-body tail (double-buffered send, ISSUE 14;
        # its only consumer is the NEXT tick's vstage).
        state = jnp.roll(out, 1, axis=0)
        # drain stage S-1 for microbatch t-(S-1)
        oidx = t - (S - 1)
        oclip = jnp.clip(oidx, 0, M - 1)
        prev = jax.lax.dynamic_index_in_dim(outputs, oclip, 0, keepdims=False)
        val = jnp.where(oidx >= 0, out[-1], prev)
        outputs = jax.lax.dynamic_update_index_in_dim(outputs, val, oclip, 0)
        return (state, outputs), None

    (_, outputs), _ = jax.lax.scan(tick, (state0, out0),
                                   jnp.arange(M + S - 1))
    return outputs


def microbatch(x, num_microbatches: int):
    """[B, ...] -> [M, B/M, ...]."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by {num_microbatches} "
                         f"microbatches")
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def unmicrobatch(x):
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


# ---------------------------------------------------------------------------
# PipelineLayer: stacked-stage module
# ---------------------------------------------------------------------------

class PipelineStack(Layer):
    """A homogeneous stack of N identical layers executed as a pipeline.

    This is the load-bearing module: it owns the *stacked* parameters
    ([num_layers, ...] per leaf, leading dim annotated "pp" after grouping
    into stages) and a template layer used purely as the per-slice compute
    function. ``forward`` runs either:

    - sequential mode (num_stages == 1): a ``lax.scan`` over the layer axis
      (standard weight-stacked transformer — fastest to compile), or
    - pipeline mode: `pipeline_spmd` with microbatching.

    Reference analogue: PipelineLayer's per-stage partition
    (pp_layers.py:237) — here partitioning is a reshape [L] -> [S, L/S].
    """

    SCHEDULES = ("gpipe", "1f1b", "interleaved")

    def __init__(self, make_layer: Callable[[], Layer], num_layers: int,
                 num_stages: int = 1, num_microbatches: int = 1,
                 remat: bool = True, schedule: str = "gpipe",
                 num_chunks: int = 1):
        super().__init__()
        if schedule not in self.SCHEDULES:
            raise ValueError(f"schedule must be one of {self.SCHEDULES}, "
                             f"got {schedule!r}")
        if schedule == "interleaved" and num_chunks < 2:
            raise ValueError("interleaved schedule needs num_chunks >= 2")
        if schedule != "interleaved":
            num_chunks = 1
        if num_layers % max(num_stages * num_chunks, 1):
            raise ValueError(f"num_layers={num_layers} must be divisible by "
                             f"num_stages*num_chunks="
                             f"{num_stages * num_chunks}")
        if (schedule == "interleaved" and num_stages > 1
                and num_microbatches % num_stages):
            raise ValueError(f"interleaved schedule needs num_microbatches="
                             f"{num_microbatches} divisible by num_stages="
                             f"{num_stages}")
        self.num_layers = num_layers
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.remat = remat
        self.schedule = schedule
        self.num_chunks = num_chunks
        # template held OUT of the registration tree (plain __dict__ slot):
        # it is only the per-slice compute fn; the real weights live in the
        # stacked Parameters below, so the template's own values are dropped
        # (replaced by zero-cost host views — functional_call always binds
        # real values over them).
        from ..base import LazyGuard
        lazy = LazyGuard._active
        template = make_layer()
        if not lazy:
            for _, p in template.named_parameters():
                p.value = np.broadcast_to(
                    np.zeros((), np.asarray(p.value).dtype),
                    tuple(p.value.shape))
        object.__setattr__(self, "template", template)
        # build stacked parameters by initializing num_layers independent
        # copies and stacking leaf-wise (keeps per-layer init distributions).
        # Under LazyGuard everything stays abstract: one template's shapes
        # are enough to derive the [L, ...] stacked ShapeDtypeStructs.
        template_params = dict(self.template.named_parameters())
        if lazy:
            self._leaf_names = list(template_params.keys())
            stacks = {n: jax.ShapeDtypeStruct(
                          (num_layers,) + tuple(p.value.shape), p.value.dtype)
                      for n, p in template_params.items()}
        else:
            trees = []
            for _ in range(num_layers):
                lyr = make_layer()
                trees.append({n: p.value for n, p in lyr.named_parameters()})
            self._leaf_names = list(trees[0].keys())
            stacks = {name: jnp.stack([t[name] for t in trees])
                      for name in self._leaf_names}
        for name in self._leaf_names:
            stacked = stacks[name]
            tp = template_params[name]
            base_shard = tuple(tp.sharding) if tp.sharding else (None,) * (stacked.ndim - 1)
            pname = "stack__" + name.replace(".", "__")
            param = Parameter(self.pack_leaf(stacked), trainable=True,
                              sharding=self._storage_sharding(base_shard),
                              name=pname)
            self.add_parameter(pname, param)

    def pack_leaf(self, stacked):
        """[L, ...] layer-stacked leaf -> storage layout. Interleaved stores
        [V, S, k, ...] so the "pp" shard axis (dim 1) matches the Megatron
        chunk placement (virtual stage v*S+s = layers [(v*S+s)*k, ...)) —
        a flat [L] leaf sharded contiguously over pp cannot express it."""
        if self.schedule != "interleaved":
            return stacked
        V, S = self.num_chunks, self.num_stages
        k = self.num_layers // (S * V)
        if isinstance(stacked, jax.ShapeDtypeStruct):   # LazyGuard path
            return jax.ShapeDtypeStruct((V, S, k) + tuple(stacked.shape[1:]),
                                        stacked.dtype)
        return stacked.reshape((V, S, k) + stacked.shape[1:])

    def unpack_leaf(self, stored):
        """Storage layout -> [L, ...] layer order."""
        if self.schedule != "interleaved":
            return stored
        return stored.reshape((self.num_layers,) + stored.shape[3:])

    def _storage_sharding(self, base_shard):
        if self.schedule == "interleaved":
            return (None, "pp", None) + tuple(base_shard)
        return ("pp",) + tuple(base_shard)

    def stacked_tree(self) -> Dict[str, jax.Array]:
        """Leaves in STORAGE layout ([L,...] or [V,S,k,...])."""
        return {name: getattr(self, "stack__" + name.replace(".", "__"))
                for name in self._leaf_names}

    def _slice_fn(self, params_slice: Dict[str, jax.Array], h, *extras):
        """Apply ONE layer with the given unstacked param tree."""
        return self.template.functional_call(params_slice, h, *extras)

    def stage_trees(self, tree=None):
        """Group the stacked leaves for the active schedule:
        [S, k, ...] (gpipe/1f1b) or [V, S, k, ...] (interleaved — already
        the storage layout)."""
        tree = self.stacked_tree() if tree is None else tree
        if self.schedule == "interleaved":
            return tree
        S = self.num_stages
        k = self.num_layers // S
        return {n: v.reshape((S, k) + v.shape[1:]) for n, v in tree.items()}

    def stage_fn(self, *extras):
        """fn(stage_params, h) applying one stage (k stacked layers)."""
        def fn(stage_params, hh):
            def body(carry, sl):
                return self._slice_fn(sl, carry, *extras), None
            hh, _ = jax.lax.scan(body, hh, stage_params)
            return hh
        return fn

    def forward(self, h, *extras):
        tree = self.stacked_tree()
        if self.num_stages <= 1:
            # sequential: scan over the layer axis
            tree = {n: self.unpack_leaf(v) for n, v in tree.items()}

            def body(carry, sl):
                fn = (jax.checkpoint(self._slice_fn) if self.remat
                      else self._slice_fn)
                return fn(sl, carry, *extras), None
            h, _ = jax.lax.scan(body, h, tree)
            return h

        staged = self.stage_trees(tree)
        xmb = microbatch(h, self.num_microbatches)
        if self.schedule == "interleaved":
            from .schedules import pipeline_interleaved
            out = pipeline_interleaved(self.stage_fn(*extras), staged, xmb,
                                       num_stages=self.num_stages,
                                       num_chunks=self.num_chunks,
                                       remat=self.remat)
        else:
            # "1f1b" reaches here only on inference-style plain forwards;
            # training uses the fused pipeline_1f1b via the owning model's
            # loss_and_grads, where 1F1B's memory profile actually matters
            out = pipeline_spmd(self.stage_fn(*extras), staged, xmb,
                                num_stages=self.num_stages,
                                remat=self.remat)
        return unmicrobatch(out)


class PipelineLayer(Layer):
    """Desc-based pipeline model (reference: pp_layers.py PipelineLayer:237).

    Accepts a list of Layers / LayerDescs; homogeneous runs of the same desc
    are pipelined via PipelineStack, leading/trailing heterogeneous layers
    (embedding, final norm, head) execute GSPMD-replicated over "pp" — the
    TPU translation of the reference keeping them on first/last stage with
    SharedLayerDesc ties.
    """

    def __init__(self, layers: Sequence, num_stages: int = 1,
                 num_microbatches: int = 1, loss_fn: Optional[Callable] = None,
                 seg_method: str = "uniform", recompute_interval: int = 0):
        super().__init__()
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.loss_fn = loss_fn
        self._order: List[str] = []

        descs = list(layers)
        # find the longest homogeneous run of LayerDescs → the pipelined body
        best = (0, 0)
        i = 0
        while i < len(descs):
            if isinstance(descs[i], LayerDesc) and not isinstance(
                    descs[i], SharedLayerDesc):
                j = i
                while (j < len(descs) and isinstance(descs[j], LayerDesc)
                       and not isinstance(descs[j], SharedLayerDesc)
                       and descs[j].layer_cls is descs[i].layer_cls
                       and descs[j].args == descs[i].args
                       and descs[j].kwargs == descs[i].kwargs):
                    j += 1
                if j - i > best[1] - best[0]:
                    best = (i, j)
                i = j
            else:
                i += 1
        run_start, run_end = best
        run_len = run_end - run_start
        use_pipe = (run_len >= num_stages and num_stages > 1
                    and run_len % num_stages == 0)

        idx = 0
        for pos, d in enumerate(descs):
            if use_pipe and pos == run_start:
                stack = PipelineStack(lambda dd=descs[pos]: dd.build(),
                                      num_layers=run_len,
                                      num_stages=num_stages,
                                      num_microbatches=num_microbatches,
                                      remat=recompute_interval > 0)
                name = f"seg_{idx}"
                setattr(self, name, stack)
                self._order.append(name)
                idx += 1
                continue
            if use_pipe and run_start < pos < run_end:
                continue
            lyr = d.build() if isinstance(d, LayerDesc) else d
            name = f"seg_{idx}"
            setattr(self, name, lyr)
            self._order.append(name)
            idx += 1

    def forward(self, x, *extras):
        for name in self._order:
            lyr = getattr(self, name)
            if isinstance(lyr, PipelineStack):
                x = lyr(x, *extras)
            else:
                x = lyr(x)
        return x


__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineStack",
           "PipelineLayer", "pipeline_spmd", "microbatch", "unmicrobatch"]
