"""Device mesh & hybrid topology.

TPU-native analogue of the reference's hybrid-parallel topology
(reference: python/paddle/distributed/fleet/base/topology.py —
CommunicateTopology:61 over axes ["data","pipe","sharding","sep","model"],
HybridCommunicateGroup:174). On TPU the N-D rank topology IS a
jax.sharding.Mesh: one mesh with named axes replaces all per-axis NCCL comm
groups; XLA collectives ride ICI/DCN according to axis order (outermost =
slowest-varying = DCN for multi-host meshes, per jax.make_mesh device order).

Axis naming convention (mirrors fleet's): "dp" data, "fsdp" sharding/ZeRO,
"pp" pipeline, "sep" sequence, "tp" model/tensor, "ep" expert.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXES_ORDER = ("pp", "dp", "fsdp", "sep", "tp")  # outer→inner (DCN→ICI)
# with expert parallelism the "ep" axis is carved OUT of dp (it is a
# subgroup of the data ranks, not extra devices) and sits between dp and
# fsdp so expert all-to-all rides the faster inner links than pure-dp
# gradient traffic; ep==1 meshes keep the exact 5-axis shape above so
# every pre-EP census/plan artifact stays byte-identical
AXES_ORDER_EP = ("pp", "dp", "ep", "fsdp", "sep", "tp")

_CURRENT: List["HybridMesh"] = []


class HybridMesh:
    """A named device mesh plus topology queries shaped like
    HybridCommunicateGroup (get_model_parallel_world_size etc.)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.ep_degree = int(mesh.shape.get("ep", 1))

    # -- construction -------------------------------------------------------

    @staticmethod
    def build(dp: int = 1, fsdp: int = 1, tp: int = 1, pp: int = 1, sep: int = 1,
              ep: int = 1, devices=None) -> "HybridMesh":
        """Create a hybrid mesh. Axis sizes must multiply to the device count.

        Mirrors fleet.init's strategy degrees (reference:
        fleet/base/topology.py:64 axis order) but expressed as one Mesh.
        The "ep" axis is a SUBGROUP of the data ranks the way the
        reference reuses comm groups for expert parallel: ``ep`` must
        divide ``dp`` and does not change the device count. When ep>1
        the mesh carries a real "ep" axis (AXES_ORDER_EP) with the dp
        axis shrunk to ``dp // ep``; when ep==1 the 5-axis mesh is
        byte-identical to the pre-EP shape.
        """
        devices = list(jax.devices()) if devices is None else list(devices)
        sizes = {"pp": pp, "dp": dp, "fsdp": fsdp, "sep": sep, "tp": tp}
        total = int(np.prod(list(sizes.values())))
        if total != len(devices):
            raise ValueError(
                f"mesh degrees {sizes} multiply to {total} but {len(devices)} "
                f"devices are available")
        if ep != 1 and dp % ep != 0:
            raise ValueError(
                f"ep={ep} must divide dp={dp}: expert parallelism carves an "
                f"expert subgroup out of the data ranks (reference: fleet "
                f"reuses comm groups for MoE's all-to-all)")
        if ep != 1:
            sizes = {"pp": pp, "dp": dp // ep, "ep": ep, "fsdp": fsdp,
                     "sep": sep, "tp": tp}
            axes = AXES_ORDER_EP
        else:
            axes = AXES_ORDER
        arr = np.array(devices).reshape([sizes[a] for a in axes])
        mesh = Mesh(arr, axes)
        hm = HybridMesh(mesh)
        hm.ep_degree = ep
        return hm

    # -- topology queries (reference: HybridCommunicateGroup) ---------------

    def axis_size(self, name: str) -> int:
        return self.mesh.shape.get(name, 1)

    def get_data_parallel_world_size(self) -> int:
        # the ep axis is carved out of dp, so data ranks span dp×ep×fsdp
        return (self.axis_size("dp") * self.axis_size("ep")
                * self.axis_size("fsdp"))

    def get_model_parallel_world_size(self) -> int:
        return self.axis_size("tp")

    def get_pipe_parallel_world_size(self) -> int:
        return self.axis_size("pp")

    def get_sharding_parallel_world_size(self) -> int:
        return self.axis_size("fsdp")

    def get_sep_parallel_world_size(self) -> int:
        return self.axis_size("sep")

    def get_expert_parallel_world_size(self) -> int:
        return max(self.axis_size("ep"), self.ep_degree)

    @property
    def nproc(self) -> int:
        return self.mesh.size

    # -- context ------------------------------------------------------------

    def __enter__(self):
        self.mesh.__enter__()
        _CURRENT.append(self)
        return self

    def __exit__(self, *exc):
        _CURRENT.pop()
        return self.mesh.__exit__(*exc)

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def __repr__(self):
        return f"HybridMesh({dict(self.mesh.shape)})"


def current_mesh() -> Optional[HybridMesh]:
    if _CURRENT:
        return _CURRENT[-1]
    # fall back to jax's ambient mesh if one is active
    try:
        env_mesh = jax._src.mesh.thread_resources.env.physical_mesh
        if env_mesh is not None and not env_mesh.empty:
            return HybridMesh(env_mesh)
    except Exception:
        pass
    return None


def pod_bootstrap_env() -> Optional[dict]:
    """Map pod/launcher env to jax.distributed.initialize kwargs.

    Sources, in precedence order (first complete set wins):
    - ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
      ``JAX_PROCESS_ID`` — set by distributed/launch (and GKE JobSet TPU
      manifests);
    - ``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM`` + ``MASTER_ADDR`` /
      ``MASTER_PORT`` — the reference recipe env
      (python/paddle/distributed/parallel.py:943 init_parallel_env reads
      the same trio for its TCPStore rendezvous).

    Returns None when the env describes a single-process job. With a
    PARTIAL env (only JAX_COORDINATOR_ADDRESS set), the caller falls back
    to bare jax.distributed.initialize() so jax's own cluster autodetect
    fills the rest; on a pod with NO bootstrap env at all, call
    jax.distributed.initialize() yourself (or use distributed.launch) —
    single-host runs must not pay an initialize() attempt."""
    import os
    env = os.environ
    # first COMPLETE set wins — fields are never mixed across sources (a
    # stale PADDLE_TRAINER_ID must not complete a partial JAX_* trio)
    sets = [
        (env.get("JAX_COORDINATOR_ADDRESS"), env.get("JAX_NUM_PROCESSES"),
         env.get("JAX_PROCESS_ID")),
    ]
    if env.get("MASTER_ADDR") and env.get("MASTER_PORT"):
        sets.append((f"{env['MASTER_ADDR']}:{env['MASTER_PORT']}",
                     env.get("PADDLE_TRAINERS_NUM"),
                     env.get("PADDLE_TRAINER_ID")))
    for coord, nproc, pid in sets:
        # empty strings (unset template vars) count as missing, so an
        # incomplete set falls through to the next source
        if coord and nproc and pid not in (None, ""):
            if int(nproc) <= 1:
                return None
            return {"coordinator_address": coord,
                    "num_processes": int(nproc), "process_id": int(pid)}
    return None


def init_parallel_env(dp: int = 1, fsdp: int = 1, tp: int = 1, pp: int = 1,
                      sep: int = 1, ep: int = 1) -> HybridMesh:
    """Multi-host bootstrap + mesh creation.

    Reference analogue: paddle.distributed.init_parallel_env
    (python/paddle/distributed/parallel.py:943 — TCPStore rendezvous +
    default ProcessGroup). On TPU, jax.distributed.initialize's
    coordination service is the TCPStore equivalent; the pod env mapping
    (pod_bootstrap_env) covers both the launcher's JAX_* trio and the
    reference's PADDLE_*/MASTER_* recipe env. No-op on single-host."""
    import os
    kwargs = pod_bootstrap_env()
    # probe initialized-ness WITHOUT touching the backend —
    # jax.process_count() would initialize it single-process and make the
    # subsequent distributed.initialize a no-op
    try:
        from jax._src import distributed as _dist
        already = _dist.global_state.client is not None
    except Exception:
        already = False
    if not already:
        if kwargs is not None:
            try:
                jax.distributed.initialize(**kwargs)
            except RuntimeError as e:
                if "already" not in str(e).lower():
                    raise  # real bootstrap failure must surface, not hang
        elif os.environ.get("JAX_COORDINATOR_ADDRESS"):
            # partial env: let jax's own discovery (TPU metadata server /
            # cluster-env autodetect) fill in the rest
            try:
                jax.distributed.initialize()
            except RuntimeError as e:
                if "already" not in str(e).lower():
                    raise
    return HybridMesh.build(dp=dp, fsdp=fsdp, tp=tp, pp=pp, sep=sep, ep=ep)
