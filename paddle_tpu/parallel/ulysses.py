"""Ulysses (all-to-all head-scatter) sequence parallelism.

The second long-context mode next to ring attention (SURVEY.md §5 marks
context parallelism absent from the reference snapshot but first-class for
the TPU build; the DeepSpeed-Ulysses paper is the published pattern).
Activations are sequence-sharded over the "sep" mesh axis; around the
attention core, one all-to-all per tensor trades the sequence sharding for
a HEAD sharding:

    [b, s/n, h, d]  --all_to_all-->  [b, s, h/n, d]
    full-sequence flash attention on h/n local heads
    [b, s, h/n, d]  --all_to_all-->  [b, s/n, h, d]

Communication is O(s·h·d/n) per device per a2a (4 of them fwd) riding ICI
— cheaper than the ring's n ppermute rounds when n is moderate and h
divides; the ring wins when h < n or when overlap with per-step compute
matters. Both are exact; `models/llama.py` picks via config.sp_mode.

GQA: when h_kv % n == 0 K/V all-to-all the same way and the contiguous
head slices stay group-aligned (q head j maps to kv head j//(h/h_kv);
slice i of q maps exactly onto slice i of kv). When h_kv < n with
n % h_kv == 0, K/V heads expand only to n (factor n/h_kv — each device's
q slice sits inside one kv group, so expanded head i IS that group);
only the ragged remainder falls back to full h expansion. Llama-70B
(h_kv=8) at sep=16 pays 2x KV bandwidth, not 8x.

The all-to-alls are linear ops with registered transposes, so jax AD
differentiates straight through them — only the attention core carries a
custom VJP (the Pallas flash kernel's).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from .mesh import current_mesh


def _local_attn(q, k, v, causal, scale, interpret):
    """Full-sequence attention on the local head slice. Dispatches to the
    Pallas flash kernel (TPU) / its interpret path or the XLA composition
    (CPU test meshes) via the normal kernel gate."""
    from ..ops.pallas.flash_attention import flash_attention_pallas
    return flash_attention_pallas(q, k, v, causal=causal, scale=scale,
                                  interpret=interpret)


def gqa_expand_factor(h: int, h_kv: int, n: int) -> int:
    """KV head repeat factor before the all-to-all. 1 when h_kv already
    splits over the axis. MINIMAL expansion n/h_kv when h_kv | n: n | h
    makes each device's q-head slice [i·h/n, (i+1)·h/n) lie inside ONE
    original kv group (h/n divides h/h_kv ⟺ h_kv | n), and expanded kv
    head i = original i·h_kv/n is precisely that group — Llama-70B
    (h=64, h_kv=8) at sep=16 pays 2x KV bandwidth, not 8x. Ragged
    remainders expand fully to h (correctness-grade)."""
    if h_kv % n == 0:
        return 1
    if n % h_kv == 0:
        return n // h_kv
    return h // h_kv


def ulysses_supported(h: int, h_kv: int, n: int) -> bool:
    """Query heads must split evenly over the sep axis, and KV heads must
    either split too or expand to h exactly (GQA group expansion)."""
    return n > 1 and h % n == 0 and (h_kv % n == 0 or h % h_kv == 0)


def ulysses_attention(q, k, v, causal: bool = True, axis: str = "sep",
                      scale: Optional[float] = None, mesh=None,
                      interpret: Optional[bool] = None):
    """Exact attention over sequence-sharded q/k/v via head all-to-all.

    q/k/v: [b, s, h(_kv), d] GLOBAL arrays sharded (or shardable) along s
    over ``axis``. Returns [b, s, h, d] with the same sharding. Falls back
    to the single-device path when no mesh/axis is active.
    """
    hm = current_mesh() if mesh is None else mesh
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if hm is None or hm.axis_size(axis) <= 1:
        from ..ops.attention import _sdpa_xla
        return _sdpa_xla(q, k, v, causal=causal, scale=scale)

    n = hm.axis_size(axis)
    # Legacy jaxlib (< 0.6) aborts lowering all-to-all inside a
    # partially-manual shard_map when another mesh axis has size > 1 —
    # same manual-subgroup limitation as ring_attention's ppermute (see
    # the comment there). Fall back to pure GSPMD on those builds: q
    # stays seq-sharded, XLA gathers K/V over the axis.
    if jax.__version_info__ < (0, 6) and any(
            hm.mesh.shape[a] > 1 for a in hm.mesh.axis_names
            if a != axis):
        from ..ops.attention import _sdpa_xla
        return _sdpa_xla(q, k, v, causal=causal, scale=scale)
    h, h_kv = q.shape[2], k.shape[2]
    if not ulysses_supported(h, h_kv, n):
        raise ValueError(
            f"ulysses_attention: need h % n == 0 and (h_kv % n == 0 or "
            f"h % h_kv == 0); got h={h}, h_kv={h_kv}, {axis}={n} — use "
            f"ring_attention instead")
    r = gqa_expand_factor(h, h_kv, n)
    if r > 1:
        k = jnp.repeat(k, r, axis=2)
        v = jnp.repeat(v, r, axis=2)
    if interpret is None:
        from ..ops.registry import backend_kind
        interpret = backend_kind() != "tpu"

    def local_fn(q_l, k_l, v_l):
        # [b, s/n, h, d] -> [b, s, h/n, d]: split heads, concat sequence
        a2a = functools.partial(jax.lax.all_to_all, axis_name=axis,
                                split_axis=2, concat_axis=1, tiled=True)
        qh, kh, vh = a2a(q_l), a2a(k_l), a2a(v_l)
        out = _local_attn(qh, kh, vh, causal, scale, interpret)
        # [b, s, h/n, d] -> [b, s/n, h, d]: split sequence, concat heads
        return jax.lax.all_to_all(out, axis_name=axis, split_axis=1,
                                  concat_axis=2, tiled=True)

    fn = shard_map(local_fn, mesh=hm.mesh, axis_names=frozenset({axis}),
                   in_specs=(P(None, axis, None, None),) * 3,
                   out_specs=P(None, axis, None, None), check_vma=False)
    return fn(q, k, v)


__all__ = ["ulysses_attention", "ulysses_supported"]
