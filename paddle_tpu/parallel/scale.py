"""Scale-fit analysis: does a (model, mesh, batch) configuration fit HBM?

Reference analogue: the auto-tuner's memory pruner
(python/paddle/distributed/auto_tuner/prune.py prune_by_memory_estimation)
— but computed from the ACTUAL abstract parameter tree (shapes + sharding
annotations) rather than a closed-form heuristic, so it can be asserted
against per-parameter NamedShardings. Built on jax.sharding.AbstractMesh:
no devices, no weights (construct the model under paddle_tpu.LazyGuard).

HBM sizes: v5e 16 GB, v5p 95 GB, v4 32 GB (public TPU specs).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import AbstractMesh, NamedSharding, PartitionSpec

HBM_GB = {"v5e": 16.0, "v5p": 95.0, "v4": 32.0, "v6e": 32.0}

_OPT_BYTES_PER_PARAM = 12  # AdamW fp32 master + m + v


def abstract_mesh(axes: Dict[str, int]) -> AbstractMesh:
    """AbstractMesh from {'pp': 4, 'fsdp': 2, 'tp': 8} — no devices needed."""
    names = tuple(axes.keys())
    sizes = tuple(int(axes[n]) for n in names)
    try:
        return AbstractMesh(sizes, names)
    except TypeError:
        # jax<0.6 spells it AbstractMesh(((name, size), ...))
        return AbstractMesh(tuple(zip(names, sizes)))


def clean_spec(sharding: Optional[Tuple], axes: Dict[str, int]) -> PartitionSpec:
    """Drop axes not present (or size-1) in the mesh — delegates to the ONE
    implementation in parallel.api (AbstractMesh satisfies its interface)."""
    from .api import _clean_spec
    if sharding is None:
        return PartitionSpec()
    return _clean_spec(sharding, abstract_mesh(axes))


def param_plan(model, axes: Dict[str, int]):
    """Yields (name, param, spec, local_shape) for every parameter, where
    local_shape is the per-device shard under the cleaned spec."""
    mesh = abstract_mesh(axes)
    for name, p in model.named_parameters():
        spec = clean_spec(p.sharding, axes)
        sh = NamedSharding(mesh, spec)
        local = sh.shard_shape(tuple(p.value.shape))
        yield name, p, spec, local


def train_state_bytes(model, axes: Dict[str, int], *, seq_len: int,
                      microbatch_size: int, recompute: str = "full",
                      vocab_size: Optional[int] = None,
                      hidden_size: Optional[int] = None,
                      num_layers: Optional[int] = None) -> Dict[str, float]:
    """Per-device training-state memory (bytes) for the model on a mesh.

    params/grads use each parameter's own dtype; optimizer state is fp32
    master + two moments (12 B/param, reference AMP-O2 master-weight
    profile); activations follow the Megatron per-layer formula scaled by
    microbatch, tp and sequence sharding, with ``recompute`` choosing how
    many layers stay live (full = 1 live layer + boundary saves,
    none = all local layers).
    """
    cfg = getattr(model, "cfg", None)
    vocab = vocab_size or getattr(cfg, "vocab_size", 0)
    h = hidden_size or getattr(cfg, "hidden_size", 0)
    layers = num_layers or getattr(cfg, "num_hidden_layers", 0)

    p_bytes = g_bytes = o_bytes = 0.0
    n_params = 0
    for name, p, spec, local in param_plan(model, axes):
        n_local = int(np.prod(local)) if local else 1
        n_total = int(np.prod(p.value.shape)) if p.value.shape else 1
        n_params += n_total
        itemsize = np.dtype(p.value.dtype).itemsize
        p_bytes += n_local * itemsize
        g_bytes += n_local * itemsize
        o_bytes += n_local * _OPT_BYTES_PER_PARAM

    tp = axes.get("tp", 1)
    sp = axes.get("sep", 1)
    pp = axes.get("pp", 1)
    b, s = microbatch_size, seq_len
    layers_local = max(layers / pp, 1)
    # Megatron activation-memory formula, bf16 profile: ~34*s*b*h bytes per
    # layer for one microbatch; tensor and sequence parallel both divide it.
    act_layer = s * b * h * 34 / (tp * sp)
    # pipeline keeps up to R = min(M, 2*pp-1) microbatch stage-inputs live
    # per stage (the 1F1B ring in parallel/schedules.py); pp=1 holds 1.
    micro = getattr(model, "num_microbatches", 1) or 1
    in_flight = min(micro, 2 * pp - 1) if pp > 1 else 1
    boundary = s * b * h * 2 / sp            # one bf16 stage/layer input
    if recompute == "full":
        # 1 live layer + per-layer remat boundaries for the microbatch in
        # backward + the pipeline ring of stage inputs
        act = act_layer + layers_local * boundary + in_flight * boundary
    elif recompute == "selective":
        act = act_layer * max(layers_local / 4, 1) + in_flight * boundary
    else:
        # no recompute: the pipeline ring holds FULL residuals for every
        # in-flight microbatch (schedules.py remat=False residual ring)
        act = act_layer * layers_local * in_flight
    # logits buffer (fp32 CE) on the last stage
    act += s * b * (vocab / tp) * 4

    total = p_bytes + g_bytes + o_bytes + act
    return {"params": p_bytes, "grads": g_bytes, "optimizer": o_bytes,
            "activations": act, "total": total, "n_params": n_params,
            "total_gb": total / 1e9}


def fits(model, axes: Dict[str, int], *, seq_len: int, microbatch_size: int,
         device: str = "v5p", recompute: str = "full",
         headroom: float = 0.85) -> Tuple[bool, Dict[str, float]]:
    """(fits, breakdown): per-device state must stay under
    headroom * HBM."""
    br = train_state_bytes(model, axes, seq_len=seq_len,
                           microbatch_size=microbatch_size,
                           recompute=recompute)
    budget = HBM_GB[device] * 1e9 * headroom
    br["budget_gb"] = budget / 1e9
    br["device"] = device
    return br["total"] <= budget, br


__all__ = ["abstract_mesh", "clean_spec", "param_plan", "train_state_bytes",
           "fits", "HBM_GB"]
