"""Ring attention: exact attention over sequence-sharded K/V.

Long-context requirement (SURVEY.md §5): the reference snapshot has no ring
attention (verified absent; FA2 + Megatron SP only) but the TPU build treats
"scale sequence length" as first-class. Design: shard_map over the "sep"
axis; each device holds q/k/v shards [b, s/n, h, d]; K/V shards rotate
around the ring with jax.lax.ppermute (ICI neighbor exchange) while each
device folds every block into its running online-softmax state.

Round-3 upgrade (was: dense [s_l, s_l] XLA scores per step): each ring step
now runs the Pallas FLASH kernel on the local (q-block, kv-block) pair —
flash_fwd_block returns the block's normalized output + logsumexp, and the
running state merges NORMALIZED partials:

    lse' = logaddexp(lse, lse_i)
    out' = out * exp(lse - lse') + out_i * exp(lse_i - lse')

Causal steps dispatch on the kv block's ORIGIN via lax.switch:
  src < my  -> full block, flash with causal=False
  src == my -> diagonal block, flash with causal=True
  src > my  -> fully masked: SKIPPED (no FLOPs — round 2 exp-suppressed
               these, wasting ~2x causal compute)

The backward is a hand-written ring (custom_vjp), as published ring/blockwise
attention does: dq accumulates locally while (k, v, dk, dv) rotate together
— after n steps each dk/dv shard has circled home carrying every device's
contribution. Each step reuses the flash backward kernels with the GLOBAL
(out, lse) residuals, so no dense [s_l, s_l] score matrix is ever
materialized in either direction.

The dense-XLA path remains as fallback for shapes the kernel doesn't
support (indivisible blocks) and runs under interpret on CPU test meshes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from .mesh import current_mesh

NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask):
    """One q-block vs one kv-block, returning (unnormalized acc, m, l).
    q: [b, sq, h, d]; k/v: [b, sk, h_kv, d]; mask [sq, sk] or
    [b, sq, sk] (dense fallback path). GQA kv heads broadcast here — at
    the block, so the rotating ring shards stay h_kv-sized (the flash
    path leaves broadcasting to the kernel the same way)."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        if mask.ndim == 2:
            mask = mask[None]
        s = jnp.where(mask[:, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [b,h,sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                                   # [b,h,sq]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)      # [b,sq,h,d]
    return acc, m, l


def _merge(state, acc, m, l):
    """Fold a new block's (acc, m, l) into the running online-softmax state
    (dense fallback path)."""
    acc0, m0, l0 = state
    m_new = jnp.maximum(m0, m)
    a0 = jnp.exp(m0 - m_new)
    a1 = jnp.exp(m - m_new)
    acc_new = acc0 * a0.transpose(0, 2, 1)[..., None] + acc * a1.transpose(0, 2, 1)[..., None]
    l_new = l0 * a0 + l * a1
    return acc_new, m_new, l_new


def _flash_blocks_ok(sl: int, h: int, h_kv: int, d: int,
                     has_seg: bool = False,
                     interpret: bool = False) -> tuple:
    """Pick (block_q, block_k) for the per-device flash blocks, or None if
    the local shapes can't satisfy the kernel's divisibility rules. With
    segment ids on real hardware, block_k must additionally be
    128-aligned or equal to the local length (Mosaic lane rule for the
    kv-segment tile)."""
    if h % h_kv:
        return None
    bq = next((c for c in (512, 256, 128, 64, 32, 16, 8) if sl % c == 0),
              None)
    bk = bq
    if bq is None or d not in (32, 64, 128, 256):
        return None
    if has_seg and not interpret and bk % 128 and bk != sl:
        # bk was already the LARGEST candidate dividing sl, so a
        # 128-multiple cannot divide sl either — no recovery possible
        return None
    return bq, bk


def _merge_norm(out0, lse0, out1, lse1):
    """Merge two NORMALIZED partial attentions given their logsumexps.
    out: [b, sl, h, d] f32; lse: [b, h, sl] f32."""
    lse_new = jnp.logaddexp(lse0, lse1)
    # a fully-skipped state has lse=NEG_INF: exp(NEG_INF - lse_new) -> 0
    w0 = jnp.exp(lse0 - lse_new)
    w1 = jnp.exp(lse1 - lse_new)
    wt = lambda w: jnp.moveaxis(w, 1, 2)[..., None]     # -> [b, sl, h, 1]
    return out0 * wt(w0) + out1 * wt(w1), lse_new


def _ring_flash(pos_l, q_l, k_l, v_l, qseg_l, kseg_l, axis, n, causal,
                scale, bq, bk, interpret):
    """shard_map-local ring attention on flash blocks with a hand-written
    ring VJP. All inputs are the per-device shards [b, sl, h(_kv), d];
    ``qseg_l``/``kseg_l`` [b, sl] (or None) carry packed-sequence segment
    ids — kseg rotates WITH its k/v block, and the kernel masks
    cross-segment pairs in VMEM (no dense mask in HBM). ``pos_l`` is the
    device's [1] shard of ``arange(n)`` over the ring axis — the ring
    index arrives as DATA because ``jax.lax.axis_index`` under a
    partially-manual legacy shard_map lowers to a bare PartitionId the
    SPMD partitioner rejects (jax < 0.6; same program either way on
    modern releases)."""
    from ..ops.pallas.flash_attention import (flash_bwd_block,
                                              flash_fwd_block)

    has_seg = qseg_l is not None
    perm = [(i, (i + 1) % n) for i in range(n)]          # rotate rightward
    # the flash-path shard_map runs check_vma=False (pallas_call out_shapes
    # carry no vma annotation), so no pcast bookkeeping is needed
    vary = lambda x: x

    def step_fwd(my, t, q_l, k_cur, v_cur, ks_cur):
        """(out_i f32, lse_i) for the kv block that originated on device
        (my - t) mod n; fully-masked causal blocks are skipped."""
        segs = dict(q_seg=qseg_l, kv_seg=ks_cur) if has_seg else {}

        def full(_):
            o, s = flash_fwd_block(q_l, k_cur, v_cur, scale, False, bq, bk,
                                   interpret, **segs)
            return o.astype(jnp.float32), s

        def diag(_):
            o, s = flash_fwd_block(q_l, k_cur, v_cur, scale, True, bq, bk,
                                   interpret, **segs)
            return o.astype(jnp.float32), s

        def skip(_):
            b, sl, h, d = q_l.shape
            return (jnp.zeros((b, sl, h, d), jnp.float32),
                    jnp.full((b, h, sl), NEG_INF, jnp.float32))

        if not causal:
            return full(None)
        src = (my - t) % n
        case = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
        return jax.lax.switch(case, (full, diag, skip), None)

    # a dummy [b, 0] int array stands in for absent segs so the scan
    # carry structure is static either way
    def _seg0(x):
        return jnp.zeros((x.shape[0], 0), jnp.int32)

    @jax.custom_vjp
    def ring(pos_l, q_l, k_l, v_l, qs_l, ks_l):
        out, lse = _ring_fwd(pos_l, q_l, k_l, v_l, qs_l, ks_l)[0]
        return out.astype(q_l.dtype)

    def _ring_fwd(pos_l, q_l, k_l, v_l, qs_l, ks_l):
        my = pos_l[0, 0]
        b, sl, h, d = q_l.shape
        out0 = vary(jnp.zeros((b, sl, h, d), jnp.float32))
        lse0 = vary(jnp.full((b, h, sl), NEG_INF, jnp.float32))

        def body(carry, t):
            out, lse, k_cur, v_cur, ks_cur = carry
            o_i, lse_i = step_fwd(my, t, q_l, k_cur, v_cur,
                                  ks_cur if has_seg else None)
            out, lse = _merge_norm(out, lse, o_i, lse_i)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            ks_nxt = jax.lax.ppermute(ks_cur, axis, perm)
            return (out, lse, k_nxt, v_nxt, ks_nxt), None

        (out, lse, _, _, _), _ = jax.lax.scan(
            body, (out0, lse0, k_l, v_l,
                   ks_l if has_seg else _seg0(k_l)), jnp.arange(n))
        return (out, lse), None

    def ring_fwd_rule(pos_l, q_l, k_l, v_l, qs_l, ks_l):
        (out, lse), _ = _ring_fwd(pos_l, q_l, k_l, v_l, qs_l, ks_l)
        return out.astype(q_l.dtype), (pos_l, q_l, k_l, v_l, qs_l, ks_l,
                                       out, lse)

    def ring_bwd_rule(res, dout):
        pos_l, q_l, k_l, v_l, qs_l, ks_l, out, lse = res
        my = pos_l[0, 0]
        out_c = out.astype(q_l.dtype)
        dout_c = dout.astype(q_l.dtype)

        def step_bwd(t, k_cur, v_cur, ks_cur):
            # qs_l (the RESIDUAL) — never the enclosing trace's qseg_l: a
            # custom_vjp bwd rule is traced in its own context, and
            # closing over a forward-trace tracer leaks it (hit live
            # under the Trainer's donated step)
            segs = dict(q_seg=qs_l, kv_seg=ks_cur) if has_seg else {}

            def full(_):
                return flash_bwd_block(q_l, k_cur, v_cur, out_c, lse, dout_c,
                                       scale, False, bq, bk, interpret,
                                       **segs)

            def diag(_):
                return flash_bwd_block(q_l, k_cur, v_cur, out_c, lse, dout_c,
                                       scale, True, bq, bk, interpret,
                                       **segs)

            def skip(_):
                return (jnp.zeros_like(q_l), jnp.zeros_like(k_cur),
                        jnp.zeros_like(v_cur))

            if not causal:
                return full(None)
            src = (my - t) % n
            case = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            return jax.lax.switch(case, (full, diag, skip), None)

        dq0 = vary(jnp.zeros(q_l.shape, jnp.float32))
        dk0 = vary(jnp.zeros(k_l.shape, jnp.float32))
        dv0 = vary(jnp.zeros(v_l.shape, jnp.float32))

        def body(carry, t):
            dq, k_cur, v_cur, ks_cur, dk_cur, dv_cur = carry
            dq_i, dk_i, dv_i = step_bwd(t, k_cur, v_cur,
                                        ks_cur if has_seg else None)
            dq = dq + dq_i.astype(jnp.float32)
            dk_cur = dk_cur + dk_i.astype(jnp.float32)
            dv_cur = dv_cur + dv_i.astype(jnp.float32)
            # dk/dv ride WITH their kv block: after n rotations total they
            # are back on the block's home device holding every device's
            # contribution
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            ks_nxt = jax.lax.ppermute(ks_cur, axis, perm)
            dk_nxt = jax.lax.ppermute(dk_cur, axis, perm)
            dv_nxt = jax.lax.ppermute(dv_cur, axis, perm)
            return (dq, k_nxt, v_nxt, ks_nxt, dk_nxt, dv_nxt), None

        (dq, _, _, _, dk, dv), _ = jax.lax.scan(
            body, (dq0, k_l, v_l, ks_l if has_seg else _seg0(k_l),
                   dk0, dv0), jnp.arange(n))
        import numpy as _np
        zseg = lambda x: _np.zeros(x.shape, jax.dtypes.float0)
        return (zseg(pos_l), dq.astype(q_l.dtype), dk.astype(k_l.dtype),
                dv.astype(v_l.dtype), zseg(qs_l), zseg(ks_l))

    ring.defvjp(ring_fwd_rule, ring_bwd_rule)
    return ring(pos_l, q_l, k_l, v_l,
                qseg_l if has_seg else _seg0(q_l),
                kseg_l if has_seg else _seg0(k_l))


def ring_attention(q, k, v, causal: bool = True, axis: str = "sep",
                   scale: Optional[float] = None, mesh=None,
                   interpret: Optional[bool] = None, segment_ids=None):
    """Exact attention with K/V rotating over the ``axis`` ring.

    q/k/v: [b, s, h, d] GLOBAL arrays sharded (or shardable) along s over
    ``axis``. Returns [b, s, h, d] with the same sharding.

    ``segment_ids`` [b, s] enables PACKED sequences under sequence
    parallelism: ids shard along s with q (query side) and rotate around
    the ring with their k/v blocks (kv side); the flash kernel masks
    cross-segment pairs in VMEM. Causal block skipping still applies —
    packing composes with the ring at full speed.
    """
    hm = current_mesh() if mesh is None else mesh
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if hm is None or hm.axis_size(axis) <= 1:
        from ..ops.attention import _sdpa_xla
        return _sdpa_xla(q, k, v, causal=causal, scale=scale,
                         segment_ids=segment_ids)

    n = hm.axis_size(axis)
    mesh_ = hm.mesh
    if interpret is None:
        from ..ops.registry import backend_kind
        interpret = backend_kind() != "tpu"

    b, s, h, _ = q.shape
    h_kv = k.shape[2]
    sl = s // n
    has_seg = segment_ids is not None
    if has_seg:
        segment_ids = jnp.asarray(segment_ids, jnp.int32)
    blocks = _flash_blocks_ok(sl, h, h_kv, d, has_seg=has_seg,
                              interpret=interpret)

    # Legacy jaxlib (< 0.6) cannot lower collective-permute inside a
    # partially-manual shard_map when ANOTHER mesh axis has size > 1
    # (hlo_sharding_util manual-subgroup check aborts; all-reduce-style
    # collectives are fine, which is why the tp paths work). On those
    # builds a hybrid mesh falls back to pure GSPMD: q stays
    # seq-sharded, XLA all-gathers K/V over the ring axis — the
    # Megatron-SP communication pattern, exact numerics, no manual
    # lowering. Modern jax (and any single-manual-axis mesh) keeps the
    # real ring.
    if jax.__version_info__ < (0, 6) and any(
            mesh_.shape[a] > 1 for a in mesh_.axis_names if a != axis):
        from ..ops.attention import _sdpa_xla
        return _sdpa_xla(q, k, v, causal=causal, scale=scale,
                         segment_ids=segment_ids)

    # each device's ring index as DATA (its [1, 1] shard of a [1, n]
    # arange over the ring axis): see _ring_flash's docstring for why
    # axis_index can't be used here. Rank 2 deliberately — a rank-1
    # axis-sharded operand trips XLA's manual-subgroup sharding check
    # under the legacy partial-manual lowering.
    ring_pos = jnp.arange(n, dtype=jnp.int32)[None]

    if blocks is not None:
        bq, bk = blocks
        kw = dict(axis=axis, n=n, causal=causal, scale=scale, bq=bq,
                  bk=bk, interpret=interpret)
        if has_seg:
            fn = shard_map(
                functools.partial(_ring_flash, **kw),
                mesh=mesh_, axis_names=frozenset({axis}),
                in_specs=(P(None, axis),)
                + (P(None, axis, None, None),) * 3
                + (P(None, axis), P(None, axis)),
                out_specs=P(None, axis, None, None), check_vma=False)
            return fn(ring_pos, q, k, v, segment_ids, segment_ids)
        fn = shard_map(
            functools.partial(_ring_flash, qseg_l=None, kseg_l=None, **kw),
            mesh=mesh_, axis_names=frozenset({axis}),
            in_specs=(P(None, axis),)
            + (P(None, axis, None, None),) * 3,
            out_specs=P(None, axis, None, None), check_vma=False)
        return fn(ring_pos, q, k, v)

    # dense fallback (unnormalized online-softmax ring; correctness-grade)
    def local_fn(pos_l, q_l, k_l, v_l, qs_l, ks_l):
        my = pos_l[0, 0]
        b, sl, h, _ = q_l.shape
        rows = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 1)
        diag_mask = cols <= rows                         # intra-block causal
        perm = [(i, (i + 1) % n) for i in range(n)]      # rotate kv rightward

        # the running state is per-device ("varying over the ring"): seed
        # it FROM the varying q shard instead of replicated constants —
        # data dependence is the one spelling every jax release agrees
        # marks it varying (modern vma typing and the legacy check_rep
        # tracker alike; jax.lax.pcast only exists on ≥0.7)
        zq = 0.0 * q_l.astype(jnp.float32)           # [b, sl, h, d]
        zrow = jnp.moveaxis(zq[..., 0], 1, 2)        # [b, h, sl]
        acc0 = zq
        m0 = zrow + NEG_INF
        l0 = zrow

        def step(carry, t):
            acc, m, l, k_cur, v_cur, ks_cur = carry
            src = (my - t) % n
            if causal:
                visible = src < my
                is_diag = src == my
                base = jnp.where(is_diag, diag_mask,
                                 jnp.broadcast_to(visible, diag_mask.shape))
            else:
                base = jnp.ones((sl, sl), bool)
            base = jnp.broadcast_to(base[None], (b, sl, sl))
            if has_seg:
                base = base & (qs_l[:, :, None] == ks_cur[:, None, :])
            a, bm, bl = _block_attn(q_l, k_cur, v_cur, scale, base)
            acc, m, l = _merge((acc, m, l), a, bm, bl)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            ks_nxt = jax.lax.ppermute(ks_cur, axis, perm)
            return (acc, m, l, k_nxt, v_nxt, ks_nxt), None

        (acc, m, l, _, _, _), _ = jax.lax.scan(
            step, (acc0, m0, l0, k_l, v_l, ks_l), jnp.arange(n))
        l_t = l.transpose(0, 2, 1)[..., None]            # [b,sl,h,1]
        safe = jnp.where(l_t == 0.0, 1.0, l_t)
        return (acc / safe).astype(q_l.dtype)

    fn = shard_map(local_fn, mesh=mesh_, axis_names=frozenset({axis}),
                   in_specs=(P(None, axis),)
                   + (P(None, axis, None, None),) * 3
                   + (P(None, axis), P(None, axis)),
                   out_specs=P(None, axis, None, None))
    # [b, 0] dummy when unpacked: nothing to shard, rotate, or read
    seg = segment_ids if has_seg else jnp.zeros((b, 0), jnp.int32)
    return fn(ring_pos, q, k, v, seg, seg)
