"""Ring attention: exact attention over sequence-sharded K/V.

Long-context requirement (SURVEY.md §5): the reference snapshot has no ring
attention (verified absent; FA2 + Megatron SP only) but the TPU build treats
"scale sequence length" as first-class. Design: shard_map over the "sep"
axis; each device holds q/k/v shards [b, s/n, h, d]; K/V shards rotate
around the ring with jax.lax.ppermute (ICI neighbor exchange) while each
device folds every block into its local online-softmax state (running max /
denominator — the flash-attention recurrence at ring scale). lax.scan keeps
the loop compiled; ppermute inside scan is differentiable, so the backward
pass is derived by JAX (it replays the ring in reverse via ppermute
transpose).

Causal masking is by *global* position: block j of K/V vs block i of Q is
fully visible when j < i, fully masked when j > i, diagonal when i == j.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from .mesh import current_mesh

NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask):
    """One q-block vs one kv-block, returning (unnormalized acc, m, l).
    q: [b, sq, h, d]; k/v: [b, sk, h, d]; mask broadcastable [sq, sk]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [b,h,sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                                   # [b,h,sq]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)      # [b,sq,h,d]
    return acc, m, l


def _merge(state, acc, m, l):
    """Fold a new block's (acc, m, l) into the running online-softmax state."""
    acc0, m0, l0 = state
    m_new = jnp.maximum(m0, m)
    a0 = jnp.exp(m0 - m_new)
    a1 = jnp.exp(m - m_new)
    acc_new = acc0 * a0.transpose(0, 2, 1)[..., None] + acc * a1.transpose(0, 2, 1)[..., None]
    l_new = l0 * a0 + l * a1
    return acc_new, m_new, l_new


def ring_attention(q, k, v, causal: bool = True, axis: str = "sep",
                   scale: Optional[float] = None, mesh=None):
    """Exact attention with K/V rotating over the ``axis`` ring.

    q/k/v: [b, s, h, d] GLOBAL arrays sharded (or shardable) along s over
    ``axis``. Returns [b, s, h, d] with the same sharding.
    """
    hm = current_mesh() if mesh is None else mesh
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if hm is None or hm.axis_size(axis) <= 1:
        from ..ops.attention import _sdpa_xla
        return _sdpa_xla(q, k, v, causal=causal, scale=scale)

    n = hm.axis_size(axis)
    mesh_ = hm.mesh

    def local_fn(q_l, k_l, v_l):
        # q_l/k_l/v_l: [b, s/n, h, d]
        my = jax.lax.axis_index(axis)
        b, sl, h, _ = q_l.shape
        rows = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 1)
        diag_mask = cols <= rows                         # intra-block causal
        perm = [(i, (i + 1) % n) for i in range(n)]      # rotate kv rightward

        # initial carry must be marked device-varying over the ring axis so
        # the scan carry type matches after the ppermute steps
        vary = lambda x: jax.lax.pcast(x, (axis,), to="varying")
        acc0 = vary(jnp.zeros((b, sl, h, d), jnp.float32))
        m0 = vary(jnp.full((b, h, sl), NEG_INF, jnp.float32))
        l0 = vary(jnp.zeros((b, h, sl), jnp.float32))

        def step(carry, t):
            acc, m, l, k_cur, v_cur = carry
            # k_cur originated on device (my - t) mod n
            src = (my - t) % n
            if causal:
                # block fully visible if src < my; masked if src > my
                visible = src < my
                is_diag = src == my
                base = jnp.where(is_diag, diag_mask,
                                 jnp.broadcast_to(visible, diag_mask.shape))
                a, bm, bl = _block_attn(q_l, k_cur, v_cur, scale, base)
                # suppress fully-masked blocks (src > my): m=-inf handles it
            else:
                a, bm, bl = _block_attn(q_l, k_cur, v_cur, scale, None)
            acc, m, l = _merge((acc, m, l), a, bm, bl)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (acc, m, l, k_nxt, v_nxt), None

        (acc, m, l, _, _), _ = jax.lax.scan(
            step, (acc0, m0, l0, k_l, v_l), jnp.arange(n))
        l_t = l.transpose(0, 2, 1)[..., None]            # [b,sl,h,1]
        safe = jnp.where(l_t == 0.0, 1.0, l_t)
        return (acc / safe).astype(q_l.dtype)

    # manual only over the ring axis; dp/fsdp batch shardings stay auto
    fn = shard_map(local_fn, mesh=mesh_, axis_names=frozenset({axis}),
                   in_specs=(P(None, axis, None, None),) * 3,
                   out_specs=P(None, axis, None, None))
    return fn(q, k, v)
