"""paddle_tpu.parallel — GSPMD parallelism (reference analogue:
python/paddle/distributed/ — fleet topology, auto_parallel api, collectives,
mp/sp layers, MoE, and the long-context attention the TPU build adds)."""

from .mesh import HybridMesh, current_mesh, init_parallel_env, AXES_ORDER
from .api import (shard_tensor, reshard, shard_layer, shard_optimizer_state,
                  param_spec_tree, Shard, Replicate, Partial, Placement)
from .mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,
                        RowParallelLinear, ParallelCrossEntropy,
                        parallel_cross_entropy,
                        parallel_fused_linear_cross_entropy,
                        scatter_seq, gather_seq,
                        ColumnSequenceParallelLinear, RowSequenceParallelLinear)
# top_k_gating is quarantined as the test oracle (ISSUE 20) — import it
# from paddle_tpu.parallel.moe explicitly if you really want the O(t*e*c)
# one-hot formulation; the package surface routes to the sort-based path.
from .moe import MoELayer, MoEMLP, top_k_routing
from .ring_attention import ring_attention
from .ulysses import ulysses_attention, ulysses_supported
from .pipeline import (LayerDesc, SharedLayerDesc, SegmentLayers,
                       PipelineStack, PipelineLayer, pipeline_spmd,
                       microbatch, unmicrobatch)
