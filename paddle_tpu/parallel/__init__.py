"""paddle_tpu.parallel — GSPMD parallelism (reference analogue:
python/paddle/distributed/ — fleet topology, auto_parallel api, collectives)."""

from .mesh import HybridMesh, current_mesh, init_parallel_env, AXES_ORDER
from .api import (shard_tensor, reshard, shard_layer, shard_optimizer_state,
                  param_spec_tree, Shard, Replicate, Partial, Placement)
