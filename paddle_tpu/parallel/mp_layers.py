"""Tensor-parallel (Megatron-style) layers.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:47, ColumnParallelLinear:333, RowParallelLinear:540,
ParallelCrossEntropy:741 — and mp_ops.py (_c_identity:83 fwd-identity/
bwd-allreduce, _mp_allreduce:285 fwd-allreduce/bwd-identity).

TPU-native design: the fwd/bwd collective pairs the reference implements as
custom PyLayers are exactly what GSPMD derives from sharding annotations, so
these layers are thin Layer subclasses that (a) annotate their weights with
("tp"-sharded) PartitionSpecs and (b) constrain their activations. The one
case where explicit collectives beat GSPMD — cross entropy over vocab-sharded
logits without materializing the gathered softmax (key memory saver for 128K
vocab) — uses shard_map + psum/pmax directly (see ParallelCrossEntropy /
parallel_cross_entropy).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from ..nn import initializer as I
from ..nn.layer import Layer
from .mesh import current_mesh


def _constrain_dim(x, dim: int, axis_name):
    """Constrain ONE tensor dim to a mesh axis (or replicate it when
    axis_name is None), leaving every other dim unconstrained so GSPMD keeps
    whatever batch/dp sharding it already derived — a full PartitionSpec of
    Nones would force an all-gather of the batch at every layer."""
    hm = current_mesh()
    if hm is None:
        return x
    if axis_name is not None and (axis_name not in hm.mesh.axis_names
                                  or hm.mesh.shape[axis_name] <= 1):
        return x
    dim = dim % x.ndim
    if isinstance(x, jax.core.Tracer):
        entries = [P.UNCONSTRAINED] * x.ndim
        entries[dim] = axis_name
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(hm.mesh, P(*entries)))
    # eager: merge with the array's existing spec
    cur = list(getattr(getattr(x, "sharding", None), "spec", ()) or ())
    cur += [None] * (x.ndim - len(cur))
    cur[dim] = axis_name
    return jax.device_put(x, NamedSharding(hm.mesh, P(*cur)))


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over "tp"
    (reference: mp_layers.py:47 — per-rank vocab range + allreduce)."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, dtype=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        init_w = weight_attr if isinstance(weight_attr, I.Initializer) \
            else I.Normal(0.0, 0.02)
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], dtype=dtype, initializer=init_w,
            sharding=("tp", "fsdp"))
        self._parameters["weight"].is_distributed = True

    def forward(self, ids):
        # GSPMD turns the gather over a vocab-sharded table into
        # dynamic-slice + masked psum — the reference's mask-and-allreduce
        # without hand-written collectives.
        return jnp.take(self.weight, ids, axis=0)


class ColumnParallelLinear(Layer):
    """Linear with output dim sharded over "tp" (reference: mp_layers.py:333;
    fwd identity / bwd allreduce comes out of GSPMD's partitioning)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = False, dtype=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        init_w = weight_attr if isinstance(weight_attr, I.Initializer) \
            else I.XavierUniform()
        self.weight = self.create_parameter(
            [in_features, out_features], dtype=dtype, initializer=init_w,
            sharding=("fsdp", "tp"))
        self._parameters["weight"].is_distributed = True
        if has_bias:
            self.bias = self.create_parameter([out_features], dtype=dtype,
                                              is_bias=True, sharding=("tp",))
            self._parameters["bias"].is_distributed = True
        else:
            self.add_parameter("bias", None)

    def forward(self, x):
        y = jnp.matmul(x, self.weight.astype(x.dtype))
        if self.bias is not None:
            y = y + self.bias.astype(y.dtype)
        y = _constrain_dim(y, -1, None if self.gather_output else "tp")
        return y


class RowParallelLinear(Layer):
    """Linear with input dim sharded over "tp" (reference: mp_layers.py:540;
    the fwd allreduce is inserted by GSPMD when the contraction dim is
    sharded)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = True, dtype=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        init_w = weight_attr if isinstance(weight_attr, I.Initializer) \
            else I.XavierUniform()
        self.weight = self.create_parameter(
            [in_features, out_features], dtype=dtype, initializer=init_w,
            sharding=("tp", "fsdp"))
        self._parameters["weight"].is_distributed = True
        if has_bias:
            # bias added after the reduce → replicated (reference semantics)
            self.bias = self.create_parameter([out_features], dtype=dtype,
                                              is_bias=True)
        else:
            self.add_parameter("bias", None)

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain_dim(x, -1, "tp")
        y = jnp.matmul(x, self.weight.astype(x.dtype))
        y = _constrain_dim(y, -1, None)
        if self.bias is not None:
            y = y + self.bias.astype(y.dtype)
        return y


# ---------------------------------------------------------------------------
# vocab-parallel cross entropy (explicit shard_map — the GSPMD exception)
# ---------------------------------------------------------------------------

def parallel_cross_entropy(logits, labels, mesh=None, axis: str = "tp",
                           ignore_index: int = -100):
    """CE over vocab-sharded logits without gathering them.

    Reference: ParallelCrossEntropy (mp_layers.py:741) backed by
    c_softmax_with_cross_entropy_op.cu — max-allreduce + sum-allreduce over
    the model-parallel group. Here: shard_map over the "tp" axis with
    lax.pmax/psum; each shard computes its local max / exp-sum / target
    logit, so the full softmax is never materialized (the memory saver for
    128K+ vocabularies).

    logits: [..., vocab] sharded on the last dim over ``axis``;
    labels: [...] global ids. Returns per-token loss [...].
    """
    hm = current_mesh() if mesh is None else mesh
    if hm is None or hm.axis_size(axis) <= 1:
        # single shard: plain stable CE
        logits32 = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits32, axis=-1)
        safe = jnp.where(labels == ignore_index, 0, labels)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        return jnp.where(labels == ignore_index, 0.0, nll)

    mesh_ = hm.mesh
    n_shards = hm.axis_size(axis)
    vocab = logits.shape[-1]
    shard_size = vocab // n_shards
    batch_spec = P(*([None] * (logits.ndim - 1)))

    def local_ce(logits_l, labels_l):
        # logits_l: [..., vocab/n]; labels_l: [...]
        idx = jax.lax.axis_index(axis)
        lo = idx * shard_size
        logits32 = logits_l.astype(jnp.float32)
        local_max = jnp.max(logits32, axis=-1)
        # stability shift only — not differentiated (pmax has no VJP)
        gmax = jax.lax.stop_gradient(
            jax.lax.pmax(jax.lax.stop_gradient(local_max), axis))
        shifted = logits32 - gmax[..., None]
        local_sumexp = jnp.sum(jnp.exp(shifted), axis=-1)
        gsumexp = jax.lax.psum(local_sumexp, axis)
        # target logit: only the owning shard contributes
        safe = jnp.where(labels_l == ignore_index, 0, labels_l)
        local_label = safe - lo
        in_range = (local_label >= 0) & (local_label < shard_size)
        gathered = jnp.take_along_axis(
            shifted, jnp.clip(local_label, 0, shard_size - 1)[..., None],
            axis=-1)[..., 0]
        target = jax.lax.psum(jnp.where(in_range, gathered, 0.0), axis)
        nll = jnp.log(gsumexp) - target
        return jnp.where(labels_l == ignore_index, 0.0, nll)

    # manual ONLY over the tp axis: other mesh axes (dp/fsdp/sep) stay
    # auto/GSPMD-managed so batch-dim shardings pass straight through —
    # no hidden all-gather of the global batch
    fn = shard_map(
        local_ce, mesh=mesh_, axis_names=frozenset({axis}),
        in_specs=(P(*([None] * (logits.ndim - 1)), axis), batch_spec),
        out_specs=batch_spec)
    return fn(logits, labels)


def parallel_fused_linear_cross_entropy(hidden, w, labels, mesh=None,
                                        axis: str = "tp",
                                        ignore_index: int = -100,
                                        block_n=None, block_v=None,
                                        impl=None, interpret: bool = False):
    """Fused CE(hidden @ w, labels) over a VOCAB-SHARDED w — the fused
    loss head's tensor-parallel composition: neither the full logits NOR a
    full vocab shard of them ever materializes.

    parallel_cross_entropy (above) still receives [..., vocab]-sharded
    logits, i.e. the projection has already been paid and stored. Here each
    tp shard runs the blockwise fused kernel (ops/pallas/fused_vocab_ce.py)
    over ITS [H, V/tp] weight shard — per-shard online log-sum-exp + local
    target gather in O(block_v) memory — and the shards combine with the
    same pmax/psum pattern the reference's c_softmax_with_cross_entropy
    uses: global lse via max-shifted psum of exp(local_lse), target logit
    via psum (only the owning shard contributes a nonzero tgt).

    hidden: [..., H] replicated over ``axis``; w: [H, V] sharded on its
    LAST dim over ``axis``; labels: [...] global ids. Returns per-token
    nll [...] (f32). Differentiable in hidden and w (the fused primitive's
    custom_vjp recomputes per-block logits; psum/pmax combine via jax AD —
    the pmax stability shift is stop_gradient'd, as in
    parallel_cross_entropy)."""
    from ..ops.pallas.fused_vocab_ce import (fused_linear_cross_entropy,
                                             lse_and_target, resolve_impl)
    hm = current_mesh() if mesh is None else mesh
    if hm is None or hm.axis_size(axis) <= 1:
        return fused_linear_cross_entropy(
            hidden, w, labels, ignore_index=ignore_index, reduction="none",
            block_n=block_n, block_v=block_v, impl=impl, interpret=interpret)

    n_shards = hm.axis_size(axis)
    vocab = w.shape[-1]
    if vocab % n_shards:
        raise ValueError(f"vocab {vocab} not divisible by {axis} degree "
                         f"{n_shards}")
    shard_size = vocab // n_shards
    hd = hidden.shape[-1]
    n_tok = int(np.prod(labels.shape))
    if block_n is None or block_v is None:
        from ..ops.pallas.autotune import fused_vocab_ce_config
        tn, tv = fused_vocab_ce_config(n_tok, hd, shard_size,
                                       str(hidden.dtype))
        block_n = block_n if block_n is not None else tn
        block_v = block_v if block_v is not None else tv
    # the block size must DIVIDE the per-shard vocab: the non-TP path pads
    # W up to a block multiple, but a pad op inside this partial-auto
    # manual region crashes the SPMD partitioner (IsManualSubgroup check).
    # Fall back to one shard-sized block (== parallel_cross_entropy's
    # per-shard working set) when nothing divides.
    if shard_size % block_v:
        block_v = next((c for c in (2048, 1024, 512, 256, 128, 64, 32, 16, 8)
                        if c <= shard_size and shard_size % c == 0),
                       shard_size)
    if impl is None:
        impl = resolve_impl(n_tok, hd, shard_size, hidden.dtype,
                            block_n, block_v, interpret)
    if impl == "xla":
        # the scan-based fallback lowers to a while loop, which the SPMD
        # partitioner rejects inside this partial-auto manual region —
        # unroll the (V/tp)/block_v vocab-block loop instead
        impl = "xla_unroll"
    batch_spec = P(*([None] * labels.ndim))
    # each shard's vocab offset arrives as DATA (an axis-sharded [n_shards]
    # array -> [1] per shard) instead of via lax.axis_index: the PartitionId
    # lowering of axis_index is rejected by the SPMD partitioner when the
    # manual region also contains the vocab-block scan
    offsets = jnp.arange(n_shards, dtype=jnp.int32) * shard_size

    def local_fn(h_l, w_l, labels_l, off_l):
        lo = off_l[0]
        lab = labels_l.reshape(-1).astype(jnp.int32)
        valid = lab != ignore_index
        # ignored rows map below every shard's range (-1 - lo <= -1)
        local = jnp.where(valid, lab, -1) - lo
        h2 = h_l.reshape(-1, hd)
        lse_l, tgt_l = lse_and_target(h2, w_l, local, block_n, block_v,
                                      impl, interpret)
        gmax = jax.lax.stop_gradient(
            jax.lax.pmax(jax.lax.stop_gradient(lse_l), axis))
        gse = jax.lax.psum(jnp.exp(lse_l - gmax), axis)
        lse = gmax + jnp.log(gse)
        tgt = jax.lax.psum(tgt_l, axis)
        nll = jnp.where(valid, lse - tgt, 0.0)
        return nll.reshape(labels_l.shape)

    fn = shard_map(
        local_fn, mesh=hm.mesh, axis_names=frozenset({axis}),
        in_specs=(P(*([None] * hidden.ndim)), P(None, axis), batch_spec,
                  P(axis)),
        out_specs=batch_spec)
    return fn(hidden, w, labels, offsets)


class ParallelCrossEntropy(Layer):
    """Layer wrapper (reference: mp_layers.py:741)."""

    def __init__(self, mp_group=None, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        return parallel_cross_entropy(logits, labels,
                                      ignore_index=self.ignore_index)


# ---------------------------------------------------------------------------
# sequence-parallel utilities
# ---------------------------------------------------------------------------

def scatter_seq(x, axis_name: str = "sep", dim: int = 1):
    """Shard activations along the seq dim — reference ScatterOp
    (fleet/utils/sequence_parallel_utils.py:85): with GSPMD this is a
    sharding constraint; the reduce-scatter/allgather pairs appear in the
    compiled program."""
    return _constrain_dim(x, dim, axis_name)


def gather_seq(x, dim: int = 1):
    """Re-replicate the seq dim — reference GatherOp/AllGatherOp."""
    return _constrain_dim(x, dim, None)


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Column-parallel linear whose input is seq-sharded (reference:
    sequence_parallel_utils.py:230 — allgather along seq before the matmul,
    emitted by GSPMD from the constraints)."""

    def forward(self, x):
        x = gather_seq(x)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Row-parallel linear whose output is seq-sharded (reference:
    sequence_parallel_utils.py:340 — reduce-scatter along seq)."""

    def forward(self, x):
        y = super().forward(x)
        return scatter_seq(y)
