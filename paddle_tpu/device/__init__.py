"""paddle_tpu.device — device management (reference: python/paddle/device/).

Reference surface: set_device/get_device, device_count, synchronize, CUDA
streams/events (device/cuda/streams.py), device properties, custom-device
discovery. TPU-native redesign: devices are XLA PjRt devices; "streams" do
not exist in the XLA execution model (the runtime orders execution per
device, and overlap is expressed inside the compiled program), so Stream /
Event keep the reference API shape as synchronization-correct shims built on
``block_until_ready`` — code written against them stays correct, XLA keeps
the scheduling.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax

from ..framework import set_device, get_device, device_count

__all__ = [
    "set_device", "get_device", "device_count", "synchronize", "get_device_properties",
    "get_available_device", "get_available_custom_device", "get_all_device_type",
    "get_all_custom_device_type", "is_compiled_with_cuda", "is_compiled_with_rocm",
    "is_compiled_with_xpu", "is_compiled_with_ipu", "is_compiled_with_custom_device", "Stream", "Event",
    "current_stream", "stream_guard", "memory_stats", "XPUPlace", "CPUPlace",
    "TPUPlace", "CUDAPlace",
]


def _resolve(device=None):
    if device is None:
        return jax.devices()[0]
    if isinstance(device, (int,)):
        return jax.devices()[device]
    if hasattr(device, "platform"):
        return device
    name = str(device)
    plat = name.split(":")[0]
    idx = int(name.split(":")[1]) if ":" in name else 0
    plat = {"gpu": "gpu", "xpu": "tpu", "tpu": "tpu", "cpu": "cpu"}.get(plat, plat)
    return jax.devices(plat)[idx]


def synchronize(device=None) -> None:
    """Block until all queued work on the device is done (reference:
    paddle.device.synchronize). XLA orders execution per device, so syncing
    means flushing: round-trip a trivial computation through the device."""
    d = _resolve(device)
    jax.block_until_ready(jax.device_put(0, d))


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return [p for p in get_all_device_type() if p not in ("cpu", "gpu", "tpu")]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()
            if d.platform not in ("cpu", "gpu", "tpu")]


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str) -> bool:
    return device_type in get_all_custom_device_type()


@dataclasses.dataclass
class DeviceProperties:
    name: str
    platform: str
    id: int
    process_index: int
    coords: Optional[tuple] = None
    core_on_chip: Optional[int] = None
    memory_stats: Optional[dict] = None


def get_device_properties(device=None) -> DeviceProperties:
    d = _resolve(device)
    stats = None
    try:
        stats = d.memory_stats()
    except Exception:
        pass
    return DeviceProperties(
        name=getattr(d, "device_kind", d.platform), platform=d.platform,
        id=d.id, process_index=d.process_index,
        coords=getattr(d, "coords", None),
        core_on_chip=getattr(d, "core_on_chip", None), memory_stats=stats)


def memory_stats(device=None) -> dict:
    """HBM usage for a device (allocator stats slot: reference
    paddle/fluid/memory/stats.h). Empty dict on backends without stats."""
    try:
        return dict(_resolve(device).memory_stats() or {})
    except Exception:
        return {}


# ---------------------------------------------------------------------------
# Stream / Event shims
# ---------------------------------------------------------------------------

class Stream:
    """API-shape shim for paddle.device.Stream. XLA has no user-visible
    streams; ``synchronize``/``wait_event``/``wait_stream`` provide the same
    ordering guarantees via block_until_ready."""

    def __init__(self, device=None, priority: int = 2):
        self.device = _resolve(device)
        self.priority = priority
        self._last = None

    def record_event(self, event: "Event" = None) -> "Event":
        event = event or Event()
        event.record(self)
        return event

    def wait_event(self, event: "Event") -> None:
        event.synchronize()

    def wait_stream(self, stream: "Stream") -> None:
        stream.synchronize()

    def synchronize(self) -> None:
        synchronize(self.device)

    def track(self, arrays) -> None:
        """Associate in-flight arrays with this stream so synchronize() can
        wait for them (TPU addition; XLA arrays are futures already)."""
        self._last = arrays


class Event:
    """API-shape shim for paddle.device.Event."""

    def __init__(self, device=None, enable_timing: bool = False,
                 blocking: bool = False, interprocess: bool = False):
        self.device = _resolve(device)
        self.enable_timing = enable_timing
        self._recorded_on: Optional[Stream] = None
        self._t = None

    def record(self, stream: Optional[Stream] = None) -> None:
        import time
        self._recorded_on = stream
        if self.enable_timing:
            self._t = time.perf_counter()

    def query(self) -> bool:
        return True

    def synchronize(self) -> None:
        if self._recorded_on is not None:
            self._recorded_on.synchronize()
        else:
            synchronize(self.device)

    def elapsed_time(self, end: "Event") -> float:
        if self._t is None or end._t is None:
            raise RuntimeError("Event timing not enabled")
        return (end._t - self._t) * 1000.0


_current_stream: dict[int, Stream] = {}


def current_stream(device=None) -> Stream:
    d = _resolve(device)
    if d.id not in _current_stream:
        _current_stream[d.id] = Stream(d)
    return _current_stream[d.id]


class stream_guard:
    """Context manager parity shim (reference: paddle.device.stream_guard)."""

    def __init__(self, stream: Stream):
        self.stream = stream

    def __enter__(self):
        self._prev = _current_stream.get(self.stream.device.id)
        _current_stream[self.stream.device.id] = self.stream
        return self.stream

    def __exit__(self, *exc):
        if self._prev is None:
            _current_stream.pop(self.stream.device.id, None)
        else:
            _current_stream[self.stream.device.id] = self._prev
        return False


# ---------------------------------------------------------------------------
# Place classes (reference: paddle.CUDAPlace/CPUPlace/XPUPlace) — thin
# wrappers resolving to jax devices so ported code can keep constructing them
# ---------------------------------------------------------------------------

# ONE Place family for the whole package: these are the same classes a
# plain `import paddle` exposes (base.py) — a second definition here made
# paddle.CPUPlace() != paddle.device.CPUPlace()
from ..base import (_Place, CPUPlace, TPUPlace, CUDAPlace,  # noqa: E402
                    CUDAPinnedPlace, IPUPlace, XPUPlace)


def get_cudnn_version():
    """No cuDNN in the TPU stack (reference: device/__init__.py
    get_cudnn_version returns None when CUDA is absent)."""
    return None


def is_compiled_with_cinn() -> bool:
    """CINN's compiler slot is filled by XLA (SURVEY §2.2 design)."""
    return False


def is_compiled_with_distribute() -> bool:
    return True


def is_compiled_with_ipu() -> bool:
    return False


def set_stream(stream=None):
    """XLA orders work on internal streams; kept for API parity
    (reference: device/__init__.py set_stream)."""
    return stream


from ..base import IPUPlace  # noqa: E402 — place shim (no IPU backend)


from . import cuda  # noqa: E402  paddle.device.cuda path
from . import xpu  # noqa: E402  paddle.device.xpu path
