"""Adapters pinning the modern jax surface onto older installed releases.

The codebase targets the current `jax.shard_map` API — keyword-only, with
``axis_names`` naming the *manual* axes and ``check_vma`` — while older jax
releases (< 0.6) only ship ``jax.experimental.shard_map.shard_map`` with the
complementary ``auto`` set and ``check_rep``. Importing this module installs
a signature adapter as ``jax.shard_map`` when the attribute is missing, so
every call site (including tests) can use the one modern spelling.
"""

from __future__ import annotations

import jax


def _install_shard_map() -> None:
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(f, /, *, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, check_vma=None, check_rep=None, auto=None):
        if check_rep is None:
            check_rep = True if check_vma is None else bool(check_vma)
        if auto is None and axis_names is not None and mesh is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_rep)
        if auto:
            kw["auto"] = frozenset(auto)
        mapped = _legacy(f, **kw)
        if auto:
            # the legacy EAGER impl raises NotImplementedError for non-empty
            # auto; tracing is the supported path, so route eager calls
            # through jit (inside an outer jit this just inlines)
            return jax.jit(mapped)
        return mapped

    jax.shard_map = shard_map


def _install_pcast() -> None:
    """``jax.lax.pcast`` (vma re-typing, jax ≥ 0.7) has no effect on
    values — on releases without the varying-manual-axes type system the
    identity is the exact semantics (the ring-attention dense fallback
    uses it to mark its carry varying over the ring axis)."""
    if hasattr(jax.lax, "pcast"):
        return

    def pcast(x, axes=None, *, to=None):
        del axes, to
        return x

    jax.lax.pcast = pcast


def _install_jax_ffi() -> None:
    """jax<0.5 ships the FFI surface as ``jax.extend.ffi``; alias it to the
    modern ``jax.ffi`` spelling (same functions: ffi_call, ffi_lowering,
    include_dir, register_ffi_target, pycapsule)."""
    import importlib
    import sys
    try:
        importlib.import_module("jax.ffi")
        return
    except ImportError:
        pass
    try:
        from jax.extend import ffi as _ffi
    except ImportError:
        return
    sys.modules["jax.ffi"] = _ffi
    jax.ffi = _ffi


def install_pallas_compat() -> None:
    """Alias the modern ``pltpu.CompilerParams`` name onto releases that
    only ship ``TPUCompilerParams`` (same dataclass, renamed in jax 0.6).
    Called by ops.pallas at import so plain ``import paddle_tpu`` never
    pays the pallas import."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:          # no pallas on this build
        return
    if not hasattr(pltpu, "CompilerParams") and hasattr(pltpu,
                                                        "TPUCompilerParams"):
        pltpu.CompilerParams = pltpu.TPUCompilerParams


_install_shard_map()
_install_pcast()
_install_jax_ffi()
