"""Persistent compile / AOT cache for jitted training steps.

Reference analogue: the new executor's program cache + CINN's compiled-kernel
serialization (SURVEY §L5) — a process start must not re-pay tracing and XLA
compilation for a step function it has compiled before. Three layers, each
opt-in and independently useful:

1. **In-process executable cache** — ``acquire()`` maps a *fingerprint*
   (model/optimizer structure + hyperparameters + argument avals + backend)
   to a ``jax.stages.Compiled`` executable. A second cold construction of
   the same step function (fresh ``Trainer`` over an identically-shaped
   model) reuses the executable: no retrace, no recompile. Hit/miss/trace
   counters make this testable.

2. **On-disk AOT artifacts** — ``save_aot``/``load_aot`` serialize the step
   via ``jax.export`` next to the checkpoint directory, so a preempted
   worker's relaunch deserializes StableHLO instead of re-tracing Python.
   Artifacts are keyed by the same fingerprint (stored in a sidecar meta
   JSON) plus the jax version and backend; any mismatch falls through to a
   normal compile — a stale artifact can never produce wrong numerics.

3. **XLA persistent compilation cache** — ``configure_compilation_cache``
   wires ``jax_compilation_cache_dir`` (env ``PT_COMPILE_CACHE_DIR`` or an
   explicit path) so even the StableHLO→executable step is disk-cached
   across processes. Strictly a no-op when no directory is configured.

Fingerprints are deliberately conservative: model class + config scalars +
sublayer structure + optimizer class/hyperparameters + donation/accumulation
flags + full argument aval signature. Anything that changes the traced
program should change the fingerprint; anything that doesn't (buffer
contents, devices' wall clock) must not.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from ..observability.goodput import ledger as _goodput_ledger
from ..observability.metrics import REGISTRY as _REG

__all__ = [
    "acquire", "aval_signature", "fingerprint", "configure_compilation_cache",
    "save_aot", "load_aot", "stats", "reset_stats", "clear", "note_trace",
    "explain_fingerprint_change",
]

_LOCK = threading.Lock()
_EXECUTABLES: "OrderedDict[str, Any]" = OrderedDict()
_MAX_EXECUTABLES = 64

_STATS = {"hits": 0, "misses": 0, "aot_hits": 0, "traces": 0}
_PERSISTENT_DIR: Optional[str] = None
# why the last stale AOT artifact was rejected (ISSUE 8: "a fingerprint
# changed" is useless — operators need to know WHICH key drifted):
# {"name": ..., "diff": [path: old -> new, ...]} or None
_LAST_STALE: Optional[Dict[str, Any]] = None

AOT_META_SUFFIX = ".meta.json"
AOT_BIN_SUFFIX = ".stablehlo.bin"


def note_trace() -> None:
    """Called from inside step-function bodies: increments once per Python
    trace (jit retrace, scan-body trace, export trace). The proof counter
    for "this path did not rebuild"."""
    with _LOCK:
        _STATS["traces"] += 1


def stats() -> Dict[str, Any]:
    with _LOCK:
        out = dict(_STATS)
    out["persistent_dir"] = _PERSISTENT_DIR
    out["executables"] = len(_EXECUTABLES)
    out["last_stale"] = _LAST_STALE
    return out


def reset_stats() -> None:
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0


def clear() -> None:
    """Drop cached executables + counters (tests use this to simulate a
    process restart without spawning one)."""
    global _LAST_STALE
    with _LOCK:
        _EXECUTABLES.clear()
        for k in _STATS:
            _STATS[k] = 0
        _LAST_STALE = None


# -- fingerprinting ----------------------------------------------------------

def aval_signature(tree) -> Tuple:
    """Stable (treedef, shape, dtype, sharding) signature of a pytree of
    arrays / ShapeDtypeStructs — the dynamic half of a fingerprint.
    Sharding is part of the key: a Compiled executable is specialized to
    its inputs' placement, and two same-shape trainers on different meshes
    must not share one. Python-scalar leaves (jit-legal weak-typed args)
    key on their TYPE, not value — jit does not bake the value either."""
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    sig = tuple(
        (str(l.shape), str(l.dtype), str(getattr(l, "sharding", None)))
        if hasattr(l, "shape") and hasattr(l, "dtype")
        else ("py", type(l).__name__)
        for l in leaves)
    return (str(treedef), sig)


def to_avals(tree):
    """Sharding-preserving aval view of a pytree: arrays become
    ShapeDtypeStructs carrying their placement (a Compiled executable is
    placement-specialized); python scalars pass through unchanged
    (jit-legal weak-typed arguments). The ONE conversion used by both the
    AOT serializer and Trainer.precompile, so the artifact and the
    in-process executable can never diverge."""
    import jax

    def conv(l):
        if hasattr(l, "shape") and hasattr(l, "dtype"):
            return jax.ShapeDtypeStruct(
                l.shape, l.dtype, sharding=getattr(l, "sharding", None))
        return l
    return jax.tree.map(conv, tree)


def fingerprint(parts) -> str:
    """sha256 over a JSON rendering of ``parts`` (nested tuples/dicts of
    scalars and strings)."""
    blob = json.dumps(parts, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()


def _norm_parts(parts):
    """JSON-normalized view (tuples become lists, keys stay) so parts
    saved to a meta sidecar and parts computed live compare structurally."""
    return json.loads(json.dumps(parts, sort_keys=True, default=str))


def explain_fingerprint_change(old_parts, new_parts, limit: int = 12):
    """Human-readable paths where two fingerprint part trees diverge —
    the "WHY did this recompile / reject the AOT artifact" report. Parts
    are labeled dicts (Trainer._fp_parts), so paths read like
    ``static.env.PT_NAIVE_LOSS_HEAD: False -> True`` instead of a tuple
    index. Returns at most ``limit`` lines."""
    diffs: list = []

    def walk(a, b, path):
        if len(diffs) >= limit:
            return
        if isinstance(a, dict) and isinstance(b, dict):
            for k in sorted(set(a) | set(b), key=str):
                if len(diffs) >= limit:
                    return
                p = f"{path}.{k}" if path else str(k)
                if k not in a:
                    diffs.append(f"{p}: <absent> -> {b[k]!r}"[:240])
                elif k not in b:
                    diffs.append(f"{p}: {a[k]!r} -> <absent>"[:240])
                elif a[k] != b[k]:
                    walk(a[k], b[k], p)
        elif isinstance(a, list) and isinstance(b, list):
            if len(a) != len(b):
                diffs.append(f"{path}: length {len(a)} -> {len(b)}")
                return
            for i, (x, y) in enumerate(zip(a, b)):
                if x != y:
                    walk(x, y, f"{path}[{i}]")
        elif a != b:
            diffs.append(f"{path}: {a!r} -> {b!r}"[:300])

    walk(_norm_parts(old_parts), _norm_parts(new_parts), "")
    return diffs


# -- in-process executable cache ---------------------------------------------

def _store(fp: str, fn) -> None:
    with _LOCK:
        _EXECUTABLES[fp] = fn
        _EXECUTABLES.move_to_end(fp)
        while len(_EXECUTABLES) > _MAX_EXECUTABLES:
            _EXECUTABLES.popitem(last=False)


def acquire(fp: str, jitted, args, *, aot_dir: Optional[str] = None,
            name: str = "step", save_artifact: bool = False,
            donate_argnums: Tuple[int, ...] = (),
            fp_parts=None):
    """Return ``(callable, outcome)`` for fingerprint ``fp``.

    Lookup order: in-process executable ("hit") → serialized AOT artifact
    under ``aot_dir`` ("aot_hit") → lower+compile ``jitted`` on ``args``
    ("miss", optionally writing the artifact). ``args`` may be concrete
    arrays or ShapeDtypeStructs. ``donate_argnums`` re-establishes buffer
    donation on the deserialized-artifact path (jax.export's call wrapper
    does not inherit the original jit's donation). If AOT lowering is
    unavailable for this function/backend the live jitted callable is
    cached instead — caching never changes semantics, only who pays the
    compile.

    ``fp_parts`` (optional, a labeled dict): the pre-hash fingerprint
    parts. Saved into the AOT meta sidecar, and on a stale-artifact
    rejection diffed against the stored parts so the log says WHICH key
    drifted (model scalar, env escape, aval signature) instead of just
    "fingerprint mismatch".
    """
    with _LOCK:
        fn = _EXECUTABLES.get(fp)
        if fn is not None:
            _EXECUTABLES.move_to_end(fp)
            _STATS["hits"] += 1
            hit = fn
        else:
            hit = None
    if hit is not None:
        if aot_dir and save_artifact and not _artifact_matches(
                aot_dir, name, fp):
            # precompile-after-train: the executable was already resident,
            # but the restart artifact must still land on disk
            try:
                save_aot(aot_dir, name, fp, jitted, args, parts=fp_parts)
            except Exception:
                pass
        return hit, "hit"
    if aot_dir:
        with _goodput_ledger().span("compile"):
            fn = load_aot(aot_dir, name, fp, donate_argnums=donate_argnums,
                          expect_parts=fp_parts)
        if fn is not None:
            _store(fp, fn)
            with _LOCK:
                _STATS["aot_hits"] += 1
            return fn, "aot_hit"
    try:
        t0 = time.perf_counter()
        with _goodput_ledger().span("compile"):
            fn = jitted.lower(*args).compile()
        if _REG.enabled:
            _REG.histogram("pt_compile_seconds",
                           "trace+lower+XLA-compile wall time per "
                           "executable", "s").observe(
                time.perf_counter() - t0, name=name)
    except Exception:
        # exotic arg types: fall back to live dispatch WITHOUT caching —
        # the jitted closure pins its Trainer's model/optimizer, and a
        # process-global cache entry would leak that graph (and alias it
        # into fingerprint-equal later Trainers)
        with _LOCK:
            _STATS["misses"] += 1
        return jitted, "miss"
    with _LOCK:
        _STATS["misses"] += 1
    if aot_dir and save_artifact:
        try:
            save_aot(aot_dir, name, fp, jitted, args, parts=fp_parts)
        except Exception:
            pass             # artifact write is best-effort, never fatal
    _store(fp, fn)
    return fn, "miss"


# -- on-disk AOT artifacts (jax.export) --------------------------------------

def _artifact_base(aot_dir: str, name: str) -> str:
    return os.path.join(aot_dir, f"aot_{name}")


def _artifact_matches(aot_dir: str, name: str, fp: str) -> bool:
    try:
        with open(_artifact_base(aot_dir, name) + AOT_META_SUFFIX) as f:
            return json.load(f).get("fingerprint") == fp
    except Exception:
        return False


def save_aot(aot_dir: str, name: str, fp: str, jitted, args,
             parts=None) -> str:
    """Serialize ``jitted`` specialized to ``args``' avals via ``jax.export``
    and write it (plus a meta sidecar carrying the fingerprint — and, when
    given, the labeled pre-hash ``parts`` a later mismatch is explained
    against) under ``aot_dir``. Returns the artifact path."""
    import jax
    from jax import export

    exp = export.export(jitted)(*to_avals(args))
    data = exp.serialize()
    os.makedirs(aot_dir, exist_ok=True)
    base = _artifact_base(aot_dir, name)
    tmp = base + AOT_BIN_SUFFIX + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, base + AOT_BIN_SUFFIX)
    meta = {"fingerprint": fp, "jax_version": jax.__version__,
            "backend": jax.default_backend(), "name": name}
    if parts is not None:
        meta["parts"] = _norm_parts(parts)
    tmp = base + AOT_META_SUFFIX + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    os.replace(tmp, base + AOT_META_SUFFIX)
    return base + AOT_BIN_SUFFIX


def load_aot(aot_dir: str, name: str, fp: str,
             donate_argnums: Tuple[int, ...] = (),
             expect_parts=None):
    """Deserialize the ``name`` artifact if its meta matches ``fp`` (and the
    current jax version/backend); returns a jitted callable or None. A
    mismatched or unreadable artifact is ignored — the caller compiles —
    but a STALE artifact's rejection is explained: when the sidecar stored
    the labeled fingerprint parts and the caller supplies its current
    ``expect_parts``, the differing keys are warned and recorded in
    ``stats()["last_stale"]`` (e.g. ``static.env.PT_NAIVE_LOSS_HEAD:
    False -> True`` — the operator knows the recompile is the env flip,
    not corruption).
    ``donate_argnums`` must restate the original jit's donation: the
    exported call wrapper does not carry it, and silently dropping it
    would double the params+opt-state HBM footprint on the resume path."""
    import jax
    from jax import export

    global _LAST_STALE
    base = _artifact_base(aot_dir, name)
    try:
        with open(base + AOT_META_SUFFIX) as f:
            meta = json.load(f)
        if (meta.get("fingerprint") != fp
                or meta.get("jax_version") != jax.__version__
                or meta.get("backend") != jax.default_backend()):
            # explanation is OPT-IN (expect_parts supplied): callers on
            # the old contract keep the silent-ignore behavior — a
            # routine jax upgrade must not start raising under -W error
            if expect_parts is not None:
                diff = []
                for key, want in (("jax_version", jax.__version__),
                                  ("backend", jax.default_backend())):
                    if meta.get(key) != want:
                        diff.append(f"{key}: {meta.get(key)!r} -> "
                                    f"{want!r}")
                if meta.get("fingerprint") != fp and "parts" in meta:
                    diff.extend(explain_fingerprint_change(meta["parts"],
                                                           expect_parts))
                if diff:
                    _LAST_STALE = {"name": name, "diff": diff}
                    import warnings
                    warnings.warn(
                        "compile_cache: AOT artifact '%s' is stale, "
                        "recompiling; drift:\n  %s" % (name,
                                                       "\n  ".join(diff)),
                        stacklevel=2)
            return None
        with open(base + AOT_BIN_SUFFIX, "rb") as f:
            data = f.read()
        exported = export.deserialize(data)
        # jit the calling convention once; the original Python body is
        # never re-traced (note_trace() stays untouched on this path)
        return jax.jit(exported.call, donate_argnums=donate_argnums)
    except Exception:
        return None


# -- XLA persistent compilation cache ----------------------------------------

def configure_compilation_cache(cache_dir: Optional[str] = None) -> bool:
    """Opt-in wiring of jax's persistent compilation cache.

    ``cache_dir`` defaults to env ``PT_COMPILE_CACHE_DIR``. When neither is
    set this is a strict NO-OP (returns False, jax config untouched) —
    guaranteed by test_superstep. When set, every XLA compile is disk-cached
    so process restarts (preemption resume!) skip compilation entirely.
    """
    global _PERSISTENT_DIR
    cache_dir = cache_dir or os.environ.get("PT_COMPILE_CACHE_DIR")
    if not cache_dir:
        return False
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # default thresholds skip "cheap" compiles; a resume wants everything
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _PERSISTENT_DIR = cache_dir
    return True
