"""Dtype surface.

Paddle-shaped dtype names mapped onto jnp dtypes (reference:
paddle/phi/common/data_type.h; python surface python/paddle/framework/dtype.py).
bfloat16 is the native TPU compute dtype; float16 is kept for API parity.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_NAME_TO_DTYPE = {
    "bool": bool_, "uint8": uint8, "int8": int8, "int16": int16,
    "int32": int32, "int64": int64, "float16": float16, "bfloat16": bfloat16,
    "float32": float32, "float64": float64, "complex64": complex64,
    "complex128": complex128,
    # paddle aliases
    "fp16": float16, "bf16": bfloat16, "fp32": float32, "fp64": float64,
}


def convert_dtype(dtype) -> jnp.dtype:
    """Normalize a string/np/jnp dtype to a jnp dtype."""
    if isinstance(dtype, str):
        if dtype not in _NAME_TO_DTYPE:
            raise ValueError(f"Unknown dtype name: {dtype}")
        return _NAME_TO_DTYPE[dtype]
    return jnp.dtype(dtype)


def is_floating_point(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), np.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), np.integer)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), np.complexfloating)
