"""Global runtime flag registry.

TPU-native analogue of the reference's gflags-style global flag system
(reference: paddle/phi/core/flags.cc — 120 PHI_DEFINE_EXPORTED_* flags;
python surface paddle.set_flags / paddle.get_flags backed by
paddle/fluid/pybind/global_value_getter_setter.cc).

Flags are typed, have defaults, can be set programmatically via
``set_flags`` or from the environment via ``FLAGS_<name>`` at import time.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _FlagSpec:
    name: str
    default: Any
    type: type
    help: str
    on_change: Optional[Callable[[Any], None]] = None


_REGISTRY: Dict[str, _FlagSpec] = {}
_VALUES: Dict[str, Any] = {}


def _parse(spec: _FlagSpec, raw: str) -> Any:
    if spec.type is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return spec.type(raw)


def define_flag(name: str, default: Any, help: str = "", type: Optional[type] = None,
                on_change: Optional[Callable[[Any], None]] = None) -> None:
    """Register a flag. Environment variable ``FLAGS_<name>`` overrides default."""
    t = type if type is not None else default.__class__
    spec = _FlagSpec(name=name, default=default, type=t, help=help, on_change=on_change)
    _REGISTRY[name] = spec
    env = os.environ.get(f"FLAGS_{name}")
    _VALUES[name] = _parse(spec, env) if env is not None else default


def set_flags(flags: Dict[str, Any]) -> None:
    """Set one or more flags. Mirrors ``paddle.set_flags``."""
    for k, v in flags.items():
        if k.startswith("FLAGS_"):
            k = k[len("FLAGS_"):]
        if k not in _REGISTRY:
            raise KeyError(f"Unknown flag: {k}. Registered: {sorted(_REGISTRY)}")
        spec = _REGISTRY[k]
        if isinstance(v, str) and spec.type is not str:
            v = _parse(spec, v)
        _VALUES[k] = spec.type(v) if spec.type is not bool else bool(v)
        if spec.on_change is not None:
            spec.on_change(_VALUES[k])


def get_flags(flags) -> Dict[str, Any]:
    """Get flag values. Mirrors ``paddle.get_flags``; accepts str or list."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        key = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
        out[k] = _VALUES[key]
    return out


def flag(name: str) -> Any:
    """Fast internal accessor used by the framework itself."""
    return _VALUES[name]


# ---------------------------------------------------------------------------
# Core framework flags (subset of reference paddle/phi/core/flags.cc that is
# meaningful on TPU/XLA; allocator/cudnn flags intentionally dropped — XLA owns
# device memory).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False, "Scan op outputs for NaN/Inf during training "
            "(reference: FLAGS_check_nan_inf, paddle/phi/core/flags.cc:74).")
define_flag("check_nan_inf_level", 0, "0: fail on NaN/Inf; higher levels only log.")
define_flag("use_stride_kernel", False, "Accepted for reference parity and "
            "inert: XLA owns layout/views on TPU, there are no stride "
            "kernels to toggle (reference: as_strided/view doctests).")
define_flag("benchmark", False, "Block-until-ready around steps for timing.")
define_flag("use_pallas_kernels", True, "Use Pallas TPU kernels for hot ops when "
            "on TPU; fall back to XLA compositions otherwise.")
define_flag("matmul_precision", "default", "jax matmul precision: default|high|highest.")
define_flag("deterministic", False, "Force deterministic kernels where possible.")
define_flag("log_memory_stats", False, "Log live/peak device memory per step.")
define_flag("executor_trace_mode", True, "Trace (serial replay) executor mode; "
            "kept for API parity with the reference new_executor.")
