"""paddle_tpu.core — flags, dtypes, RNG, compile cache."""

from . import compile_cache, dtype, flags, rng
from .compile_cache import configure_compilation_cache
from .flags import set_flags, get_flags, define_flag
from .rng import seed, rng_tracker

# opt-in persistent XLA compile cache: strict no-op unless
# PT_COMPILE_CACHE_DIR is set in the environment (see compile_cache.py)
configure_compilation_cache()
