"""RNG state management.

TPU-native analogue of the reference's RNG tracker for parallelism-correct
randomness (reference: python/paddle/distributed/fleet/layers/mpu/random.py —
``RNGStatesTracker`` keeps named states, "global_seed" shared across
model-parallel ranks and "local_seed" unique per rank, so dropout inside TP
regions decorrelates across ranks while replicated regions stay identical).

On TPU/JAX this is functional: a tracker holds named base keys; consumers draw
sub-keys via an internal fold_in counter. Inside jit-traced functions the
tracker is seeded with a traced key argument (``scope``), so compiled steps
stay pure — the counter resets per trace and every re-execution of the traced
python produces the same fold_in sequence.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional

import jax

GLOBAL_STREAM = "global_seed"
LOCAL_STREAM = "local_seed"


class RNGStatesTracker:
    """Named PRNG streams with deterministic fold_in sub-key derivation."""

    def __init__(self):
        self._keys: Dict[str, jax.Array] = {}
        self._counters: Dict[str, int] = {}
        self._lock = threading.Lock()

    def reset(self) -> None:
        self._keys.clear()
        self._counters.clear()

    def add(self, name: str, seed_or_key) -> None:
        if isinstance(seed_or_key, int):
            key = jax.random.key(seed_or_key)
        else:
            key = seed_or_key
        self._keys[name] = key
        self._counters[name] = 0

    def has(self, name: str) -> bool:
        return name in self._keys

    @staticmethod
    def _tracing() -> bool:
        try:
            from jax._src import core as _jcore   # jax 0.9: private only
            return not _jcore.trace_state_clean()
        except (ImportError, AttributeError):  # pragma: no cover
            # unknown jax layout: assume tracing, which keeps the SAFE
            # behavior (loud unseeded error instead of a tracer leak)
            return True

    def next_key(self, name: str = GLOBAL_STREAM) -> jax.Array:
        """Draw the next sub-key from stream ``name`` (deterministic sequence)."""
        with self._lock:
            if name not in self._keys:
                if name == GLOBAL_STREAM and not self._tracing():
                    # reference parity: paddle's global generator works
                    # without an explicit paddle.seed() (random seed).
                    # Auto-seed from entropy with a ONE-TIME warning —
                    # EAGER only: inside a trace, key creation would store
                    # a tracer (frozen randomness + leaked-tracer crashes),
                    # so traced unseeded use keeps the loud error.
                    import time
                    import warnings
                    warnings.warn(
                        "global RNG stream auto-seeded from entropy; call "
                        "paddle.seed(<int>) for reproducible randomness",
                        stacklevel=3)
                    self._keys[name] = jax.random.key(
                        int(time.time_ns()) & 0x7FFFFFFF)
                    self._counters[name] = 0
                else:
                    raise RuntimeError(
                        f"RNG stream '{name}' not seeded. Call "
                        f"paddle_tpu.seed(...) or rng_tracker().add('{name}', "
                        f"seed) first, or run inside rng_tracker().scope(key).")
            c = self._counters[name]
            self._counters[name] = c + 1
        return jax.random.fold_in(self._keys[name], c)

    @contextlib.contextmanager
    def scope(self, key: jax.Array, name: str = GLOBAL_STREAM,
              local_key: Optional[jax.Array] = None):
        """Temporarily seed stream(s) from (possibly traced) keys.

        Used by training steps: the step key is an argument of the jitted
        function, so randomness is reproducible and pure under jit.
        """
        saved = (dict(self._keys), dict(self._counters))
        try:
            self.add(name, key)
            if local_key is not None:
                self.add(LOCAL_STREAM, local_key)
            elif name == GLOBAL_STREAM and LOCAL_STREAM not in self._keys:
                # default local stream derived from global; parallel layers
                # re-fold mesh coordinates in (parallel/mesh.py).
                self.add(LOCAL_STREAM, jax.random.fold_in(key, 0x10C4))
            yield self
        finally:
            self._keys, self._counters = saved


_TRACKER = RNGStatesTracker()


def rng_tracker() -> RNGStatesTracker:
    return _TRACKER


def seed(s: int) -> None:
    """Seed the global + local default streams (mirrors ``paddle.seed``)."""
    _TRACKER.reset()
    _TRACKER.add(GLOBAL_STREAM, s)
    _TRACKER.add(LOCAL_STREAM, s + 0x5EED)


def next_key(name: str = GLOBAL_STREAM) -> jax.Array:
    return _TRACKER.next_key(name)
