"""Deterministic chaos training script (subprocess side of testing.chaos).

``python -m paddle_tpu.testing._chaos_train --ckpt-dir D --steps N [...]``
trains a tiny regression model with the full resilience stack wired in
(CheckpointManager + PreemptionGuard + resume="auto") and prints one
machine-readable ``CHAOS_RESULT {...}`` line. Fault flags:

* ``--hard-exit-at K``   — os._exit(137) when step K completes (SIGKILL
  shape: no final checkpoint, no commit of the in-flight async save);
* ``--self-sigterm-at K``— SIGTERM to self at step K (preemption shape:
  the guard latches it, fit writes a final sync checkpoint and exits with
  the RESUMABLE status);
* ``--fail-at K``        — raise RuntimeError at step K (plain crash; the
  relauncher's failure budget, not the preemption path).

Relaunching with the same --ckpt-dir resumes from the newest committed
checkpoint; an uninterrupted run and a killed+resumed run print identical
digests (the bit-exact contract tests assert on).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys


def build(seed: int = 0):
    import paddle_tpu as pt
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu import nn
    from paddle_tpu.nn.layer import Layer
    from paddle_tpu.io import DataLoader, TensorDataset
    from paddle_tpu.optimizer import SGD
    from paddle_tpu.trainer import Trainer

    pt.seed(seed)

    class TinyReg(Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(8, 16)
            self.l2 = nn.Linear(16, 1)

        def forward(self, x, y):
            h = jnp.tanh(self.l1(x))
            return jnp.mean((self.l2(h) - y) ** 2)

    rs = np.random.RandomState(1234)
    xs = rs.randn(512, 8).astype(np.float32)
    ys = (xs.sum(axis=1, keepdims=True) * 0.1).astype(np.float32)
    ds = TensorDataset([xs, ys])
    loader = DataLoader(ds, batch_size=16, shuffle=False, drop_last=True,
                        collate_fn=lambda items: {
                            "x": np.stack([i[0] for i in items]),
                            "y": np.stack([i[1] for i in items])})
    model = TinyReg()
    opt = SGD(learning_rate=0.05, parameters=model)
    return Trainer(model, opt, donate=False), loader


def params_digest(params) -> str:
    import numpy as np
    h = hashlib.sha256()
    for k in sorted(params):
        h.update(k.encode())
        h.update(np.ascontiguousarray(np.asarray(params[k])).tobytes())
    return h.hexdigest()[:16]


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--save-interval", type=int, default=5)
    p.add_argument("--async-save", action="store_true")
    p.add_argument("--hard-exit-at", type=int, default=None)
    p.add_argument("--self-sigterm-at", type=int, default=None)
    p.add_argument("--fail-at", type=int, default=None)
    args = p.parse_args(argv)

    from paddle_tpu.resilience import CheckpointManager, PreemptionGuard
    from paddle_tpu.testing import chaos

    tr, loader = build()
    mgr = CheckpointManager(args.ckpt_dir,
                            save_interval_steps=args.save_interval,
                            keep_last_n=3, async_save=args.async_save)

    def cb(m):
        if args.hard_exit_at is not None and m.step >= args.hard_exit_at:
            os._exit(137)
        if args.fail_at is not None and m.step >= args.fail_at:
            raise RuntimeError(f"injected failure at step {m.step}")

    on_metrics = cb if (args.hard_exit_at is not None
                        or args.fail_at is not None) else None
    if args.self_sigterm_at is not None:
        on_metrics = chaos.kill_at_step(args.self_sigterm_at)

    with PreemptionGuard() as guard:
        hist = tr.fit(loader, steps=args.steps, log_every=1,
                      on_metrics=on_metrics, checkpoint_manager=mgr,
                      resume="auto", preemption_guard=guard)

    losses = [m.loss for m in hist]
    print("CHAOS_RESULT " + json.dumps({
        "step": tr._step,
        "final_loss": losses[-1] if losses else None,
        "digest": params_digest(tr.params),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
