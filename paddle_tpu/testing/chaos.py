"""Fault-injection helpers for resilience tests.

Reference analogue: the reference exercises auto_checkpoint with simulated
"break process" runs (test_auto_checkpoint.py kills and relaunches the
trainer); here the faults are first-class helpers so tests can inject each
failure mode precisely:

* :func:`kill_mid_save` — a checkpoint write that "dies" after the data is
  durable but BEFORE the commit marker (the classic torn save);
* :func:`corrupt_checkpoint` — bit-flip / truncate / unlink files inside a
  committed step dir (bit-rot, partial GC, fat-fingered operator);
* :func:`nan_batch` / :func:`nan_injector` — poison-batch streams for
  AnomalyGuard tests;
* :func:`kill_at_step` — an ``on_metrics`` callback that SIGTERMs the
  current process at a chosen step (preemption mid-fit);
* :func:`spawn_trainer` — run ``paddle_tpu.testing._chaos_train`` in a
  subprocess for the real kill -9 / exit-status tests (mark those `slow`).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
from typing import Iterable, Iterator, Optional, Sequence

import numpy as np

__all__ = ["kill_mid_save", "corrupt_checkpoint", "nan_batch",
           "nan_injector", "kill_at_step", "spawn_trainer",
           "spawn_elastic", "kill_replica", "hang_replica",
           "unhang_replica"]


def kill_mid_save(manager, step: int, tree) -> str:
    """Write checkpoint ``step`` but simulate process death BEFORE the
    commit marker: the orbax payload is fully durable, the ``.PENDING``
    sidecar remains, no ``_COMMITTED`` exists. Returns the step dir.

    This is exactly the state a SIGKILL between the async write's
    completion and ``finalize()`` leaves behind; a correct resume must skip
    it (checkpoint.latest_step) or quarantine it (CheckpointManager)."""
    from paddle_tpu import checkpoint as ckpt
    manager.save(step, tree, async_save=True)
    ckpt.wait_until_finished()      # data durable...
    manager._pending = None         # ...but the committer "died" here
    return manager.step_dir(step)


def corrupt_checkpoint(step_dir: str, mode: str = "flip",
                       skip: Sequence[str] = ("_COMMITTED",)) -> str:
    """Damage a checkpoint dir in place; returns the path of the file hit.

    mode="flip" inverts a byte in the LARGEST payload file (silent bit-rot:
    sizes still match, only the checksum catches it); "truncate" halves a
    file; "delete" unlinks it; "manifest" overwrites _MANIFEST.json with
    junk."""
    if mode == "manifest":
        target = os.path.join(step_dir, "_MANIFEST.json")
        with open(target, "w") as f:
            f.write("{corrupt")
        return target
    files = []
    for dirpath, _dirs, names in os.walk(step_dir):
        for name in names:
            if name in skip or name == "_MANIFEST.json":
                continue
            full = os.path.join(dirpath, name)
            files.append((os.path.getsize(full), full))
    if not files:
        raise FileNotFoundError(f"no payload files under {step_dir}")
    _, target = max(files)
    if mode == "flip":
        with open(target, "r+b") as f:
            f.seek(os.path.getsize(target) // 2)
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0xFF]))
    elif mode == "truncate":
        with open(target, "r+b") as f:
            f.truncate(max(1, os.path.getsize(target) // 2))
    elif mode == "delete":
        os.remove(target)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return target


def nan_batch(batch: dict, fields: Optional[Iterable[str]] = None) -> dict:
    """Copy of ``batch`` with float arrays replaced by NaN (poison batch).
    Integer arrays (token ids) are left alone unless named in ``fields`` —
    those are replaced by out-of-range -1 ids instead."""
    fields = set(fields) if fields is not None else None
    out = {}
    for k, v in batch.items():
        arr = np.asarray(v)
        if fields is not None and k in fields and arr.dtype.kind in "iu":
            out[k] = np.full_like(arr, -1)
        elif np.issubdtype(arr.dtype, np.floating) and (
                fields is None or k in fields):
            out[k] = np.full_like(arr, np.nan)
        else:
            out[k] = v
    return out


def nan_injector(batches: Iterable[dict], at: int,
                 fields: Optional[Iterable[str]] = None) -> Iterator[dict]:
    """Yield from ``batches``, poisoning the ``at``-th one (0-based)."""
    for i, b in enumerate(batches):
        yield nan_batch(b, fields) if i == at else b


def kill_at_step(step: int, sig: int = signal.SIGTERM):
    """``on_metrics`` callback delivering ``sig`` to THIS process when the
    given step is reached (use log_every=1 for per-step resolution). With a
    PreemptionGuard installed the signal latches instead of killing."""
    def cb(metrics):
        if metrics.step >= step:
            os.kill(os.getpid(), sig)
    return cb


def kill_replica(transport, name: str) -> None:
    """Drop a serving-fabric replica mid-whatever-it-was-doing — the
    serving analogue of kill -9. Requires a transport with a ``kill``
    hook (the in-process transport: every later router op raises
    ``ReplicaDown``, exactly what a SIGKILLed remote looks like through
    the TCP transport); for transports without one (TCP), SIGKILL the
    replica's server process directly — the raised TypeError says so.
    The router's failover re-admission (replay-exact continuation on a
    survivor) is what the chaos tests then assert."""
    k = getattr(transport, "kill", None)
    if k is not None:
        k(name)
        return
    raise TypeError(f"transport {type(transport).__name__} has no kill "
                    f"hook; SIGKILL the replica's server process "
                    f"directly (TcpReplicaServer.stop / os.kill)")


def hang_replica(transport, name: str) -> None:
    """Wedge a serving-fabric replica: it still answers ``status``
    (heartbeats look healthy) but every op that would make PROGRESS —
    poll, submit, extract, adopt — blocks forever. This is crash's
    evil twin (GC pause, wedged accelerator, half-open partition) and
    the failure mode the circuit breaker's op-class timeouts exist
    for: without a breaker the router stalls on the hung poll; with
    one the op times out, trips ReplicaDown, and PR 12's replay-exact
    failover takes over. Requires a transport with a ``hang`` hook
    (the in-process transport); for TCP, SIGSTOP the replica's server
    process instead — the raised TypeError says so. Undo with
    :func:`unhang_replica`."""
    h = getattr(transport, "hang", None)
    if h is not None:
        h(name)
        return
    raise TypeError(f"transport {type(transport).__name__} has no hang "
                    f"hook; SIGSTOP the replica's server process "
                    f"directly (os.kill(pid, signal.SIGSTOP))")


def unhang_replica(transport, name: str) -> None:
    """Release :func:`hang_replica`: blocked ops wake and report
    ReplicaDown (their answers are lost — that RPC already failed);
    fresh ops succeed, so a breaker's half-open probe readmits."""
    u = getattr(transport, "unhang", None)
    if u is not None:
        u(name)
        return
    raise TypeError(f"transport {type(transport).__name__} has no "
                    f"unhang hook; SIGCONT the server process instead")


def spawn_trainer(ckpt_dir: str, *, steps: int, extra_args: Sequence[str] = (),
                  env: Optional[dict] = None) -> subprocess.Popen:
    """Launch the chaos training script (tiny deterministic model) as a
    subprocess: ``python -m paddle_tpu.testing._chaos_train``. The caller
    kills/waits on the returned Popen. Slow (fresh jax import) — tests
    using this belong in the `slow` tier."""
    cmd = [sys.executable, "-m", "paddle_tpu.testing._chaos_train",
           "--ckpt-dir", ckpt_dir, "--steps", str(steps), *extra_args]
    full_env = dict(os.environ)
    full_env.setdefault("JAX_PLATFORMS", "cpu")
    if env:
        full_env.update(env)
    return subprocess.Popen(cmd, env=full_env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)


def spawn_elastic(ckpt_dir: str, *, steps: int, virtual_devices: int,
                  extra_args: Sequence[str] = (),
                  env: Optional[dict] = None) -> subprocess.Popen:
    """Launch the elastic training script (llama-micro on a virtual-device
    mesh): ``python -m paddle_tpu.testing._elastic_train``. The parent's
    XLA_FLAGS is stripped so ``--virtual-devices`` alone decides the
    child's device count — resume-on-fewer-devices IS the scenario. The
    caller kills/waits on the returned Popen (SIGKILL shape: pass
    ``--hard-exit-at K`` and assert exit code 137)."""
    cmd = [sys.executable, "-m", "paddle_tpu.testing._elastic_train",
           "--ckpt-dir", ckpt_dir, "--steps", str(steps),
           "--virtual-devices", str(virtual_devices), *extra_args]
    full_env = dict(os.environ)
    full_env.pop("XLA_FLAGS", None)
    full_env.setdefault("JAX_PLATFORMS", "cpu")
    if env:
        full_env.update(env)
    return subprocess.Popen(cmd, env=full_env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
