"""OpTest harness: numpy-reference + numeric-grad + path-parity checks.

Reference model: test/legacy_test/op_test.py:420 (``check_output`` /
``check_grad`` run each op through every registered path and compare against
a numpy forward reference and finite-difference gradients). Here the "paths"
are: eager (op-by-op dispatch), ``jax.jit`` (XLA-compiled), and sharded
execution over a ``jax.sharding.Mesh`` (GSPMD) — outputs must agree across
all of them.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# per-dtype default tolerances, mirroring the reference's white_list/tolerance
# tiers (test/white_list/op_accuracy_white_list.py)
_DEFAULT_TOL = {
    np.dtype(np.float64): (1e-7, 1e-7),
    np.dtype(np.float32): (1e-5, 1e-5),
    np.dtype(np.float16): (1e-3, 1e-3),
    jnp.bfloat16.dtype: (2e-2, 2e-2),
}


def _tol_for(dtype, rtol, atol):
    d_rtol, d_atol = _DEFAULT_TOL.get(np.dtype(dtype), (1e-5, 1e-5))
    return (rtol if rtol is not None else d_rtol,
            atol if atol is not None else d_atol)


def numeric_grad(f: Callable[[np.ndarray], float], x: np.ndarray,
                 eps: float = 1e-3) -> np.ndarray:
    """Central-difference gradient of scalar ``f`` at ``x`` (fp64)."""
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy(); xp[idx] += eps
        xm = x.copy(); xm[idx] -= eps
        g[idx] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


def _as_arrays(inputs, dtype):
    out = []
    for a in inputs:
        a = np.asarray(a)
        if np.issubdtype(a.dtype, np.floating):
            a = a.astype(dtype)
        out.append(a)
    return out


def check_output(fn: Callable, np_ref: Callable, inputs: Sequence[np.ndarray],
                 dtypes: Sequence = (np.float32,), rtol: Optional[float] = None,
                 atol: Optional[float] = None, with_jit: bool = True,
                 kwargs: Optional[Dict] = None) -> None:
    """Assert fn(*inputs) == np_ref(*inputs) per dtype, eagerly and under jit.

    Float inputs are cast to each dtype in ``dtypes``; the numpy reference
    always runs in fp64 for a stable oracle.
    """
    kwargs = kwargs or {}
    ref = np_ref(*_as_arrays(inputs, np.float64), **kwargs)
    ref_list = ref if isinstance(ref, (tuple, list)) else [ref]
    for dtype in dtypes:
        r, a = _tol_for(dtype, rtol, atol)
        xs = [jnp.asarray(v) for v in _as_arrays(inputs, dtype)]
        paths = [("eager", fn)]
        if with_jit:
            # one trace per dtype under test is the POINT of this helper —
            # trace-lint: waive(jit-in-loop) correctness oracle, not hot path
            paths.append(("jit", jax.jit(lambda *args: fn(*args, **kwargs))))
        for name, f in paths:
            got = f(*xs, **({} if name == "jit" else kwargs))
            got_list = got if isinstance(got, (tuple, list)) else [got]
            assert len(got_list) == len(ref_list), (
                f"{name}: arity {len(got_list)} != ref {len(ref_list)}")
            for g, e in zip(got_list, ref_list):
                np.testing.assert_allclose(
                    np.asarray(g, np.float64), np.asarray(e, np.float64),
                    rtol=r, atol=a,
                    err_msg=f"path={name} dtype={np.dtype(dtype).name}")


def check_grad(fn: Callable, np_ref: Callable, inputs: Sequence[np.ndarray],
               arg_idx: int = 0, eps: float = 1e-3, rtol: float = 1e-3,
               atol: float = 1e-3, kwargs: Optional[Dict] = None) -> None:
    """Check jax.grad of sum(fn) at inputs[arg_idx] vs finite differences of
    the fp64 numpy reference (the reference's numeric grad check)."""
    kwargs = kwargs or {}
    base = _as_arrays(inputs, np.float64)

    def scalar_np(x):
        args = list(base)
        args[arg_idx] = x
        out = np_ref(*args, **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return float(np.sum(np.asarray(out, np.float64)))

    g_num = numeric_grad(scalar_np, base[arg_idx], eps=eps)

    def scalar_jax(x):
        args = [jnp.asarray(v, jnp.float32) for v in base]
        args[arg_idx] = x
        out = fn(*args, **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return jnp.sum(out)

    g_jax = jax.grad(scalar_jax)(jnp.asarray(base[arg_idx], jnp.float32))
    np.testing.assert_allclose(np.asarray(g_jax, np.float64), g_num,
                               rtol=rtol, atol=atol)


def check_sharded(fn: Callable, inputs: Sequence[np.ndarray], mesh,
                  in_specs: Sequence, rtol: float = 1e-5, atol: float = 1e-5,
                  kwargs: Optional[Dict] = None) -> None:
    """Run fn with inputs placed under NamedShardings on ``mesh`` and assert
    the result matches unsharded execution (GSPMD path parity — the analogue
    of the reference running ops on every backend)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    kwargs = kwargs or {}
    xs = [jnp.asarray(v) for v in inputs]
    ref = fn(*xs, **kwargs)
    placed = [jax.device_put(x, NamedSharding(mesh, spec if spec is not None else P()))
              for x, spec in zip(xs, in_specs)]
    got = jax.jit(lambda *args: fn(*args, **kwargs))(*placed)
    ref_list = ref if isinstance(ref, (tuple, list)) else [ref]
    got_list = got if isinstance(got, (tuple, list)) else [got]
    for g, e in zip(got_list, ref_list):
        np.testing.assert_allclose(np.asarray(g, np.float64),
                                   np.asarray(e, np.float64),
                                   rtol=rtol, atol=atol)


class OpTest:
    """Declarative op test, the shape of the reference's ``OpTest`` subclassing
    pattern: set ``fn`` / ``np_ref`` / ``inputs`` (and optionally ``kwargs``,
    ``dtypes``, ``grad_args``) in ``setup`` and call the check methods.

    Example::

        class TestSilu(OpTest):
            def setup(self):
                self.fn = F.silu
                self.np_ref = lambda x: x / (1 + np.exp(-x))
                self.inputs = [np.random.randn(4, 8)]

        TestSilu().run()    # checks output (fp32+bf16), grads, jit parity
    """

    fn: Callable = None
    np_ref: Callable = None
    inputs: Sequence[np.ndarray] = ()
    kwargs: Dict = {}
    dtypes: Sequence = (np.float32,)
    grad_args: Sequence[int] = (0,)
    grad_tol: Tuple[float, float] = (1e-3, 1e-3)

    def setup(self):  # override
        raise NotImplementedError

    def run(self, grad: bool = True):
        self.setup()
        check_output(self.fn, self.np_ref, self.inputs, dtypes=self.dtypes,
                     kwargs=self.kwargs)
        if grad:
            rtol, atol = self.grad_tol
            for i in self.grad_args:
                if np.issubdtype(np.asarray(self.inputs[i]).dtype, np.floating):
                    check_grad(self.fn, self.np_ref, self.inputs, arg_idx=i,
                               rtol=rtol, atol=atol, kwargs=self.kwargs)
