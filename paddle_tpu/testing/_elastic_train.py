"""Deterministic elastic training script (subprocess side of ISSUE 15).

``python -m paddle_tpu.testing._elastic_train --ckpt-dir D --steps N
--virtual-devices V --config dp4_tp2 [...]`` trains the llama-micro model
on a virtual-device mesh with the full elastic stack wired in
(ShardingPlan via apply_plan + CheckpointManager(plan=...) +
resume="auto") and prints one machine-readable ``ELASTIC_RESULT {...}``
line. Elastic knobs:

* ``--hard-exit-at K``     — os._exit(137) when step K completes (SIGKILL
  shape: no final checkpoint; a later invocation with fewer
  ``--virtual-devices`` is the scale-in resume);
* ``--plan-auto``          — ask the auto-parallel planner for the best
  legal config on THIS process's devices (``--candidates`` bounds the
  priced set; the chosen config is reported);
* ``--switch-at K --switch-config C`` — the uninterrupted REFERENCE leg:
  at step K a WorldSizeChanged is raised in-process and
  ``ElasticManager.run_elastic`` re-plans onto ``C`` (fewer devices of
  the same process) and re-enters ``fit(resume="auto")`` through the
  resharded restore — the same mesh schedule as a killed+resumed run,
  with no process death. Chaos-vs-reference loss comparison is therefore
  about the kill/restore machinery alone, not cross-mesh numerics.

Per-attempt segments (config, world size, steps, losses) ride in the
result so tests can assert bit-exactness modulo the batch schedule.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys


def _parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--save-interval", type=int, default=4)
    p.add_argument("--async-save", action="store_true")
    p.add_argument("--virtual-devices", type=int, default=None)
    p.add_argument("--config", default="dp4_tp2")
    p.add_argument("--plan-auto", action="store_true")
    p.add_argument("--candidates", default="")
    p.add_argument("--switch-at", type=int, default=None)
    p.add_argument("--switch-config", default="dp2_tp2")
    p.add_argument("--switch-devices", type=int, default=None)
    p.add_argument("--hard-exit-at", type=int, default=None)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--probe-reshard", action="store_true",
                   help="no training: run the timed mini reshard cycle "
                        "and print ELASTIC_PROBE {json} (bench rows)")
    return p.parse_args(argv)


def micro_config():
    from paddle_tpu.models import LlamaConfig
    return LlamaConfig(vocab_size=320, hidden_size=64, intermediate_size=96,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, max_position_embeddings=128)


def build_data(global_batch: int, seq_len: int, steps: int):
    import numpy as np
    from paddle_tpu.io import DataLoader, TensorDataset
    rs = np.random.RandomState(1234)
    toks = rs.randint(0, 320, (global_batch * (steps + 4), seq_len + 1))
    ds = TensorDataset([toks.astype(np.int64)])
    return DataLoader(ds, batch_size=global_batch, shuffle=False,
                      drop_last=True,
                      collate_fn=lambda items: {
                          "input_ids": np.stack([i[0][:-1] for i in items]),
                          "labels": np.stack([i[0][1:] for i in items])})


class ShardedLoader:
    """Wrap a DataLoader: place each batch per the CURRENT plan (the
    holder is swapped on a mesh switch so later batches land on the new
    mesh) and forward the cursor protocol so resume fast-forwards."""

    def __init__(self, inner, holder):
        self.inner = inner
        self.holder = holder      # dict with "plan" and "mesh"

    def __iter__(self):
        for b in self.inner:
            yield self.holder["plan"].shard_batch(b, self.holder["mesh"])

    def state_dict(self):
        return self.inner.state_dict()

    def set_state_dict(self, sd):
        return self.inner.set_state_dict(sd)


def params_digest(tree) -> str:
    import numpy as np
    import jax
    from jax.tree_util import tree_flatten_with_path
    h = hashlib.sha256()
    leaves, _ = tree_flatten_with_path(tree)
    for path, x in sorted(leaves, key=lambda kv: str(kv[0])):
        h.update(str(path).encode())
        h.update(np.ascontiguousarray(
            np.asarray(jax.device_get(x))).tobytes())
    return h.hexdigest()[:16]


def pick_plan(args, mcfg, devices):
    """Explicit config, or the planner over the candidate set."""
    from paddle_tpu.distributed.auto_parallel import (
        ParallelConfig, plan as ap_plan, plan_for_config)
    if not args.plan_auto:
        cfg = ParallelConfig.parse(args.config)
        return plan_for_config(mcfg, cfg, devices=devices)
    cand = ([ParallelConfig.parse(s) for s in args.candidates.split(",")
             if s.strip()] or None)
    report = ap_plan(mcfg, devices=devices, global_batch=args.global_batch,
                     seq_len=args.seq_len, configs=cand, drift="ignore")
    return report.chosen.plan


def reshard_probe() -> dict:
    """Timed mini elastic cycle for the bench detail rows: llama-micro
    state checkpointed every 4 steps under the largest feasible dp×tp
    plan, a SIGKILL-shape death at step 6, resharded restore onto HALF
    the devices. ``elastic_reshard_seconds`` is the verify+reshard+place
    wall time; ``elastic_resume_steps_replayed`` is killed_step −
    restored_step (the work the save cadence forfeits, 2 here by
    construction — a regression means the cadence or the fallback
    broke)."""
    import shutil
    import tempfile
    import time

    import numpy as np
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel import shard_optimizer_state
    from paddle_tpu.resilience import CheckpointManager
    from paddle_tpu.distributed.auto_parallel import (ParallelConfig,
                                                      plan_for_config)

    devs = jax.devices()
    n = 1
    while n * 2 <= len(devs):
        n *= 2
    if n < 2:
        raise RuntimeError(f"reshard probe needs >=2 devices, have "
                           f"{len(devs)}")
    src_cfg = (ParallelConfig(dp=n // 2, tp=2) if n >= 4
               else ParallelConfig(dp=2, tp=1))
    dst_cfg = (ParallelConfig(dp=n // 4, tp=2) if n >= 8
               else ParallelConfig(dp=1, tp=2) if n >= 4
               else ParallelConfig(dp=1, tp=1))
    mcfg = micro_config()
    pt.seed(0)
    model = LlamaForCausalLM(mcfg)
    src = plan_for_config(mcfg, src_cfg, devices=devs[:n])
    with src.apply(model):
        opt = AdamW(learning_rate=1e-3, parameters=model)
        params = {k: p.value for k, p in model.named_parameters()}
        opt_state = shard_optimizer_state(opt.init_state(params),
                                          src.param_specs)
    tree = {"step": np.asarray(0, np.int64), "params": params,
            "opt_state": opt_state}

    root = tempfile.mkdtemp(prefix="pt_reshard_probe_")
    try:
        mgr = CheckpointManager(root, save_interval_steps=4,
                                keep_last_n=2, plan=src)
        killed_at = 6
        for s in range(1, killed_at + 1):   # trainer cadence: step 4 only
            if s % mgr.save_interval_steps == 0:
                mgr.save(s, tree)
        dst = plan_for_config(mcfg, dst_cfg, devices=devs[:n // 2])
        hm = dst.build_mesh(devices=devs[:n // 2])
        t0 = time.perf_counter()
        mgr2 = CheckpointManager(root, plan=dst, mesh=hm.mesh)
        restored = mgr2.restore(tree)
        dt = time.perf_counter() - t0
        assert restored is not None
        return {"elastic_reshard_seconds": round(dt, 4),
                "elastic_resume_steps_replayed": killed_at - restored[0],
                "elastic_probe_configs": f"{src.config_str}"
                                         f"->{dst.config_str}"}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def main(argv=None) -> int:
    args = _parse_args(argv)
    if args.virtual_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.virtual_devices}").strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.probe_reshard:
        print("ELASTIC_PROBE " + json.dumps(reshard_probe()), flush=True)
        return 0

    import jax
    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.resilience import CheckpointManager
    from paddle_tpu.distributed.elastic import (ElasticManager,
                                                WorldSizeChanged)
    from paddle_tpu.distributed.auto_parallel import plan_for_config, \
        ParallelConfig

    mcfg = micro_config()
    pt.seed(0)
    model = LlamaForCausalLM(mcfg)
    trainer = Trainer(model, AdamW(learning_rate=1e-3, parameters=model),
                      donate=False)
    loader = build_data(args.global_batch, args.seq_len, args.steps)

    devices = list(jax.devices())
    holder = {"plan": None, "mesh": None}
    data = ShardedLoader(loader, holder)
    segments = []

    def train_leg(attempt: int, world_size: int) -> None:
        if attempt == 0 and not args.switch_at:
            plan = pick_plan(args, mcfg, devices[:world_size])
        elif attempt == 0:
            plan = plan_for_config(mcfg, ParallelConfig.parse(args.config),
                                   devices=devices[:world_size])
        else:
            # post-switch leg of the reference run: the agreed smaller
            # config on the surviving devices
            plan = plan_for_config(
                mcfg, ParallelConfig.parse(args.switch_config),
                devices=devices[:world_size])
        hm = trainer.apply_plan(plan, devices=devices[:world_size])
        holder["plan"], holder["mesh"] = plan, hm
        mgr = CheckpointManager(args.ckpt_dir,
                                save_interval_steps=args.save_interval,
                                keep_last_n=4, async_save=args.async_save)
        seg = {"attempt": attempt, "world_size": world_size,
               "config": plan.config_str, "steps": [], "losses": []}
        segments.append(seg)

        def cb(m):
            seg["steps"].append(int(m.step))
            seg["losses"].append(float(m.loss))
            if (args.hard_exit_at is not None
                    and m.step >= args.hard_exit_at):
                os._exit(137)
            if (args.switch_at is not None and attempt == 0
                    and m.step > args.switch_at):
                raise WorldSizeChanged(world_size,
                                       args.switch_devices
                                       or world_size // 2)

        with hm:
            trainer.fit(data, steps=args.steps, log_every=1,
                        on_metrics=cb, checkpoint_manager=mgr,
                        resume="auto")

    if args.switch_at is not None:
        em = ElasticManager(np=1, heartbeat_timeout=60.0)
        schedule = iter([len(devices),
                         args.switch_devices or len(devices) // 2])
        last = [len(devices)]

        def ws_fn():
            try:
                last[0] = next(schedule)
            except StopIteration:
                pass
            return last[0]

        ok = em.run_elastic(train_leg, world_size_fn=ws_fn,
                            sleep=lambda _s: None)
        em.exit()
        assert ok, "reference elastic run did not complete"
    else:
        train_leg(0, len(devices))

    tree = {"params": trainer.params, "opt_state": trainer.opt_state}
    print("ELASTIC_RESULT " + json.dumps({
        "step": trainer._step,
        "devices": len(devices),
        "segments": segments,
        "digest": params_digest(tree),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
