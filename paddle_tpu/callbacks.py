"""paddle.callbacks re-export (reference: python/paddle/callbacks.py —
a thin alias of hapi.callbacks; VisualDL/Wandb integrations are external
services and are out of scope by design, recorded in
docs/DESIGN_DECISIONS.md)."""

from .hapi.callbacks import (Callback, CallbackList, EarlyStopping, History,
                             LRSchedulerCallback as LRScheduler,
                             ModelCheckpoint, ProgBarLogger)

__all__ = ["Callback", "CallbackList", "EarlyStopping", "History",
           "LRScheduler", "ModelCheckpoint", "ProgBarLogger"]
