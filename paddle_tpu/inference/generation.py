"""LLM generation loop over the KV-cache decode path (reference analogue:
PaddleNLP's generation utils driving the fused/block attention kernels;
in-repo kernels masked_multihead_attention / block_multi_head_attention).

TPU-native: prefill compiles once for the padded prompt length, the decode
step compiles once (static cache shape, dynamic position index), and the
token loop runs on host while all math stays on device. Sampling strategies:
greedy, temperature, top-k, top-p — each a pure function over logits.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    seed: int = 0


def _sample_logits(logits, cfg: GenerationConfig, key):
    """[b, vocab] → [b] next tokens."""
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -cfg.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; always keep the best
        cutoff_idx = jnp.sum(cum < cfg.top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def _mask_logits_rowwise(logits, temperature, top_k, top_p):
    """Shared temperature/top-k/top-p masking for the per-row samplers:
    [b, vocab] logits + per-row knob arrays → masked [b, vocab] logits
    ready for ``jax.random.categorical``."""
    b, vocab = logits.shape
    x = logits / jnp.maximum(temperature, 1e-6)[:, None]

    # top-k: keep each row's k best (k=0 -> vocab = keep all)
    sorted_x = jnp.sort(x, axis=-1)[:, ::-1]             # descending
    k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, vocab), vocab)
    kth = jnp.take_along_axis(sorted_x, (k_eff - 1)[:, None], axis=-1)
    x = jnp.where(x < kth, -jnp.inf, x)
    # top-p over the top-k-FILTERED distribution (filters compose
    # sequentially, matching _sample_logits): smallest prefix with mass
    # >= p, always keeping the best token. No second O(V log V) sort:
    # the kept set is {x >= kth} and sorted_x is already descending, so
    # the masked sort is the PREFIX of sorted_x with value >= kth — a
    # value compare, NOT a position compare (ties at the kth value all
    # survive the mask, exactly as the scalar reference's re-sort sees
    # them). This runs inside the decode scan every step; the sort is
    # the sampler's dominant cost.
    sorted_m = jnp.where(sorted_x >= kth, sorted_x, -jnp.inf)
    probs = jax.nn.softmax(sorted_m, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1)
    cutoff_idx = jnp.minimum(cutoff_idx, vocab - 1)
    cutoff = jnp.take_along_axis(sorted_m, cutoff_idx[:, None], axis=-1)
    # top_p >= 1.0 must be a strict no-op: fp32 cumsum saturates to 1.0
    # thousands of tokens early at real vocab sizes (measured on v5e:
    # 22604/32000 tokens wrongly masked), so `cum < 1.0` is NOT a no-op
    cutoff = jnp.where((top_p < 1.0)[:, None], cutoff, -jnp.inf)
    return jnp.where(x < cutoff, -jnp.inf, x)


def sample_logits_batched(logits, temperature, top_k, top_p, do_sample,
                          key):
    """Per-ROW sampling: [b, vocab] logits + per-row knob arrays → [b].

    The serving-engine sampler (reference analogue: the dedicated per-row
    kernel phi/kernels/gpu/top_p_sampling_kernel.cu:1, whose ``ps`` input
    is per batch row). All knobs are TRACED ARRAYS, so one compiled
    decode block serves any mix of greedy and sampled requests with any
    per-request temperature/top-k/top-p — no recompile per config:

      temperature [b] f32   (<=0 treated as 1e-6)
      top_k       [b] i32   (0 = disabled)
      top_p       [b] f32   (1.0 = disabled)
      do_sample   [b] bool  (False = argmax row)

    Rows draw independent samples from one key via
    ``jax.random.categorical`` over the jointly masked logits.
    """
    greedy = jnp.argmax(logits, axis=-1)
    x = _mask_logits_rowwise(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(key, x, axis=-1)
    return jnp.where(do_sample, sampled, greedy)


def sample_logits_per_slot(logits, temperature, top_k, top_p, do_sample,
                           keys):
    """``sample_logits_batched`` with per-ROW keys ([b] stacked PRNG
    keys): each row draws from its OWN key instead of a shared per-step
    key. The async serving engine derives row keys as
    ``fold_in(fold_in(base, request_id), token_index)``, which makes a
    request's sampled stream a pure function of (seed, request, token
    index) — independent of batching, speculative-dispatch depth, and
    preemption/replay interleaving, so a pipelined engine stays
    token-identical to its synchronous (depth-1) schedule."""
    greedy = jnp.argmax(logits, axis=-1)
    x = _mask_logits_rowwise(logits, temperature, top_k, top_p)
    sampled = jax.vmap(jax.random.categorical)(keys, x)
    return jnp.where(do_sample, sampled, greedy)


def fold_sampling_keys(base_key, rseed, token_index):
    """Per-row replay-exact sampling keys: ``fold_in(fold_in(base, rid),
    token_index)`` for each row. This derivation IS the serving engine's
    determinism contract — the non-speculative decode scan and the
    speculative verify tick must fold IDENTICALLY so a draft is accepted
    iff it equals the token the plain scan would have emitted (spec-on ≡
    spec-off), and so sampled streams are independent of batching,
    pipelining depth, and preemption/replay. One definition, two call
    sites (``serving._build_decode`` / ``serving._build_spec_decode``)."""
    return jax.vmap(
        lambda r, n: jax.random.fold_in(jax.random.fold_in(base_key, r), n)
    )(rseed, token_index)


def decode_stop_update(tok, active, budget, eos_id):
    """On-device stop detection for one decode step (the sampling body's
    ``done`` bookkeeping). ``tok`` [b] is the token just emitted for rows
    where ``active``; ``budget`` [b] counts remaining allowed tokens;
    ``eos_id`` [b] is the per-row stop id (-1 = disabled). Returns
    ``(new_active, new_budget)`` — a row deactivates AFTER emitting its
    eos/budget-exhausting token (that token is kept, matching the host
    scheduler's append-then-check semantics), so the host never needs a
    block's tokens to decide whether the next block may dispatch."""
    budget = budget - active.astype(budget.dtype)
    stop = active & ((budget <= 0) | ((eos_id >= 0) & (tok == eos_id)))
    return active & ~stop, budget


def generate(model, input_ids, generation_config: GenerationConfig = None,
             **kwargs) -> jnp.ndarray:
    """Autoregressive generation for models exposing
    ``model.prefill(ids, max_len)`` / ``model.decode_step(tok, pos, caches)``
    (LlamaModel contract) with a ``logits(hidden)`` head on the wrapper.

    Returns [b, prompt + max_new_tokens] token ids (prompt included,
    reference generate() convention).
    """
    cfg = generation_config or GenerationConfig(**kwargs)
    input_ids = jnp.asarray(input_ids)
    b, prompt_len = input_ids.shape
    max_len = prompt_len + cfg.max_new_tokens

    core = getattr(model, "model", model)   # LlamaForCausalLM → LlamaModel
    head = model.logits if hasattr(model, "logits") else (lambda h: h)

    hidden, caches = core.prefill(input_ids, max_len)
    logits = head(hidden[:, -1, :])
    key = jax.random.PRNGKey(cfg.seed)

    decode = getattr(model, "_compiled_decode", None)
    if decode is None:
        def _step(tok, pos, caches):
            h, caches = core.decode_step(tok, pos, caches)
            return head(h[:, 0, :]), caches
        decode = _step

    tokens = [input_ids]
    finished = jnp.zeros((b,), bool)
    for i in range(cfg.max_new_tokens):
        key, sub = jax.random.split(key)
        next_tok = _sample_logits(logits.astype(jnp.float32), cfg, sub)
        if cfg.eos_token_id is not None:
            next_tok = jnp.where(finished, cfg.pad_token_id, next_tok)
            finished = finished | (next_tok == cfg.eos_token_id)
        tokens.append(next_tok[:, None])
        if cfg.eos_token_id is not None and bool(finished.all()):
            pad = jnp.full((b, cfg.max_new_tokens - i - 1), cfg.pad_token_id,
                           input_ids.dtype)
            if pad.shape[1]:
                tokens.append(pad)
            break
        if i < cfg.max_new_tokens - 1:
            pos = jnp.full((b,), prompt_len + i, jnp.int32)
            logits, caches = decode(next_tok, pos, caches)
    return jnp.concatenate(tokens, axis=1)


def _compiled_generate(model, cfg: GenerationConfig, b: int, prompt_len: int,
                       kind: str, page_size: int):
    """One jitted (prefill → scan-decode → tokens) program, cached ON THE
    MODEL per (config, shape, cache kind): repeat calls with the same
    shapes reuse the executable instead of re-tracing (the Python-loop
    ``generate`` gets this via _compiled_decode; the scan drivers need it
    too or every call pays full compile).

    ``kind``: "dense" (contiguous [b, max_len, kv, hd] caches) or "paged"
    (head-major page pools + block table — the vLLM-style serving path,
    reference: block_multi_head_attention_kernel.cu). All cache state is
    allocated INSIDE the traced function so nothing is baked into the
    executable as a constant.
    """
    key_ = (kind, page_size, b, prompt_len, cfg.max_new_tokens,
            cfg.do_sample, cfg.temperature, cfg.top_k, cfg.top_p,
            cfg.eos_token_id, cfg.pad_token_id)
    cache = model.__dict__.setdefault("_generate_cache", {})
    if key_ in cache:
        cache[key_] = cache.pop(key_)        # LRU refresh (dict is ordered)
        return cache[key_]

    max_len = prompt_len + cfg.max_new_tokens
    core = getattr(model, "model", model)
    head = model.logits if hasattr(model, "logits") else (lambda h: h)
    eos = cfg.eos_token_id

    def run(params, input_ids, key):
        # run under the layer's functional bridge so params are traced inputs
        with model._bind(params) if hasattr(model, "_bind") else \
                _nullcontext():
            if kind == "paged":
                pools0, tables = core.alloc_paged_caches(b, max_len,
                                                         page_size)
                hidden, caches = core.prefill_paged(input_ids, pools0,
                                                    tables)
                decode = lambda tok, pos, c: core.decode_step_paged(
                    tok, pos, c, tables)
            else:
                hidden, caches = core.prefill(input_ids, max_len)
                decode = core.decode_step
            logits0 = head(hidden[:, -1, :])

            def step(carry, i):
                logits, caches, key, finished = carry
                key, sub = jax.random.split(key)
                tok = _sample_logits(logits.astype(jnp.float32), cfg, sub)
                if eos is not None:
                    tok = jnp.where(finished, cfg.pad_token_id, tok)
                    finished = finished | (tok == eos)
                pos = jnp.full((b,), prompt_len + i, jnp.int32)
                h, caches = decode(tok, pos, caches)
                new_logits = head(h[:, 0, :])
                return (new_logits, caches, key, finished), tok

            finished0 = jnp.zeros((b,), bool)
            (_, _, _, _), toks = jax.lax.scan(
                step, (logits0, caches, key, finished0),
                jnp.arange(cfg.max_new_tokens))
        return jnp.concatenate([input_ids, toks.T], axis=1)

    compiled = jax.jit(run)
    cache[key_] = compiled
    # bounded LRU: serving with varied (batch, prompt_len) shapes must not
    # retain every compiled executable for the model's lifetime
    while len(cache) > 8:
        cache.pop(next(iter(cache)))
    return compiled


def generate_scan(model, input_ids, generation_config: GenerationConfig = None,
                  **kwargs) -> jnp.ndarray:
    """Fully-compiled generation: the whole decode loop is ONE lax.scan
    inside jit — no host↔device roundtrip per token (the Python-loop
    ``generate`` dispatches one device call per step). Finished sequences
    keep emitting pad; output matches ``generate`` for greedy decoding.

    TPU notes: static cache shapes (prompt padded into max_len at prefill),
    dynamic position via the scan carry — everything XLA needs to keep the
    decode step as a single resident program.
    """
    cfg = generation_config or GenerationConfig(**kwargs)
    input_ids = jnp.asarray(input_ids)
    b, prompt_len = input_ids.shape
    params = model.raw_parameters() if hasattr(model, "raw_parameters") else {}
    compiled = _compiled_generate(model, cfg, b, prompt_len, "dense", 0)
    return compiled(params, input_ids, jax.random.PRNGKey(cfg.seed))


def generate_paged(model, input_ids,
                   generation_config: GenerationConfig = None,
                   page_size: int = 128, **kwargs) -> jnp.ndarray:
    """Fully-compiled generation over PAGED KV caches (vLLM-style serving
    path; reference capability: block_multi_head_attention_kernel.cu).

    Instead of one dense [b, max_len, kv, hd] cache per layer, K/V live in
    head-major page pools indexed by a block table; each decode step
    writes one page slot and attends through the Pallas paged kernel on
    TPU (XLA gather elsewhere). Greedy output matches generate_scan.
    """
    cfg = generation_config or GenerationConfig(**kwargs)
    input_ids = jnp.asarray(input_ids)
    b, prompt_len = input_ids.shape
    params = model.raw_parameters() if hasattr(model, "raw_parameters") else {}
    compiled = _compiled_generate(model, cfg, b, prompt_len, "paged",
                                  page_size)
    return compiled(params, input_ids, jax.random.PRNGKey(cfg.seed))


class _nullcontext:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
