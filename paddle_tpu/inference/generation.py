"""LLM generation loop over the KV-cache decode path (reference analogue:
PaddleNLP's generation utils driving the fused/block attention kernels;
in-repo kernels masked_multihead_attention / block_multi_head_attention).

TPU-native: prefill compiles once for the padded prompt length, the decode
step compiles once (static cache shape, dynamic position index), and the
token loop runs on host while all math stays on device. Sampling strategies:
greedy, temperature, top-k, top-p — each a pure function over logits.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    seed: int = 0


def _sample_logits(logits, cfg: GenerationConfig, key):
    """[b, vocab] → [b] next tokens."""
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -cfg.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; always keep the best
        cutoff_idx = jnp.sum(cum < cfg.top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def generate(model, input_ids, generation_config: GenerationConfig = None,
             **kwargs) -> jnp.ndarray:
    """Autoregressive generation for models exposing
    ``model.prefill(ids, max_len)`` / ``model.decode_step(tok, pos, caches)``
    (LlamaModel contract) with a ``logits(hidden)`` head on the wrapper.

    Returns [b, prompt + max_new_tokens] token ids (prompt included,
    reference generate() convention).
    """
    cfg = generation_config or GenerationConfig(**kwargs)
    input_ids = jnp.asarray(input_ids)
    b, prompt_len = input_ids.shape
    max_len = prompt_len + cfg.max_new_tokens

    core = getattr(model, "model", model)   # LlamaForCausalLM → LlamaModel
    head = model.logits if hasattr(model, "logits") else (lambda h: h)

    hidden, caches = core.prefill(input_ids, max_len)
    logits = head(hidden[:, -1, :])
    key = jax.random.PRNGKey(cfg.seed)

    decode = getattr(model, "_compiled_decode", None)
    if decode is None:
        def _step(tok, pos, caches):
            h, caches = core.decode_step(tok, pos, caches)
            return head(h[:, 0, :]), caches
        decode = _step

    tokens = [input_ids]
    finished = jnp.zeros((b,), bool)
    for i in range(cfg.max_new_tokens):
        key, sub = jax.random.split(key)
        next_tok = _sample_logits(logits.astype(jnp.float32), cfg, sub)
        if cfg.eos_token_id is not None:
            next_tok = jnp.where(finished, cfg.pad_token_id, next_tok)
            finished = finished | (next_tok == cfg.eos_token_id)
        tokens.append(next_tok[:, None])
        if cfg.eos_token_id is not None and bool(finished.all()):
            pad = jnp.full((b, cfg.max_new_tokens - i - 1), cfg.pad_token_id,
                           input_ids.dtype)
            if pad.shape[1]:
                tokens.append(pad)
            break
        if i < cfg.max_new_tokens - 1:
            pos = jnp.full((b,), prompt_len + i, jnp.int32)
            logits, caches = decode(next_tok, pos, caches)
    return jnp.concatenate(tokens, axis=1)


def generate_scan(model, input_ids, generation_config: GenerationConfig = None,
                  **kwargs) -> jnp.ndarray:
    """Fully-compiled generation: the whole decode loop is ONE lax.scan
    inside jit — no host↔device roundtrip per token (the Python-loop
    ``generate`` dispatches one device call per step). Finished sequences
    keep emitting pad; output matches ``generate`` for greedy decoding.

    TPU notes: static cache shapes (prompt padded into max_len at prefill),
    dynamic position via the scan carry — everything XLA needs to keep the
    decode step as a single resident program.
    """
    cfg = generation_config or GenerationConfig(**kwargs)
    input_ids = jnp.asarray(input_ids)
    b, prompt_len = input_ids.shape
    max_len = prompt_len + cfg.max_new_tokens
    core = getattr(model, "model", model)
    head = model.logits if hasattr(model, "logits") else (lambda h: h)
    eos = cfg.eos_token_id

    params = model.raw_parameters() if hasattr(model, "raw_parameters") else {}

    def run(params, input_ids, key):
        # run under the layer's functional bridge so params are traced inputs
        with model._bind(params) if hasattr(model, "_bind") else \
                _nullcontext():
            hidden, caches = core.prefill(input_ids, max_len)
            logits0 = head(hidden[:, -1, :])

            def step(carry, i):
                logits, caches, key, finished = carry
                key, sub = jax.random.split(key)
                tok = _sample_logits(logits.astype(jnp.float32), cfg, sub)
                if eos is not None:
                    tok = jnp.where(finished, cfg.pad_token_id, tok)
                    finished = finished | (tok == eos)
                pos = jnp.full((b,), prompt_len + i, jnp.int32)
                h, caches = core.decode_step(tok, pos, caches)
                new_logits = head(h[:, 0, :])
                return (new_logits, caches, key, finished), tok

            finished0 = jnp.zeros((b,), bool)
            (_, _, _, _), toks = jax.lax.scan(
                step, (logits0, caches, key, finished0),
                jnp.arange(cfg.max_new_tokens))
        return jnp.concatenate([input_ids, toks.T], axis=1)

    compiled = jax.jit(run)
    return compiled(params, input_ids, jax.random.PRNGKey(cfg.seed))


class _nullcontext:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
