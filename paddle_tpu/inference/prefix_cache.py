"""Radix prefix-shared KV cache index (ISSUE 7 tentpole).

RadixAttention (Zheng et al., SGLang 2024) over the serving engine's
existing paged pool: a radix/trie index over TOKEN sequences whose nodes
own physical pages in the pool the engine allocates from. Admission
walks the tree, maps the matched pages straight into the new slot's page
table (zero-copy prefix reuse — the integer-factor TTFT win when most
traffic shares a system prompt), and prefills only the unmatched suffix.

Design constraints, all page-shaped:

* **Page-aligned edges and splits.** Every node's edge label is a whole
  number of pages (``len(tokens) == len(pages) * page_size``), and a
  node only ever splits AT a page boundary — so "map the matched
  prefix" is literally copying physical page ids into a table row, and
  a page is shareable iff all ``page_size`` of its tokens matched.
  Divergence INSIDE a page cannot be shared structurally; the engine
  either recomputes that page (chunked-prefill suffix) or, when the
  whole prompt matched, copy-on-writes it (serving.py owns COW — the
  tree only answers "who owns this page").
* **Refcount == number of mapping tables.** ``node.ref`` counts the
  live :class:`PrefixLock` objects (one per engine slot) holding the
  node. A slot's table maps ALL pages of every node in its lock and no
  page of any other node, so per-page "how many tables map me" is
  exactly the owning node's ref — the invariant the engine's fuzz test
  asserts. Splits preserve it by giving the new lower half the same ref
  and splicing it into every registered lock that held the original.
* **Eviction only at ref 0, LRU, tail-first.** Under pool pressure the
  engine asks :meth:`evict` for pages; only leaves nobody maps are
  touched, oldest-``last_use`` first, trimming pages from the END of an
  edge (a shorter prefix stays valid) and deleting emptied nodes so
  their parents become evictable in turn. Freed ids go back to the
  engine's free list — the allocator the rest of the scheduler
  (``pool_dry_drains``, recompute-preemption) already reasons about.
  ``protect`` pins the match path of the request currently being
  admitted so admission can't evict the very prefix it is mapping.

The tree is host-only bookkeeping (ints and numpy token arrays); no
device state lives here.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["RadixPrefixCache", "PrefixLock"]


def _common_len(a: np.ndarray, b: np.ndarray) -> int:
    """Length of the common prefix of two int token arrays."""
    n = min(len(a), len(b))
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if len(neq) else n


class _Node:
    """One radix edge: ``tokens`` (page-multiple length) + the physical
    pages holding their KV. ``ref`` = live locks holding this node."""

    __slots__ = ("tokens", "pages", "children", "parent", "ref",
                 "last_use")

    def __init__(self, tokens: np.ndarray, pages: List[int],
                 parent: Optional["_Node"]):
        self.tokens = np.asarray(tokens, np.int32)
        self.pages = list(pages)
        self.children: Dict[int, "_Node"] = {}
        self.parent = parent
        self.ref = 0
        self.last_use = 0


class PrefixLock:
    """A slot's hold on a root-to-descendant node path. The owning
    table maps exactly ``pages()`` (in order); releasing decrements
    every node once. Registered with the tree so node splits can splice
    the new half into the path and keep refcounts page-exact."""

    __slots__ = ("nodes",)

    def __init__(self, nodes: List[_Node]):
        self.nodes = list(nodes)

    def pages(self) -> List[int]:
        out: List[int] = []
        for n in self.nodes:
            out.extend(n.pages)
        return out


class RadixPrefixCache:
    """Page-granular radix index over token sequences; see module doc."""

    def __init__(self, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = int(page_size)
        self.root = _Node(np.zeros((0,), np.int32), [], None)
        # page id -> owning node (the "is this page tree-owned" oracle
        # the engine's free/COW paths consult per page)
        self._pages: Dict[int, _Node] = {}
        self._locks: List[PrefixLock] = []     # live locks (split fixup)
        self._clock = 0                        # LRU tick
        # bumped whenever a mutation can change match() results
        # (insert grows coverage, evict shrinks it — splits don't):
        # callers may cache per-sequence match lengths against it
        self.epoch = 0

    # -- introspection -------------------------------------------------------

    def owns(self, page: int) -> bool:
        return page in self._pages

    @property
    def num_pages(self) -> int:
        return len(self._pages)

    def num_nodes(self) -> int:
        return sum(1 for _ in self._iter_nodes())

    def _iter_nodes(self):
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    # -- matching / locking --------------------------------------------------

    def _walk(self, tokens: np.ndarray, touch: bool = True):
        """Yield ``(node, n_matched_in_edge)`` along the match path of
        ``tokens``; the last yield is the first partial (or zero) edge
        match. With ``touch`` (default) bumps ``last_use`` on every
        node — a read that precedes a mapping IS a use for LRU
        purposes; pass ``touch=False`` for speculative reads (admission
        PRICING of queued requests) so a request that is deferred every
        tick cannot keep its prefix LRU-hot and crowd out the pages of
        conversations actually being served."""
        tokens = np.asarray(tokens, np.int32)
        if touch:
            self._clock += 1
        node, i = self.root, 0
        while i < len(tokens):
            child = node.children.get(int(tokens[i]))
            if child is None:
                return
            if touch:
                child.last_use = self._clock
            m = _common_len(child.tokens, tokens[i:])
            yield child, m
            if m < len(child.tokens):
                return
            node, i = child, i + m

    def match(self, tokens, touch: bool = True) -> int:
        """Token-granular length of the longest tree prefix of
        ``tokens`` (may end mid-page; the CALLER decides how many whole
        pages of it to map and whether the partial page is COW-able).
        ``touch=False`` reads without bumping LRU (see ``_walk``)."""
        return sum(m for _, m in self._walk(tokens, touch))

    def new_lock(self) -> PrefixLock:
        """An empty registered lock — the engine gives every admitted
        slot one even on a cold miss, so later :meth:`insert` calls can
        attach donated nodes to it and release stays uniform."""
        lock = PrefixLock([])
        self._locks.append(lock)
        return lock

    def lock_prefix(self, tokens, n_pages: int) -> PrefixLock:
        """Take a refcounted hold on exactly the first ``n_pages`` pages
        of the match path (splitting the boundary node page-aligned if
        needed) and return the lock whose ``pages()`` the caller maps
        into its table. ``n_pages`` must not exceed the full pages the
        tree can serve for ``tokens`` (i.e. ``match(tokens) //
        page_size``)."""
        ps = self.page_size
        tokens = np.asarray(tokens, np.int32)
        self._clock += 1
        nodes: List[_Node] = []
        node, i, need = self.root, 0, int(n_pages)
        while need > 0:
            child = (node.children.get(int(tokens[i]))
                     if i < len(tokens) else None)
            if child is None:
                raise ValueError(f"lock_prefix: tree holds fewer than "
                                 f"{n_pages} matched pages for this "
                                 f"prefix")
            child.last_use = self._clock
            m = _common_len(child.tokens, tokens[i:])
            have = min(m // ps, need)
            if have == 0:
                raise ValueError(f"lock_prefix: tree holds fewer than "
                                 f"{n_pages} matched pages for this "
                                 f"prefix")
            if have < len(child.pages):
                self._split(child, have)
            nodes.append(child)
            need -= have
            node, i = child, i + have * ps
        for n in nodes:
            n.ref += 1
        lock = PrefixLock(nodes)
        self._locks.append(lock)
        return lock

    def match_page_ids(self, tokens, touch: bool = False) -> List[int]:
        """Physical ids of every FULLY matched page along the match path
        of ``tokens``, in order — the serialize_pages export set (and
        exactly the pages ``lock_prefix`` could map). Defaults to a
        non-touching read: an export must not bump LRU rank the way a
        mapping admission does."""
        ps = self.page_size
        ids: List[int] = []
        for child, m in self._walk(tokens, touch):
            ids.extend(int(p) for p in child.pages[:m // ps])
            if m < len(child.tokens):
                break
        return ids

    def page_at(self, tokens, page_index: int) -> Optional[int]:
        """Physical id of page ``page_index`` along the match path of
        ``tokens`` — the engine's COW source. The page is returned as
        soon as the match reaches INTO it (it may be only partially
        matched — the caller knows how many of its token slots are
        valid); None when the match stops short of it."""
        ps = self.page_size
        idx = 0
        for child, m in self._walk(tokens):
            for j in range(-(-m // ps)):       # ceil: partial page counts
                if idx == page_index:
                    return int(child.pages[j])
                idx += 1
            if m < len(child.tokens):
                return None
        return None

    def release(self, lock: PrefixLock) -> None:
        """Drop a slot's hold: every node's ref falls by one; pages of
        ref-0 nodes stay CACHED (that is the point) but become
        LRU-evictable under pool pressure."""
        try:
            self._locks.remove(lock)
        except ValueError:
            raise RuntimeError("release of a lock not held (double "
                               "release would corrupt refcounts)")
        for n in lock.nodes:
            n.ref -= 1
            assert n.ref >= 0, "refcount underflow"
        lock.nodes = []

    # -- insertion -----------------------------------------------------------

    def insert(self, tokens, pages: List[int],
               lock: Optional[PrefixLock] = None) -> List[int]:
        """Donate ``pages`` (one per ``page_size`` tokens of
        ``tokens``) to the tree. Ranges the tree already covers are
        skipped — the caller KEEPS those duplicate pages private (they
        stay in its table; the return value lists the donated ids that
        actually became tree-owned so the caller can account). Newly
        created nodes join ``lock`` (ref 1) when given, so the owning
        slot's release path needs no special casing; pass ``lock=None``
        only when the donor no longer maps the pages (ref starts 0).

        A divergence INSIDE a page is not insertable past the aligned
        boundary (page-granularity limit, documented in serving's
        design notes) — the remainder is silently dropped and stays the
        caller's private pages."""
        ps = self.page_size
        tokens = np.asarray(tokens, np.int32)
        if len(tokens) % ps:
            raise ValueError("insert needs a whole-page token multiple")
        if len(tokens) != len(pages) * ps:
            raise ValueError(f"insert: {len(tokens)} tokens need "
                             f"{len(tokens) // ps} pages, got {len(pages)}")
        self._clock += 1
        donated: List[int] = []
        node, i = self.root, 0
        while i < len(tokens):
            child = node.children.get(int(tokens[i]))
            if child is None:
                new = _Node(tokens[i:], pages[i // ps:], node)
                new.last_use = self._clock
                node.children[int(tokens[i])] = new
                for p in new.pages:
                    self._pages[p] = new
                donated.extend(new.pages)
                self.epoch += 1
                if lock is not None:
                    new.ref = 1
                    lock.nodes.append(new)
                return donated
            child.last_use = self._clock
            m = _common_len(child.tokens, tokens[i:])
            k = m // ps
            if m == len(child.tokens):
                node, i = child, i + m          # full edge: descend
                continue
            if k == 0:
                return donated                  # mid-page divergence
            if k < len(child.pages):
                self._split(child, k)
            node, i = child, i + k * ps
        return donated

    # -- splits --------------------------------------------------------------

    def _split(self, node: _Node, k: int) -> None:
        """Split ``node`` page-aligned after its first ``k`` pages:
        ``node`` keeps the top, a new lower node takes the rest (same
        ref — every holder of the original maps both halves). Every
        registered lock holding ``node`` gets the lower half spliced in
        right after it, so release stays one-decrement-per-node."""
        ps = self.page_size
        assert 0 < k < len(node.pages)
        lower = _Node(node.tokens[k * ps:], node.pages[k:], node)
        lower.children = node.children
        for c in lower.children.values():
            c.parent = lower
        lower.ref = node.ref
        lower.last_use = node.last_use
        node.tokens = node.tokens[:k * ps]
        node.pages = node.pages[:k]
        node.children = {int(lower.tokens[0]): lower}
        for p in lower.pages:
            self._pages[p] = lower
        for lk in self._locks:
            if node in lk.nodes:
                lk.nodes.insert(lk.nodes.index(node) + 1, lower)

    # -- eviction ------------------------------------------------------------

    def evict(self, n: int, protect=None) -> List[int]:
        """Free up to ``n`` pages from refcount-0 LRU leaves (tail pages
        first; emptied nodes are unlinked so parents become leaves) and
        return the freed physical ids. ``protect`` pins every node on
        that token sequence's match path — admission evicts FOR a
        request without eating the prefix it is about to map."""
        pinned = set()
        if protect is not None:
            for child, _ in self._walk(protect):
                pinned.add(id(child))
        freed: List[int] = []
        while len(freed) < n:
            victim = None
            for cand in self._iter_nodes():
                if (cand.ref == 0 and not cand.children
                        and id(cand) not in pinned
                        and (victim is None
                             or cand.last_use < victim.last_use)):
                    victim = cand
            if victim is None:
                break
            ps = self.page_size
            while victim.pages and len(freed) < n:
                p = victim.pages.pop()
                victim.tokens = victim.tokens[:len(victim.pages) * ps]
                del self._pages[p]
                freed.append(p)
            if not victim.pages:
                # unlink by identity (the emptied node's first-token
                # key is gone with its tokens)
                parent = victim.parent
                for key, c in list(parent.children.items()):
                    if c is victim:
                        del parent.children[key]
                        break
        if freed:
            self.epoch += 1
        return freed

    # -- invariants (test hook) ---------------------------------------------

    def check(self) -> None:
        """Structural self-check: page-aligned edges, page-map
        consistency, non-negative refs, child keys, lock paths."""
        ps = self.page_size
        seen: Dict[int, _Node] = {}
        for n in self._iter_nodes():
            assert len(n.tokens) == len(n.pages) * ps, "unaligned edge"
            assert len(n.pages) > 0, "empty node left linked"
            assert n.ref >= 0, "negative refcount"
            for key, c in n.children.items():
                assert c.parent is n and int(c.tokens[0]) == key
            for p in n.pages:
                assert p not in seen, f"page {p} owned twice"
                seen[p] = n
        assert seen == self._pages, "page map out of sync"
        for key, c in self.root.children.items():
            assert c.parent is self.root and int(c.tokens[0]) == key
        held: Dict[int, int] = {}
        for lk in self._locks:
            for nnode in lk.nodes:
                held[id(nnode)] = held.get(id(nnode), 0) + 1
        for n in self._iter_nodes():
            assert n.ref == held.get(id(n), 0), \
                "node ref != live locks holding it"
