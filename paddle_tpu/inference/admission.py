"""SLO-aware admission / eviction policy for the serving engine (ISSUE 7).

The continuous-batching engine's default scheduling is FIFO admission
and newest-first recompute-preemption — exact, simple, and oblivious to
both the prefix cache and the latency SLOs the telemetry plane (PR 4)
already measures. This module is the pluggable policy object that makes
those two signals drive scheduling:

* **Prefix-cache-aware ordering** (the SGLang insight): among queued
  requests, admit the one with the SHORTEST uncached suffix first — its
  prefill is cheapest, it reuses the hottest tree path before eviction
  can claim it, and batching high-hit requests together keeps shared
  pages shared. FIFO order breaks ties, and a starvation bound forces
  the oldest request through after ``starvation_ticks`` skips.
* **SLO-priced admission**: a request's admission cost is its predicted
  prefill work — the UNCACHED suffix length, since matched pages cost
  one table write. When the engine's inter-token-latency percentile
  gauge is over target (running decodes already stalling), a long cold
  prefill would stretch every running request's ITL further, so it is
  DEFERRED; cheap high-hit admits still flow. TTFT pressure pushes the
  other way (queued requests aging), so a TTFT-target breach disables
  deferral — admit and eat the ITL hit.
* **Victim choice** for recompute-preemption: prefer slots that cost
  the least to replay (low progress — fewest generated tokens burned)
  and free the most real memory (many PRIVATE pages, few shared
  tree-refs: evicting a high-sharing slot returns almost nothing to the
  pool because the tree still owns its prefix).

The policy is deliberately host-pure and engine-agnostic: ``select``
and ``choose_victim`` take plain snapshots, so unit tests drive them
with synthetic gauges (tests/test_serving_prefix.py) and the engine
calls them with live ones.

In a multi-replica deployment this policy is the per-replica LEAF of
the fabric's policy tree (ISSUE 12): ``serving_fabric.TenantFairPolicy``
decides which tenant's request leaves the ROUTER's global queue
(weighted fairness + token buckets, priced in the same uncached-suffix
unit ``uncached_of`` computes here), and each engine's
``SLOAdmissionPolicy`` still orders and defers its own admits against
its own gauges.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["AdmissionPolicy", "SLOAdmissionPolicy", "VictimInfo"]


@dataclass
class VictimInfo:
    """One preemptible slot as the victim chooser sees it."""
    slot: int
    rid: int
    progress: int          # generated tokens that replay would recompute
    private_pages: int     # pages eviction returns to the pool
    shared_pages: int      # tree-owned pages (eviction frees none)


class AdmissionPolicy:
    """Base contract. The default instance reproduces the engine's
    built-in behavior (FIFO admission, newest-rid victim) so subclasses
    can override one decision without re-specifying the other."""

    def select(self, queue: Sequence, uncached_of: Callable[[object], int],
               lat: Dict[str, float]) -> Optional[int]:
        """Index into ``queue`` of the request to admit next, or None
        to defer every queued request this tick. ``uncached_of(req)``
        prices a request's prefill (uncached suffix tokens); ``lat`` is
        the engine's ``latency_stats()`` snapshot."""
        return 0 if len(queue) else None

    def note_admitted(self, queue: Sequence, chosen: int) -> None:
        """Feedback hook: the engine admitted ``queue[chosen]`` (pages
        really claimed). Default: stateless, nothing to record."""

    def choose_victim(self, candidates: List[VictimInfo]) -> int:
        """Slot to recompute-preempt when the pool is dry; must pick
        from ``candidates`` (non-empty)."""
        return max(candidates, key=lambda v: v.rid).slot


class SLOAdmissionPolicy(AdmissionPolicy):
    """Admission priced by predicted prefill cost against the live
    TTFT/ITL percentile gauges; see module docstring.

    ``itl_p99_target_s`` — defer admits costlier than
    ``defer_uncached_tokens`` while ``lat["itl_p99_s"]`` exceeds this
    (None disables deferral).
    ``ttft_p99_target_s`` — when ``lat["ttft_p99_s"]`` ALSO breaches
    this, queued requests are the emergency: deferral is suspended.
    ``defer_uncached_tokens`` — admits at or below this predicted
    prefill cost are never deferred (they barely dent ITL).
    ``starvation_ticks`` — a request skipped this many select() calls
    (by ordering or deferral) is forced through FIFO-style regardless.
    """

    def __init__(self, itl_p99_target_s: Optional[float] = None,
                 ttft_p99_target_s: Optional[float] = None,
                 defer_uncached_tokens: int = 256,
                 starvation_ticks: int = 64):
        self.itl_p99_target_s = itl_p99_target_s
        self.ttft_p99_target_s = ttft_p99_target_s
        self.defer_uncached_tokens = int(defer_uncached_tokens)
        self.starvation_ticks = int(starvation_ticks)
        self.deferrals = 0                     # lifetime defer decisions
        self._skips: Dict[int, int] = {}       # id(req) -> skipped selects

    # -- admission -----------------------------------------------------------

    def _itl_breached(self, lat: Dict[str, float]) -> bool:
        if self.itl_p99_target_s is None:
            return False
        itl = lat.get("itl_p99_s")
        if itl is None or itl <= self.itl_p99_target_s:
            return False
        if self.ttft_p99_target_s is not None and \
                lat.get("ttft_p99_s", 0.0) > self.ttft_p99_target_s:
            return False                       # queue is the bigger fire
        return True

    def select(self, queue, uncached_of, lat):
        if not queue:
            return None
        live = {id(r) for r in queue}
        self._skips = {k: v for k, v in self._skips.items() if k in live}
        # starvation override: the oldest over-skipped request wins
        for i, req in enumerate(queue):
            if self._skips.get(id(req), 0) >= self.starvation_ticks:
                return i
        costs = [int(uncached_of(r)) for r in queue]
        order = sorted(range(len(queue)), key=lambda i: (costs[i], i))
        breached = self._itl_breached(lat)
        for i in order:
            if breached and costs[i] > self.defer_uncached_tokens:
                continue                       # too expensive right now
            return i
        # every queued request is an expensive cold prefill during an
        # ITL breach: defer them all, let running decodes catch up —
        # a genuine policy decision, so it counts toward starvation
        self.deferrals += 1
        for req in queue:
            self._skips[id(req)] = self._skips.get(id(req), 0) + 1
        return None

    def note_admitted(self, queue, chosen: int) -> None:
        """Charge a skip to every request a SUCCESSFUL admit passed
        over. The engine calls this only once pages were actually
        claimed — a tick where the pool blocked the chosen admit
        charged nobody (no real admission opportunity was lost), and a
        request repeatedly chosen but unadmittable keeps accruing
        others' skips toward its own starvation protection."""
        for i, req in enumerate(queue):
            if i != chosen:
                self._skips[id(req)] = self._skips.get(id(req), 0) + 1
        self._skips.pop(id(queue[chosen]), None)

    # -- preemption victim ---------------------------------------------------

    def choose_victim(self, candidates: List[VictimInfo]) -> int:
        """Cheapest replay first: least progress burned, then most
        private pages actually returned to the pool, then fewest shared
        refs (leave high-sharing slots resident), newest rid last — the
        default rule's tiebreak, so the policy degrades to it when all
        else is equal."""
        best = min(candidates,
                   key=lambda v: (v.progress, -v.private_pages,
                                  v.shared_pages, -v.rid))
        return best.slot
