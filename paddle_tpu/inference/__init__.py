"""paddle_tpu.inference — deployment engine (reference:
paddle/fluid/inference/ AnalysisPredictor, api at
paddle_inference_api.h / python paddle.inference.{Config,create_predictor}).

TPU-native redesign: the reference's IR-pass pipeline + engine offload
collapses into XLA AOT — a Predictor wraps a jit.save'd export (StableHLO)
or a live Layer jitted on first run. The name/handle API
(get_input_names/get_input_handle/run) is preserved so serving code ports,
but handles are zero-copy device arrays rather than LoDTensors. LLM serving
(KV-cache generation loops, greedy/top-k/top-p) lives in
paddle_tpu.inference.generation; the production serving control plane —
continuous batching, radix prefix-shared KV, SLO-aware admission — in
paddle_tpu.inference.{serving,prefix_cache,admission}.
"""

from .predictor import Config, Predictor, create_predictor
from . import generation
from .generation import GenerationConfig, generate
from .serving import ContinuousBatchingEngine
from .speculative import DraftProvider, NgramDraftProvider
from .prefix_cache import RadixPrefixCache
from .admission import AdmissionPolicy, SLOAdmissionPolicy, VictimInfo

__all__ = ["Config", "Predictor", "create_predictor", "generation",
           "GenerationConfig", "generate", "ContinuousBatchingEngine",
           "DraftProvider", "NgramDraftProvider", "RadixPrefixCache",
           "AdmissionPolicy", "SLOAdmissionPolicy", "VictimInfo"]
