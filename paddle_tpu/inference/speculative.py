"""Draft proposers for token-level speculative decoding (ISSUE 6).

Speculative decoding (Leviathan et al., 2023) closes decode's
memory-bandwidth gap: instead of one weight pass per token, a cheap
draft proposes k tokens and the target model VERIFIES all k positions in
one batched forward. The serving engine
(``serving.ContinuousBatchingEngine(spec_k=k)``) owns the verify loop;
this module owns the drafting side behind one small contract.

``DraftProvider`` is the extension point: ``propose`` runs INSIDE the
engine's compiled decode tick (it must be pure jax, traced arrays in →
traced arrays out, no host state). The first provider is draft-FREE
prompt-lookup / n-gram drafting (Saxena, 2023): match the stream's
trailing n-gram against its own prompt+generated history and propose the
tokens that followed the previous occurrence — zero extra model cost,
and on repetitive or quoting workloads acceptance is high. A small draft
MODEL sharing the paged KV pool is the planned second implementation
(same signature; it would close over its own params/pools the way the
engine's decode fn closes over the target's).
"""

from __future__ import annotations

import jax.numpy as jnp


class DraftProvider:
    """Contract for speculative-draft proposers.

    ``propose(history, hist_len, k)`` → ``[B, k]`` int32 draft tokens.

    * ``history`` ``[B, H]`` int32 — per-slot token history (prompt +
      committed generations, device-resident, maintained by the engine);
      entries at index >= ``hist_len`` are stale and must be ignored.
    * ``hist_len`` ``[B]`` int32 — valid prefix length per row. The
      engine calls ``propose`` AFTER appending the tick's first
      (unconditionally committed) token, so drafts condition on it.
    * ``k`` — static draft length (compiled into the engine's tick).

    The call is traced into the engine's compiled decode block, so it
    must be jit-pure: no python branching on array values, no host I/O.
    Rows the engine has deactivated are proposed for anyway and masked by
    the engine — providers need no liveness logic. Proposals are SAFE by
    construction: a wrong draft costs only wasted verify width (the
    engine's acceptance step masks the rejected suffix to pad and routes
    its KV to the garbage page), never a wrong output token.
    """

    def propose(self, history, hist_len, k: int):
        raise NotImplementedError


class NgramDraftProvider(DraftProvider):
    """Prompt-lookup / n-gram drafting over the slot's own history.

    For each row, find the most recent PRIOR occurrence of the trailing
    ``n``-gram (longest ``n`` first, ``max_ngram`` down to ``min_ngram``)
    and propose the ``k`` tokens that followed it. Rows with no match —
    or matches whose continuation runs off the end of history — fall back
    to repeating the last token (still occasionally right on repetitive
    text, and wrong drafts are free).

    Everything is vectorized over ``[B, H]``: the match scan is a handful
    of rolled equality ANDs + one argmax-style reduction, a few microsec
    of VPU work next to the verify forward it feeds.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not (1 <= min_ngram <= max_ngram):
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, got "
                             f"({min_ngram}, {max_ngram})")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, history, hist_len, k: int):
        B, H = history.shape
        pos_i = jnp.arange(H, dtype=jnp.int32)[None, :]          # [1, H]
        last_tok = jnp.take_along_axis(
            history, jnp.clip(hist_len - 1, 0, H - 1)[:, None], axis=1)
        best = jnp.full((B,), -1, jnp.int32)   # continuation start, -1=none
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            suf_idx = jnp.clip(hist_len[:, None] - n
                               + jnp.arange(n, dtype=jnp.int32)[None, :],
                               0, H - 1)
            suffix = jnp.take_along_axis(history, suf_idx, axis=1)  # [B,n]
            m = jnp.ones((B, H), bool)
            for j in range(n):
                # roll wraps, but validity below forces i+n < hist_len
                # <= H so wrapped tail positions never survive the mask
                m &= jnp.roll(history, -j, axis=1) == suffix[:, j:j + 1]
            # strictly PRIOR occurrence with at least one continuation
            # token (the trailing n-gram itself sits at i = hist_len - n
            # and is excluded by i + n < hist_len)
            m &= (pos_i + n) < hist_len[:, None]
            m &= (hist_len >= n + 1)[:, None]
            cand = jnp.max(jnp.where(m, pos_i + n, -1), axis=1)
            best = jnp.where(best < 0, cand, best)   # longest n wins
        d_idx = best[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
        in_hist = (best[:, None] >= 0) & (d_idx < hist_len[:, None])
        toks = jnp.take_along_axis(history, jnp.clip(d_idx, 0, H - 1),
                                   axis=1)
        return jnp.where(in_hist, toks, last_tok).astype(jnp.int32)


__all__ = ["DraftProvider", "NgramDraftProvider"]
