"""Async continuous-batching serving engine over the paged-KV decode path.

Reference capability: the block/paged KV-cache serving stack
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu and the
fleet dist-inference helpers). The reference exposes the kernel; serving
systems built on it (vLLM-style) add a page allocator + request scheduler.
This module is that scheduler, TPU-shaped:

- ONE compiled decode block over ``max_batch`` fixed slots (static shapes;
  no recompilation as requests come and go). Inactive slots write their
  K/V into a reserved garbage page and their sampled token is ignored.
- A host-side free-list page allocator over a global pool. Prompt pages
  are claimed at admission; decode pages are claimed LAZILY when a
  sequence's position crosses a page boundary, so short completions never
  reserve worst-case memory (the point of paged attention).
- Recompute-style preemption: if the pool is exhausted when a running
  sequence needs its next page, the most recently admitted active slot is
  evicted back to the queue (pages freed, generated tokens kept for
  replay) — vLLM's "recompute" policy, which on TPU is just a re-prefill.
- Prefill runs per-slot with the prompt padded up to a page multiple
  (bucketed → bounded executable count); the first-token logits are taken
  at the true last-prompt index.

ASYNC hot loop (vLLM SOSP'23 / NanoFlow-style host-overlap, TPU-shaped):

- Stop detection runs ON DEVICE: the decode scan carries per-slot eos ids
  and remaining-token budgets, deactivates a slot the step AFTER it emits
  its stop token, masks later tokens to pad and routes their K/V to the
  garbage page. The host never needs block N's tokens to decide whether
  block N+1 may dispatch.
- Dispatches are PIPELINED: block N+1 is issued while block N is still in
  flight (bounded window, ``async_depth``, default 2). Block N's [K, B]
  tokens + done flags drain via an async device→host copy and are
  reconciled at block boundaries — retirements, admissions and page
  bookkeeping all happen one block behind the device, hidden under its
  compute. A slot retired by block N's results had its speculative
  block-N+1 writes routed to the garbage page by the same on-device
  active mask, so rollback is free and outputs are bit-identical to the
  synchronous (``async_depth=1``) schedule.
- Scheduler state is DEVICE-RESIDENT: pos, active mask, budgets, sampling
  knobs and last logits persist as device arrays threaded from block to
  block; admissions/evictions touch them through small jitted update fns.
  The per-tick host work of the old engine (seven ``jnp.asarray`` uploads
  + a host ``jax.random.split``) is gone; sampling keys fold on-device
  from (seed, request id, token index), making sampled streams
  schedule-independent (and exact across preemption/replay).

TOKEN-LEVEL SPECULATION (``spec_k > 0``, Leviathan'23 / prompt-lookup
Saxena'23, TPU-shaped):

- Each tick drafts ``spec_k`` tokens from the slot's device-resident
  token history (``DraftProvider``; n-gram prompt-lookup by default —
  zero model cost), verifies all of them in ONE (spec_k+1)-wide forward
  against the paged KV cache (``decode_verify_paged``), and commits the
  agreeing prefix: 1..spec_k+1 tokens per weight pass.
- Acceptance reuses the replay-exact (seed, rid, token_index) keys, so
  a draft is accepted iff it EQUALS the token the non-speculative scan
  would have emitted — spec-on streams are token-identical to spec-off,
  greedy and sampled alike (tests/test_serving_spec.py).
- Accept/reject folds into the same ``decode_stop_update`` carry that
  retires slots: rejected suffixes leave the tick as pad with
  ``kept=False`` and their K/V is overwritten by the next verify chunk
  (positions advance only by the committed prefix) or routed to the
  garbage page — no rollback, and the depth-2 in-flight window is
  preserved because a speculatively dispatched block self-masks tokens
  the previous block rejected, exactly as it self-masks retired slots.
- Page claims become variable-stride: the host projects the MAX stride
  per in-flight block and re-anchors at drained truth; tables keep every
  page ever claimed, so claim coverage is monotone and always ahead of
  what the device can commit.

RADIX PREFIX SHARING (``prefix_cache=True``, PagedAttention Kwon'23 /
RadixAttention Zheng'24, TPU-shaped):

- A radix tree over token sequences (``prefix_cache.RadixPrefixCache``)
  owns REFCOUNTED pages in the same pool the engine allocates from.
  Admission walks the tree, maps the matched pages straight into the new
  slot's page table (one lock per slot; node splits are page-aligned)
  and prefills ONLY the unmatched suffix through the existing
  chunked-prefill path from a page-aligned offset — shared system
  prompts cost one table write instead of a full prefill.
- A FULL-prompt match takes the COW fast path: the page holding the
  last prompt token is copy-on-written into a private page (decode is
  about to diverge into it) and exactly ONE token is re-forwarded
  (``decode_verify_paged`` at L-1) to produce the first-token logits —
  TTFT collapses to one decode-step's work.
- Retiring/preempted slots DONATE their completed full pages to the
  tree before their lock releases, so conversation-style reuse and
  preemption replay both hit. Refcount-0 tree pages stay cached and are
  LRU-evicted (tail-first) only under pool pressure, inside
  ``_alloc_pages`` — the ``pool_dry_drains``/recompute-preemption
  machinery downstream is untouched, it just sees a deeper pool.
- The refcount invariant (fuzz-tested): every pool page is free, OR
  privately owned by exactly one table, OR tree-owned with
  ``node.ref == number of tables mapping it``. Decode never writes a
  shared page: the mapped prefix always ends below the first decode
  position (the COW fast path privatizes the boundary page at admit).
- ``prefix_cache=False`` (default) leaves every path above unbuilt —
  the engine is characterization-identical to the pre-prefix code.

SLO-AWARE ADMISSION (``admission=SLOAdmissionPolicy(...)``): queued
requests are admitted shortest-uncached-suffix first (prefix-aware
ordering — the SGLang insight), a long cold prefill is DEFERRED while
the ITL p99 gauge breaches its target (unless TTFT is also breaching),
and recompute-preemption prefers low-progress / low-shared-refcount
victims. ``admission=None`` (default) keeps FIFO + newest-rid victims.

The engine is exact: greedy outputs match ``generate_scan`` per request
regardless of batching/preemption/pipelining/speculation/prefix-sharing
interleaving (tests/test_serving.py, tests/test_serving_async.py,
tests/test_serving_spec.py, tests/test_serving_prefix.py).
"""

from __future__ import annotations

import hashlib
import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.metrics import REGISTRY as _REG
from ..observability.sentry import sentry as _sentry
from ..observability.tracing import TRACER as _TRACE
from ..profiler import RecordEvent
from .admission import AdmissionPolicy, VictimInfo
from .generation import (GenerationConfig, decode_stop_update,
                         fold_sampling_keys, sample_logits_per_slot)
from .prefix_cache import RadixPrefixCache
from .speculative import DraftProvider, NgramDraftProvider


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray                  # [L] int32
    max_new_tokens: int
    # per-request sampling knobs (engine defaults when not overridden)
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    do_sample: bool = False
    eos_token_id: Optional[int] = None
    # sampling-stream identity: the value folded into the per-token keys
    # (defaults to rid). A router re-admitting a request on ANOTHER
    # engine passes the original identity so the sampled stream is
    # engine-independent (serving_fabric failover replay).
    rseed: Optional[int] = None
    generated: List[int] = field(default_factory=list)
    done: bool = False
    slot: int = -1                      # active slot, -1 = queued/finished
    submit_t: float = 0.0               # perf_counter at submit
    first_tok_t: float = 0.0            # TTFT timestamp (0 = none yet)
    done_t: float = 0.0                 # completion timestamp
    last_emit_t: float = 0.0            # previous tick's emit timestamp
    itl_gaps: List[float] = field(default_factory=list)  # per-TICK gaps
    prefilled: int = 0                  # KV tokens written (chunked mode)
    prefill_target: int = 0             # prompt+replay length to prefill
    # distributed tracing (ISSUE 19): {"tr": tracer, "parent": wire ctx,
    # "queue"/"res": open spans, "last": decode-epoch wall stamp}. None
    # when untraced — every tracing branch below is one attr test.
    tspans: Optional[dict] = None


@dataclass
class _InflightBlock:
    """One dispatched decode block awaiting host reconciliation. The
    device arrays are the block's OUTPUTS (fresh buffers, never donated),
    async-copied to host at dispatch; ``participants`` snapshots the
    (slot, request) pairs the host believed live at dispatch time —
    a slot that stopped on-device in an earlier in-flight block simply
    drains an all-False kept column here."""
    toks: object                        # [K, B] device int32
    kept: object                        # [K, B] device bool (prefix mask)
    pos: object                         # [B] device int32, post-block
    active: object                      # [B] device bool, post-block
    participants: List[Tuple[int, "_Request"]]
    K: int
    # spec mode only: per-slot MAX possible commits this block (the
    # stride the host projected at dispatch) — drains subtract it back
    # out of the projection when the device committed fewer
    steps: Optional[Dict[int, int]] = None


# self-describing KV-page handoff payload format (serialize_pages /
# adopt_pages); bump on any layout change — adoption REJECTS unknown fmts.
# v2 (ISSUE 17) carries the pool dtype and, for int8 pools, the per-page
# fp32 K/V scales. v1 payloads (scale-less) are still adopted by NATIVE
# (bf16/f32) pools — a v1 emitter predates quantized pools, so its pages
# are float and layout-compatible; an int8 pool REJECTS v1 (no scales to
# dequant by), and the fabric's failed-handoff path falls back to a cold
# prefill.
HANDOFF_FMT = "pt-kv-pages-v2"
HANDOFF_FMT_V1 = "pt-kv-pages-v1"


def _entry_page_copy(entry, src, dst):
    """Copy physical page ``src`` → ``dst`` within one per-layer pool
    entry, generically over layout: 4-D pool arrays carry pages on axis
    1, 1-D per-page scale arrays (int8 pools) on axis 0 — so COW, the
    tail re-forward and page adoption move a page's scale with its
    bytes for free."""
    return tuple(a.at[:, dst].set(a[:, src]) if a.ndim == 4
                 else a.at[dst].set(a[src]) for a in entry)


class _PoolDry(Exception):
    """Page pool exhausted while speculative blocks are still in flight:
    drain them first (retirements may free pages) before preempting."""


class ContinuousBatchingEngine:
    """vLLM-style continuous batching over a model exposing the paged-KV
    trio (``alloc_paged_caches`` / ``prefill_paged`` / ``decode_step_paged``
    on its core, e.g. ``LlamaForCausalLM``).

    ``async_depth``: bounded in-flight dispatch window. 1 = synchronous
    (dispatch → drain → bookkeep, the pre-async engine's schedule, kept
    bit-identical); 2 (default) overlaps host scheduling/bookkeeping of
    block N with the device computing block N+1.

    ``spec_k``: draft tokens per speculative tick (0 = off). When on,
    the tick is one (spec_k+1)-wide verify forward and ``decode_block``
    is NOT consulted — the spec tick already amortizes the host round
    trip over its committed run the way a K-token block does."""

    def __init__(self, model, max_batch: int = 8, page_size: int = 128,
                 max_len: int = 2048, num_pages: Optional[int] = None,
                 generation_config: Optional[GenerationConfig] = None,
                 decode_block: int = 1, chunked_prefill: bool = False,
                 prefill_chunk: Optional[int] = None, async_depth: int = 2,
                 attn_crossover: Optional[int] = None, spec_k: int = 0,
                 draft_provider: Optional[DraftProvider] = None,
                 prefix_cache: bool = False,
                 admission: Optional[AdmissionPolicy] = None,
                 name: Optional[str] = None):
        self.model = model
        # replica identity (ISSUE 12 satellite): N engines in one process
        # (the in-proc serving fabric) must not merge their registry
        # series — every gauge/counter this engine publishes carries an
        # engine=<name> label when a name is given. Unnamed engines keep
        # their historical label-free series.
        self.name = name or ""
        self._mlabels: Dict[str, str] = ({"engine": self.name}
                                         if self.name else {})
        # tracer override hook: tests inject a private Tracer so ONE
        # process can play both sides of the TCP hop without the
        # replica's spans landing in the router's singleton
        self._tracer = None
        self.core = getattr(model, "model", model)
        if spec_k and not hasattr(self.core, "decode_verify_paged"):
            raise ValueError(
                f"spec_k={spec_k} needs a model whose core implements "
                f"decode_verify_paged (multi-token paged verify); "
                f"{type(self.core).__name__} does not")
        self.cfg = generation_config or GenerationConfig()
        self.max_batch = max_batch
        self.page_size = page_size
        self.max_len = max_len
        self.pages_per_seq = -(-max_len // page_size)
        # pool: page 0 is the reserved garbage page for inactive slots
        total = (num_pages if num_pages is not None
                 else max_batch * self.pages_per_seq) + 1
        pools, _ = self.core.alloc_paged_caches(
            1, total * page_size, page_size)
        self.pools = pools
        # int8 KV pages (ISSUE 17): a quantized pool's per-layer entry is
        # the 4-tuple (kp, vp, kscale, vscale); everything below that
        # moves pages (COW, handoff, adoption) is layout-generic, and the
        # decode/prefill write paths quantize inside the model
        self.kv_quant = len(pools[0]) == 4
        self.kv_quant_ticks = 0             # decode ticks on an int8 pool
        self._total_pages = total - 1
        self._free: List[int] = list(range(total - 1, 0, -1))  # stack; 0 kept
        self.tables = np.zeros((max_batch, self.pages_per_seq), np.int32)
        self._tables_dev = None
        self._tables_dirty = True
        # reconciled positions (exact up to the last drained block) and
        # the device-side PROJECTION including in-flight blocks — the
        # allocator claims pages against the projection, so speculative
        # writes always land in owned pages. For a live (not-stopped)
        # slot projection == device pos; an early eos only ever makes the
        # projection an over-claim, freed wholesale at retirement.
        self.pos = np.zeros((max_batch,), np.int32)
        self._proj_pos = np.zeros((max_batch,), np.int64)
        self._proj_gen = np.zeros((max_batch,), np.int64)
        # host mirrors of the per-slot sampling knobs (device copies are
        # updated by the jitted activation fn; the mirror only drives the
        # any_sample executable choice)
        self._dosample = np.zeros((max_batch,), bool)
        self._slots: List[Optional[_Request]] = [None] * max_batch
        self._queue: Deque[_Request] = deque()
        self._requests: Dict[int, _Request] = {}
        self._rid = itertools.count()
        self._params = (model.raw_parameters()
                        if hasattr(model, "raw_parameters") else {})
        self._base_key = jax.random.PRNGKey(self.cfg.seed)
        self._prefill_cache: Dict[int, object] = {}
        # decode_block = tokens generated per compiled scheduler tick. One
        # tick costs ONE dispatch + ONE host readback regardless of K, so
        # over a high-latency link (tunneled TPU; real pods to a lesser
        # degree) throughput scales ~K until compute dominates. The scan
        # deactivates a slot at its own EOS/max_new ON DEVICE, so tokens
        # past the stop are pad + garbage-page KV and outputs are EXACT
        # for any K.
        self.decode_block = max(1, int(decode_block))
        self._decode_fns: Dict[tuple, object] = {}  # (K, sample, impl) -> fn
        self.async_depth = max(1, int(async_depth))
        # token-level speculative decoding (ISSUE 6): each tick drafts
        # spec_k tokens (DraftProvider, n-gram prompt-lookup by default),
        # verifies all of them in ONE (spec_k+1)-wide forward against the
        # paged KV cache, and commits the matching prefix — 1..spec_k+1
        # tokens per tick for one weight pass. spec_k=0 is EXACTLY the
        # non-speculative engine (every spec branch below is gated).
        self.spec_k = max(0, int(spec_k))
        self._draft: Optional[DraftProvider] = None
        self._hist = None                   # [B, max_len] device history
        self._hist_set_fn = None
        self.spec_tokens_proposed = 0       # drafts scored by a verify pass
        self.spec_tokens_accepted = 0       # drafts committed (beyond the
        #                                     tick's one guaranteed token)
        self._spec_drains = 0               # committing spec drains
        if self.spec_k:
            self._draft = draft_provider or NgramDraftProvider()
            self._hist = jnp.zeros((max_batch, max_len), jnp.int32)
        # context-aware dense/paged dispatch (VERDICT r05 weak #5: the
        # engine always paged despite its own crossover data — dense wins
        # short contexts, the Pallas paged kernel wins 1.45-3.6x at 8-16K).
        # Each dispatched block picks the attention path from the batch's
        # MAX projected context vs the measured crossover (TuneDB-backed,
        # autotune.paged_decode_crossover); the choice is baked per
        # executable, so at most 2 executables per (K, any_sample).
        if attn_crossover is None:
            from ..ops.pallas.autotune import paged_decode_crossover
            attn_crossover = paged_decode_crossover()
        self.attn_crossover = int(attn_crossover)
        self.attn_path_ticks = {"dense": 0, "paged": 0}
        self._inflight: Deque[_InflightBlock] = deque()
        # radix prefix-shared KV (ISSUE 7): tree nodes own refcounted
        # pages in THIS pool; one PrefixLock per occupied slot records
        # exactly which nodes its table maps. prefix_cache=False builds
        # none of it — every sharing branch below gates on _prefix.
        self._prefix = (RadixPrefixCache(page_size) if prefix_cache
                        else None)
        self._tree_locks: List[Optional[object]] = [None] * max_batch
        self._admission = admission
        self.prefix_hit_tokens = 0          # prompt tokens NOT recomputed
        self.prefix_cow_copies = 0          # shared pages copy-on-written
        self._prefix_prompt_tokens = 0      # denominator for the hit rate
        self._price_cache: Dict[int, tuple] = {}   # rid -> (key, price)
        self._cow_fn = None                 # jitted page copy (COW)
        self._tail_fn = None                # 1-token re-forward for logits
        # KV-page handoff (ISSUE 12): jitted gather/scatter for
        # serialize_pages/adopt_pages + lifetime transfer counters
        self._gather_fn = None
        self._scatter_fn = None
        self.pages_exported = 0
        self.pages_adopted = 0
        # chunked prefill (Sarathi/vLLM prefill-extend): admission claims
        # pages but prefill proceeds one chunk per scheduler tick,
        # interleaved with decode of running slots — bounds the per-tick
        # stall a long prompt inflicts on running requests' ITL. The
        # chunk is page-aligned so every chunk writes whole pages.
        self.chunked_prefill = bool(chunked_prefill)
        self.prefill_chunk = int(prefill_chunk or page_size)
        if self.prefill_chunk % page_size:
            raise ValueError(f"prefill_chunk ({self.prefill_chunk}) must "
                             f"be a multiple of page_size ({page_size})")
        self._chunk_fn = None
        # device-resident scheduler state, created at first activation:
        #   state = (logits [B,V], pos [B], active [B], budget [B], gen [B])
        #   knobs = dict(rseed, eos, temp, topk, topp, dosample)  [B] each
        self._state = None
        self._knobs = None
        self._act_fn = None
        self._deact_fn = None
        self.preemptions = 0
        # times a dry pool was answered by draining the in-flight window
        # (instead of immediately evicting) — retirements it reveals often
        # free pages without costing anyone a replay
        self.pool_dry_drains = 0
        # bounded window (run() releases _Request objects for the same
        # reason — a long-lived engine must not grow per-request state)
        self._latencies = deque(maxlen=10_000)  # (ttft_s, total_s, n_tok)
        # per-tick inter-token gaps of retired requests (incl. stalls a
        # preemption or a long peer prefill inflicted on them)
        self._itl_gaps = deque(maxlen=100_000)
        # cost observatory (ISSUE 9): attached when a decode executable is
        # built with the metrics plane on; drain timestamps give the
        # measured seconds-per-block its breakdown gauges divide
        self._cost_watch = None
        self._drain_stamps = deque(maxlen=256)
        # metrics-plane lifetime counters (plain attrs: zero cost until
        # publish_metrics mirrors them into the registry as deltas)
        self._tokens_emitted = 0
        self._requests_retired = 0
        self._published: Dict[str, float] = {}
        # gauge handles resolved ONCE (registry.reset() keeps metric
        # objects valid): the per-tick path must not pay a registry
        # name-lookup per gauge per tick
        self._g_queue = _REG.gauge("pt_serving_queue_depth",
                                   "requests waiting for a slot")
        self._g_inflight = _REG.gauge(
            "pt_serving_inflight_blocks",
            "decode blocks dispatched but not yet drained")
        self._g_active = _REG.gauge("pt_serving_active_slots",
                                    "slots holding a request")
        self._g_free = _REG.gauge("pt_serving_free_pages",
                                  "KV pool pages unclaimed")
        self._g_occupancy = _REG.gauge(
            "pt_serving_page_pool_occupancy",
            "fraction of the KV page pool claimed")
        self._g_prefix_pages = _REG.gauge(
            "pt_serving_prefix_shared_pages",
            "pool pages owned by the radix prefix cache")
        self._g_prefix_hit = _REG.gauge(
            "pt_serving_prefix_hit_rate",
            "prefix-cache hit tokens / admitted prompt tokens")

    # -- public API ---------------------------------------------------------

    def submit(self, input_ids, max_new_tokens: Optional[int] = None,
               generation_config: Optional[GenerationConfig] = None,
               rseed: Optional[int] = None,
               replay_prefix=None, trace=None) -> int:
        """Queue one request; returns its id.

        ``rseed`` overrides the sampling-stream identity folded into the
        per-token keys (default: this engine's rid). A router spreading
        one logical request stream across replicas — or re-admitting it
        on a survivor after a replica death — passes the ORIGINAL
        identity so sampled tokens are engine-independent.

        ``replay_prefix`` seeds the request with tokens ALREADY emitted
        by a previous incarnation (a failed replica): the engine treats
        it exactly like its own recompute-preemption replay — the prefix
        is re-prefilled (or prefix-cache mapped), generation resumes at
        token index ``len(replay_prefix)`` with the remaining budget,
        and the replay-exact keys make the continuation token-identical
        to the uninterrupted stream.

        ``generation_config`` overrides the engine's sampling knobs
        (do_sample/temperature/top_k/top_p) and eos_token_id for THIS
        request only; the token budget comes from the ``max_new_tokens``
        PARAMETER (falling back to the engine default) — gc's own
        max_new_tokens is deliberately ignored, since a caller passing a
        config just to enable sampling would otherwise silently get the
        dataclass default budget of 32. Knobs are per-slot arrays inside
        the one compiled decode block (sample_logits_per_slot), so any
        mix of greedy and sampled requests batches together with no
        recompilation — the TPU analogue of the reference's per-row
        top_p_sampling_kernel.cu."""
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        gc = generation_config or self.cfg
        new = (max_new_tokens if max_new_tokens is not None
               else self.cfg.max_new_tokens)
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {new}")
        if len(ids) + new > self.max_len:
            raise ValueError(f"prompt {len(ids)} + max_new {new} exceeds "
                             f"engine max_len {self.max_len}")
        replay = ([] if replay_prefix is None
                  else [int(t) for t in np.asarray(replay_prefix,
                                                   np.int32).reshape(-1)])
        if len(replay) >= new:
            raise ValueError(f"replay_prefix ({len(replay)} tokens) "
                             f"exhausts max_new_tokens ({new})")
        # the replay prefix re-prefills WITH the prompt, so it counts
        # against the pool here — otherwise a router failover re-submit
        # passes validation and _admit raises mid-step, which would
        # crash the whole fabric instead of failing one request
        if -(-(len(ids) + len(replay)) // self.page_size) \
                > self._total_pages:
            raise ValueError(f"prompt needs more pages than the pool "
                             f"holds ({self._total_pages}); raise "
                             f"num_pages")
        req = _Request(next(self._rid), ids, new,
                       temperature=float(gc.temperature),
                       top_k=int(gc.top_k), top_p=float(gc.top_p),
                       do_sample=bool(gc.do_sample),
                       eos_token_id=gc.eos_token_id,
                       rseed=None if rseed is None else int(rseed))
        req.generated = replay
        req.submit_t = time.perf_counter()
        if trace is not None:
            # ``trace`` is the wire TraceContext dict the fabric carried
            # over the transport; spans minted here stitch under it
            tr = self._tracer or _TRACE
            if tr.enabled:
                sp = tr.start("replica::queue", parent=trace,
                              tags={"rid": req.rid,
                                    "engine": self.name})
                if sp is not None:
                    req.tspans = {"tr": tr, "parent": trace,
                                  "queue": sp}
        self._requests[req.rid] = req
        self._queue.append(req)
        return req.rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def step(self) -> List[tuple]:
        """One scheduler tick: reconcile drained blocks, admit what fits,
        advance at most one prefill chunk (chunked mode), dispatch the
        next decode block. Returns [(rid, token), ...] whose results
        ARRIVED this tick — with ``async_depth > 1`` a token is emitted
        the tick its block drains, one block behind its dispatch."""
        emitted: List[tuple] = []
        with RecordEvent("serving::admit"):
            self._admit()
        if self.chunked_prefill:
            self._prefill_tick()
        dispatched = self._dispatch_block(emitted)
        if not dispatched and self._inflight:
            # nothing new to dispatch: force progress on the oldest block
            emitted.extend(self._reconcile_one())
        # bounded window: block on the oldest until at most depth-1 remain
        while len(self._inflight) > self.async_depth - 1:
            emitted.extend(self._reconcile_one())
        # opportunistic: drain blocks whose results already landed
        while self._inflight and self._block_ready(self._inflight[0]):
            emitted.extend(self._reconcile_one())
        if _REG.enabled:
            self._tick_gauges()
            # SLO sentry (ISSUE 10): drain boundary — the gauges above
            # are fresh. A default-constructed sentry evaluates EVERY
            # tick (a full registry snapshot); production installs on a
            # busy engine should set min_interval_s (README shows 1.0).
            # Uninstalled is a load + branch.
            _sentry.maybe_tick()
        return emitted

    def run(self) -> Dict[int, np.ndarray]:
        """Drive until all submitted requests complete; returns
        {rid: np.ndarray of generated tokens} for the requests finished by
        this call and RELEASES them (a long-lived engine must not retain
        every request it ever served)."""
        # run() is a burst boundary: drop drain stamps from earlier runs
        # so the cost observatory's seconds-per-block never averages in
        # inter-run idle gaps (the median filter alone loses once idle
        # gaps outnumber genuine ones under short bursty runs)
        self._drain_stamps.clear()
        while self.has_work():
            self.step()
        # leftover speculative blocks are fully masked on device (every
        # participant already stopped); reconcile them so allocator and
        # position mirrors stay exact for the next run
        while self._inflight:
            self._reconcile_one()
        out = {rid: np.asarray(r.generated, np.int32)
               for rid, r in self._requests.items() if r.done}
        for rid in out:
            del self._requests[rid]
        if _REG.enabled:
            self.publish_metrics()
            # run() completion republished the percentile gauges — the
            # drain boundary an ITL/TTFT ceiling rule should see
            _sentry.maybe_tick()
        return out

    def stats(self) -> Dict[str, int]:
        out = {"free_pages": len(self._free),
               "active": sum(s is not None for s in self._slots),
               "queued": len(self._queue),
               "preemptions": self.preemptions,
               "inflight": len(self._inflight),
               "attn_dense_ticks": self.attn_path_ticks["dense"],
               "attn_paged_ticks": self.attn_path_ticks["paged"]}
        if self.spec_k:
            out["spec_tokens_proposed"] = self.spec_tokens_proposed
            out["spec_tokens_accepted"] = self.spec_tokens_accepted
        if self._prefix is not None:
            out["prefix_hit_tokens"] = self.prefix_hit_tokens
            out["prefix_cow_copies"] = self.prefix_cow_copies
            out["prefix_shared_pages"] = self._prefix.num_pages
        return out

    def prefix_stats(self) -> Dict[str, float]:
        """Prefix-cache effectiveness over the engine's lifetime: hit
        tokens (prompt tokens served from shared pages instead of being
        re-prefilled), hit rate against all admitted prompt tokens,
        copy-on-write count and current tree size. Empty when
        ``prefix_cache=False``."""
        if self._prefix is None:
            return {}
        out = {"prefix_hit_tokens": float(self.prefix_hit_tokens),
               "prefix_prompt_tokens": float(self._prefix_prompt_tokens),
               "prefix_cow_copies": float(self.prefix_cow_copies),
               "prefix_shared_pages": float(self._prefix.num_pages),
               "prefix_nodes": float(self._prefix.num_nodes())}
        if self._prefix_prompt_tokens:
            out["prefix_hit_rate"] = (self.prefix_hit_tokens
                                      / self._prefix_prompt_tokens)
        return out

    def spec_stats(self) -> Dict[str, float]:
        """Speculation effectiveness over the engine's lifetime:
        acceptance rate (accepted ÷ proposed drafts) and mean committed
        tokens per committing drain (1.0 = no speculation win,
        spec_k+1 = every draft accepted). Empty when ``spec_k == 0``."""
        if not self.spec_k:
            return {}
        out = {"spec_k": float(self.spec_k),
               "spec_tokens_proposed": float(self.spec_tokens_proposed),
               "spec_tokens_accepted": float(self.spec_tokens_accepted)}
        if self.spec_tokens_proposed:
            out["spec_accept_rate"] = (self.spec_tokens_accepted
                                       / self.spec_tokens_proposed)
        if self._spec_drains:
            out["spec_mean_accepted_len"] = 1.0 + (
                self.spec_tokens_accepted / self._spec_drains)
        return out

    def take_finished(self) -> Dict[int, np.ndarray]:
        """Finished requests' full token streams (replay prefix
        included), RELEASING them — the incremental analogue of
        ``run()``'s collection for callers (a fabric replica) that drive
        ``step()`` themselves and must observe completions between
        ticks."""
        out = {rid: np.asarray(r.generated, np.int32)
               for rid, r in self._requests.items() if r.done}
        for rid in out:
            del self._requests[rid]
        return out

    def cancel(self, rid: int) -> bool:
        """Terminate ``rid`` NOW and free its slot/pages (the front
        door's slow-client / deadline / client-cancel path). A queued
        request is simply removed; an active one drains the in-flight
        blocks first (the preemption discipline — freed pages must not
        be re-claimed while a dispatched block still writes them), then
        the slot releases through the one ``_free_slot`` path with
        ``cache=True``: a cancelled conversation's completed pages are
        still future prefix hits. Returns True when the request existed
        and had not already finished (a finished request stays for
        ``take_finished`` — cancel does not eat a delivered result)."""
        req = self._requests.get(rid)
        if req is None or req.done:
            return False
        try:
            self._queue.remove(req)
        except ValueError:
            pass
        slot = next((i for i, s in enumerate(self._slots) if s is req),
                    -1)
        if slot >= 0:
            # tokens other slots commit in this drain are NOT lost: they
            # land in their requests' .generated and the full stream
            # ships with each finish — only this tick's incremental
            # emission view is bypassed
            self._drain_all()
            if not req.done and self._slots[slot] is req:
                self._deactivate(slot)
                self._free_slot(slot, cache=True)
        if req.done:
            return False
        if req.tspans is not None:
            for k in ("queue", "res"):
                sp = req.tspans.pop(k, None)
                if sp is not None:
                    sp.tag(outcome="cancelled").end()
        self._requests.pop(rid, None)
        self._price_cache.pop(rid, None)
        return True

    # -- KV-page handoff (serving-fabric disaggregation, ISSUE 12) -----------

    @staticmethod
    def _handoff_bucket(n: int) -> int:
        """Next power of two ≥ n: the gather/scatter executable count
        stays O(log max pages) instead of one per distinct page
        count."""
        b = 1
        while b < n:
            b *= 2
        return b

    def serialize_pages(self, tokens) -> Optional[Dict[str, object]]:
        """Export the KV pages the radix tree holds for the longest
        page-aligned prefix of ``tokens``: page contents (every layer's
        K and V, gathered in one jitted dispatch), the covered token
        run, and a sha256 over both — the prefill→decode handoff unit.
        Returns None when the tree covers no full page of ``tokens``.

        The payload is self-describing (``shape``/``dtype``/``sha256``)
        so :meth:`adopt_pages` can validate it END-TO-END before
        touching its own pool; the wire codec (base64 over TCP) lives in
        ``serving_fabric.transport``, this dict is the in-process
        form."""
        if self._prefix is None:
            raise RuntimeError("serialize_pages needs prefix_cache=True "
                               "(the radix tree owns the exportable "
                               "pages)")
        toks = np.asarray(tokens, np.int32).reshape(-1)
        ids = self._prefix.match_page_ids(toks)
        if not ids:
            return None
        toks = toks[:len(ids) * self.page_size]
        if self._gather_fn is None:
            def run(pools, pids):
                kv = jnp.stack(
                    [jnp.stack([e[0][:, pids], e[1][:, pids]], axis=0)
                     for e in pools], axis=0)
                if self.kv_quant:        # [L, 2, n] per-page scales
                    sc = jnp.stack(
                        [jnp.stack([e[2][pids], e[3][pids]], axis=0)
                         for e in pools], axis=0)
                    return kv, sc
                return kv, None
            self._gather_fn = jax.jit(run)
        # page count padded to a power-of-two bucket (extra rows read
        # the garbage page, sliced off below): the jit retraces per
        # page-count SHAPE, and unbucketed counts would pay a fresh
        # compile per distinct prompt length on the serving path
        b = self._handoff_bucket(len(ids))
        kv, scales = self._gather_fn(
            self.pools,
            jnp.asarray(ids + [0] * (b - len(ids)), jnp.int32))
        kv = np.ascontiguousarray(np.asarray(kv)[:, :, :, :len(ids)])
        self.pages_exported += len(ids)
        payload = {"fmt": HANDOFF_FMT, "page_size": self.page_size,
                   "tokens": toks, "kv": kv, "dtype": str(kv.dtype),
                   "shape": list(kv.shape)}
        blob = toks.tobytes() + kv.tobytes()
        if scales is not None:
            sc = np.ascontiguousarray(
                np.asarray(scales, np.float32)[:, :, :len(ids)])
            payload["scales"] = sc
            payload["scales_shape"] = list(sc.shape)
            blob += sc.tobytes()
        payload["sha256"] = hashlib.sha256(blob).hexdigest()
        return payload

    def adopt_pages(self, payload) -> List[int]:
        """Adopt a :meth:`serialize_pages` payload into THIS engine's
        pool + radix tree: pages land in freshly allocated pool slots
        (under pressure the allocator's existing tree eviction makes
        room) and the token run is inserted at refcount 0 — cached, so
        the NEXT admission of a matching prompt prefix-hits, which is
        how a prefill→decode transfer seeds future sharing. Returns the
        page ids that became tree-owned ([] when the tree already
        covered the whole run).

        Validation is strictly first: a corrupt, truncated or
        mis-shaped payload raises ValueError before anything mutates."""
        if self._prefix is None:
            raise RuntimeError("adopt_pages needs prefix_cache=True")
        fmt = payload.get("fmt") if isinstance(payload, dict) else None
        if fmt not in (HANDOFF_FMT, HANDOFF_FMT_V1):
            raise ValueError("handoff payload: unknown format")
        if fmt == HANDOFF_FMT_V1 and self.kv_quant:
            # a v1 emitter has float pages and no scales — nothing to
            # dequant by; the fabric treats this like any failed handoff
            # and falls back to a cold prefill
            raise ValueError("handoff payload: v1 (scale-less) payload "
                             "cannot seed an int8 KV pool")
        if int(payload.get("page_size", -1)) != self.page_size:
            raise ValueError(
                f"handoff payload: page_size {payload.get('page_size')} "
                f"!= engine page_size {self.page_size}")
        toks = np.asarray(payload.get("tokens"), np.int32).reshape(-1)
        kv = payload.get("kv")
        ps = self.page_size
        if len(toks) == 0 or len(toks) % ps:
            raise ValueError("handoff payload: token run is not a "
                             "whole-page multiple")
        n = len(toks) // ps
        kp0 = self.pools[0][0]
        want = (len(self.pools), 2, kp0.shape[0], n, ps, kp0.shape[3])
        if not isinstance(kv, np.ndarray) or kv.shape != want \
                or list(kv.shape) != list(payload.get("shape", [])):
            raise ValueError(
                f"handoff payload: kv shape "
                f"{getattr(kv, 'shape', None)} != expected {want}")
        if str(kv.dtype) != payload.get("dtype") \
                or str(kv.dtype) != str(kp0.dtype):
            raise ValueError(
                f"handoff payload: dtype {payload.get('dtype')} != pool "
                f"dtype {kp0.dtype}")
        scales = payload.get("scales")
        blob = toks.tobytes() + kv.tobytes()
        if self.kv_quant:
            sc_want = (len(self.pools), 2, n)
            if not isinstance(scales, np.ndarray) \
                    or scales.shape != sc_want \
                    or str(scales.dtype) != "float32" \
                    or list(scales.shape) != list(
                        payload.get("scales_shape", [])):
                raise ValueError(
                    f"handoff payload: scales shape "
                    f"{getattr(scales, 'shape', None)} != expected "
                    f"{sc_want} (int8 pool needs per-page fp32 scales)")
            blob += scales.tobytes()
        elif scales is not None:
            raise ValueError("handoff payload: scales present but this "
                             "engine's KV pool is not quantized")
        digest = hashlib.sha256(blob).hexdigest()
        if digest != payload.get("sha256"):
            raise ValueError("handoff payload: checksum mismatch "
                             "(corrupt or truncated transfer)")
        # -- validated; now (and only now) touch the pool. Only the
        # UNCOVERED whole-page suffix is staged: pages the tree already
        # serves would be scattered and immediately freed — and worse,
        # allocating them under pressure could evict the very cached
        # prefixes the transfer exists to seed.
        k = min(self._prefix.match(toks, touch=False) // ps, n)
        if k >= n:
            return []                   # tree already covers the run
        pages = self._alloc_pages(n - k, protect=toks)
        if pages is None:
            raise RuntimeError(
                f"adopt_pages: pool cannot hold {n - k} more pages "
                f"even after tree eviction; raise num_pages")
        if self._scatter_fn is None:
            def run(pools, pids, data, sc):
                out = []
                for i, e in enumerate(pools):
                    ne = (e[0].at[:, pids].set(data[i, 0]),
                          e[1].at[:, pids].set(data[i, 1]))
                    if sc is not None:
                        ne += (e[2].at[pids].set(sc[i, 0]),
                               e[3].at[pids].set(sc[i, 1]))
                    out.append(ne)
                return out
            self._scatter_fn = jax.jit(run, donate_argnums=(0,))
        # same power-of-two bucketing as the gather: padded rows write
        # the garbage page (reserved junk — the designated sink)
        b = self._handoff_bucket(n - k)
        kv_pad = np.zeros(kv.shape[:3] + (b,) + kv.shape[4:], kv.dtype)
        kv_pad[:, :, :, :n - k] = kv[:, :, :, k:]
        sc_pad = None
        if self.kv_quant:
            sc_pad = np.zeros(scales.shape[:2] + (b,), np.float32)
            sc_pad[:, :, :n - k] = scales[:, :, k:]
            sc_pad = jnp.asarray(sc_pad)
        self.pools = self._scatter_fn(
            self.pools,
            jnp.asarray(list(pages) + [0] * (b - (n - k)), jnp.int32),
            jnp.asarray(kv_pad), sc_pad)
        # insert walks the FULL run; the covered prefix needs page-id
        # placeholders that are never read (insert only consumes ids
        # from the first uncovered boundary on — and a coverage that
        # ends mid-page donates nothing at all, freeing the stage)
        donated = self._prefix.insert(toks, [0] * k + pages, lock=None)
        assert all(p in set(pages) for p in donated), \
            "placeholder page id donated to the tree"
        taken = set(donated)
        self._free.extend(p for p in pages if p not in taken)
        self.pages_adopted += len(donated)
        return donated

    # -- metrics plane -------------------------------------------------------

    def _tick_gauges(self) -> None:
        """Per-tick point-in-time view (cheap: five cached-handle gauge
        sets, and only ever reached when the registry is enabled)."""
        lb = self._mlabels
        self._g_queue.set(len(self._queue), **lb)
        self._g_inflight.set(len(self._inflight), **lb)
        self._g_active.set(sum(s is not None for s in self._slots), **lb)
        self._g_free.set(len(self._free), **lb)
        self._g_occupancy.set(
            1.0 - len(self._free) / max(self._total_pages, 1), **lb)
        if self._prefix is not None:
            self._g_prefix_pages.set(self._prefix.num_pages, **lb)

    def _decode_args(self, spec_mode: bool) -> tuple:
        """The decode tick's argument tuple — ONE definition shared by
        the dispatch call and the cost observatory's eager lower, so a
        signature change can't leave the two silently diverged."""
        args = (self._params, self.pools, self._tables_dev,
                self._base_key, self._state, self._knobs)
        return args + (self._hist,) if spec_mode else args

    def _maybe_compile_with_costs(self, jfn, spec_mode: bool):
        """Resolve a freshly built decode tick for dispatch. With the
        metrics plane OFF this returns the jitted fn untouched (it
        compiles lazily at first call, exactly the old behavior). With
        the plane ON it pays the same one trace+compile EAGERLY —
        ``lower().compile()`` on the concrete args of this dispatch — so
        the cost observatory can attribute flops/bytes from the
        optimized HLO of the executable that will actually run. Any
        failure falls back to the jitted fn."""
        if not _REG.enabled:
            return jfn
        try:
            compiled = jfn.lower(*self._decode_args(spec_mode)).compile()
        except Exception:
            return jfn
        try:
            from ..observability.costs import CostWatch
            if self._cost_watch is None:
                self._cost_watch = CostWatch("serving")
            self._cost_watch.observe_executable(compiled)
        except Exception:
            pass
        return compiled

    def _publish_cost_metrics(self) -> None:
        """Breakdown/MFU gauges for the serving tick: measured seconds
        per decode block from drain-to-drain gaps (median-filtered so
        idle gaps between runs don't pollute the estimate), attributed
        against the analyzed tick executable."""
        watch = self._cost_watch
        if watch is None or not watch.attached:
            return
        stamps = list(self._drain_stamps)
        gaps = [b - a for a, b in zip(stamps, stamps[1:])]
        if not gaps:
            return
        gaps.sort()
        med = gaps[len(gaps) // 2]
        kept = [g for g in gaps if g <= 10 * med] or [med]
        watch.publish(sum(kept) / len(kept))

    def publish_metrics(self) -> Dict[str, float]:
        """Mirror the engine's telemetry into the process metrics registry
        — the counters/percentiles ``stats()``/``latency_stats()`` used to
        be the only window onto. Lifetime counters publish as DELTAS since
        the previous publish, so registry counters stay monotonic across
        repeated calls; called automatically at ``run()`` completion and
        safe to call any time. Returns ``latency_stats()`` for
        convenience."""
        lat = self.latency_stats()
        if not _REG.enabled:
            return lat
        lb = self._mlabels
        for name, val, help in (
                ("pt_serving_preemptions_total", self.preemptions,
                 "recompute-policy slot evictions"),
                ("pt_serving_pool_dry_drains_total", self.pool_dry_drains,
                 "dry pools answered by draining the in-flight window"),
                ("pt_serving_tokens_total", self._tokens_emitted,
                 "tokens emitted to clients"),
                ("pt_serving_requests_total", self._requests_retired,
                 "requests retired"),
                ("pt_spec_tokens_proposed_total",
                 self.spec_tokens_proposed,
                 "draft tokens scored by speculative verify passes"),
                ("pt_spec_tokens_accepted_total",
                 self.spec_tokens_accepted,
                 "draft tokens committed by speculative verify passes"),
                ("pt_serving_prefix_hit_tokens_total",
                 self.prefix_hit_tokens,
                 "prompt tokens served from shared prefix pages"),
                ("pt_serving_cow_copies_total", self.prefix_cow_copies,
                 "shared pages copy-on-written at divergence"),
                ("pt_serving_kv_quant_ticks_total", self.kv_quant_ticks,
                 "decode/verify ticks served from an int8 KV pool")):
            prev = self._published.get(name, 0)
            if val > prev:
                _REG.counter(name, help).inc(val - prev, **lb)
            self._published[name] = val
        sp = self.spec_stats()
        if "spec_accept_rate" in sp:
            _REG.gauge("pt_spec_accept_rate",
                       "accepted / proposed speculative drafts").set(
                sp["spec_accept_rate"], **lb)
        if "spec_mean_accepted_len" in sp:
            _REG.gauge("pt_spec_mean_accepted_len",
                       "mean committed tokens per speculative drain").set(
                sp["spec_mean_accepted_len"], **lb)
        if self._prefix is not None and self._prefix_prompt_tokens:
            self._g_prefix_hit.set(self.prefix_hit_tokens
                                   / self._prefix_prompt_tokens, **lb)
        _REG.gauge("pt_serving_kv_quant_enabled",
                   "1 when the KV page pool is int8 with per-page "
                   "scales").set(float(self.kv_quant), **lb)
        if self.kv_quant:
            _REG.gauge("pt_serving_kv_quant_pool_bytes",
                       "HBM bytes held by the int8 KV pool incl. scale "
                       "arrays", "By").set(float(sum(
                           a.size * a.dtype.itemsize
                           for e in self.pools for a in e)), **lb)
        for key, metric in (("ttft", "pt_serving_ttft_seconds"),
                            ("latency", "pt_serving_latency_seconds"),
                            ("itl", "pt_serving_itl_seconds")):
            for q in ("p50", "p99"):
                v = lat.get(f"{key}_{q}_s")
                g = _REG.gauge(metric, f"{key} percentile over the "
                                       f"retired-request window", "s")
                if v is not None:
                    g.set(v, q=q, **lb)
                else:
                    # empty/reset window: CLEAR rather than leave the
                    # previous publish reading as current — an absent
                    # percentile is honest (and what the sentry's
                    # Staleness rule exists to notice), a stale one lies
                    g.clear(q=q, **lb)
        _REG.gauge("pt_serving_window_requests",
                   "retired requests in the latency window").set(
            lat.get("requests", 0), **lb)
        self._publish_cost_metrics()
        self._tick_gauges()
        return lat

    # -- page allocator -----------------------------------------------------

    def _alloc_pages(self, n: int,
                     protect=None) -> Optional[List[int]]:
        """Pop ``n`` pages; under pressure, refcount-0 prefix-tree pages
        are LRU-evicted back into the free list first (``protect`` pins
        the match path of the request being admitted so admission can't
        evict the prefix it is about to map). Only once the tree has
        nothing evictable does the caller see None — the dry-pool
        drain/preemption machinery downstream is unchanged."""
        if len(self._free) < n and self._prefix is not None:
            self._free.extend(
                self._prefix.evict(n - len(self._free), protect))
        if len(self._free) < n:
            return None
        return [self._free.pop() for _ in range(n)]

    def _free_slot(self, slot: int, cache: bool = False):
        req = self._slots[slot]
        # free every held page (page 0 == unset): counting from pos would
        # leak a boundary page granted earlier in the same scheduling pass
        if self._prefix is not None:
            if cache and req is not None and self._decode_ready(req):
                # donate completed full pages before the lock releases:
                # retirement caches the whole conversation, preemption
                # caches the replay's own prefix (the re-prefill hits)
                self._insert_prefix(slot, req)
            lock = self._tree_locks[slot]
            if lock is not None:
                # released exactly ONCE, whether the slot retired,
                # was preempted mid-decode, or was evicted mid-prefill
                # before ever activating — a mid-prefill slot's table
                # holds admission-claimed private pages PLUS the mapped
                # shared prefix, and only the former go back to the
                # free list (the tree still owns the latter)
                self._prefix.release(lock)
                self._tree_locks[slot] = None
            self._free.extend(int(p) for p in self.tables[slot]
                              if p != 0 and not self._prefix.owns(int(p)))
        else:
            self._free.extend(int(p) for p in self.tables[slot] if p != 0)
        self.tables[slot] = 0
        self._tables_dirty = True
        self.pos[slot] = 0
        self._proj_pos[slot] = 0
        self._proj_gen[slot] = 0
        self._slots[slot] = None
        if req is not None:
            req.slot = -1
            req.prefilled = 0     # freed pages took the written KV along
            if req.tspans is not None:
                rsp = req.tspans.pop("res", None)
                if rsp is not None:
                    rsp.tag(reason="done" if req.done else "preempt",
                            n=len(req.generated)).end()

    # -- device-resident scheduler state ------------------------------------

    def _init_state(self, logits_row):
        B = self.max_batch
        vocab = logits_row.shape[-1]
        self._state = (jnp.zeros((B, vocab), logits_row.dtype),
                       jnp.zeros((B,), jnp.int32),
                       jnp.zeros((B,), bool),
                       jnp.zeros((B,), jnp.int32),
                       jnp.zeros((B,), jnp.int32))
        self._knobs = {"rseed": jnp.zeros((B,), jnp.uint32),
                       "eos": jnp.full((B,), -1, jnp.int32),
                       "temp": jnp.ones((B,), jnp.float32),
                       "topk": jnp.zeros((B,), jnp.int32),
                       "topp": jnp.ones((B,), jnp.float32),
                       "dosample": jnp.zeros((B,), bool)}

    def _build_act_fn(self):
        def run(state, knobs, slot, logits_row, pos0, budget0, gen0,
                rseed0, eos0, temp0, topk0, topp0, dos0):
            logits, pos, active, budget, gen = state
            state = (logits.at[slot].set(logits_row.astype(logits.dtype)),
                     pos.at[slot].set(pos0),
                     active.at[slot].set(True),
                     budget.at[slot].set(budget0),
                     gen.at[slot].set(gen0))
            knobs = {"rseed": knobs["rseed"].at[slot].set(rseed0),
                     "eos": knobs["eos"].at[slot].set(eos0),
                     "temp": knobs["temp"].at[slot].set(temp0),
                     "topk": knobs["topk"].at[slot].set(topk0),
                     "topp": knobs["topp"].at[slot].set(topp0),
                     "dosample": knobs["dosample"].at[slot].set(dos0)}
            return state, knobs

        # no donation: in-flight blocks hold references to prior state
        # arrays for their async host drains
        return jax.jit(run)

    def _activate(self, slot: int, req: _Request, logits_row):
        """Flip a slot live on device after its prefill finished: one
        small jitted dispatch setting the slot's row in every scheduler
        array (pos/active/budget/gen/knobs) + its first-token logits."""
        if self._state is None:
            self._init_state(logits_row)
        if self._act_fn is None:
            self._act_fn = self._build_act_fn()
        L = req.prefill_target
        eos = req.eos_token_id if req.eos_token_id is not None \
            else self.cfg.eos_token_id
        self._state, self._knobs = self._act_fn(
            self._state, self._knobs, np.int32(slot), logits_row,
            np.int32(L), np.int32(req.max_new_tokens - len(req.generated)),
            np.int32(len(req.generated)),
            np.uint32((req.rid if req.rseed is None else req.rseed)
                      & 0x7FFFFFFF),
            np.int32(-1 if eos is None else eos),
            np.float32(req.temperature), np.int32(req.top_k),
            np.float32(req.top_p), np.bool_(req.do_sample))
        self.pos[slot] = L
        self._proj_pos[slot] = L
        self._proj_gen[slot] = len(req.generated)
        if req.tspans is not None:
            # decode-epoch anchor: the first replica::decode span for
            # this residency starts where activation finished
            req.tspans["last"] = time.time()
        self._dosample[slot] = req.do_sample
        if self.spec_k:
            # device-resident token history for the draft proposer:
            # prompt + replayed generations now, committed tokens appended
            # on device by each spec tick (the host is async_depth behind,
            # so drafting must read the carry, not host state)
            if self._hist_set_fn is None:
                self._hist_set_fn = jax.jit(
                    lambda h, slot, row: h.at[slot].set(row),
                    donate_argnums=(0,))
            row = np.zeros((self.max_len,), np.int32)
            row[:len(req.prompt)] = req.prompt
            if req.generated:
                row[len(req.prompt):L] = req.generated
            self._hist = self._hist_set_fn(self._hist, np.int32(slot), row)

    def _deactivate(self, slot: int):
        if self._state is None:
            return
        if self._deact_fn is None:
            self._deact_fn = jax.jit(
                lambda active, slot: active.at[slot].set(False))
        logits, pos, active, budget, gen = self._state
        self._state = (logits, pos, self._deact_fn(active, np.int32(slot)),
                       budget, gen)

    # -- admission / prefill ------------------------------------------------

    def _bucket(self, L: int) -> int:
        return -(-L // self.page_size) * self.page_size

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_cache.get(bucket)
        if fn is not None:
            return fn
        core, model = self.core, self.model
        head = model.logits if hasattr(model, "logits") else (lambda h: h)

        def run(params, ids, pools, tables1, last_idx):
            ctx = model._bind(params) if hasattr(model, "_bind") else None
            with ctx if ctx is not None else _null():
                hidden, pools = core.prefill_paged(ids, pools, tables1)
                logits = head(hidden[0, last_idx, :])
            return logits, pools

        fn = jax.jit(run, donate_argnums=(2,))
        self._prefill_cache[bucket] = fn
        return fn

    @staticmethod
    def _req_tokens(req: _Request) -> np.ndarray:
        """The request's replay token sequence: prompt + anything
        generated before a preemption — the ONE definition the prefix
        match, donation, admission-pricing and chunk-prefill paths all
        key on."""
        return np.concatenate([req.prompt,
                               np.asarray(req.generated, np.int32)])

    def _uncached_tokens(self, req: _Request) -> int:
        """Predicted prefill cost of admitting ``req`` now: the tokens
        its admission would actually recompute (1 for a full-prompt hit
        — just the logits re-forward). The admission policy prices
        admits with this. Prices are cached per (rid, replay length)
        against the tree's mutation epoch, so a deep deferred queue
        costs one tree walk per request per tree CHANGE, not per tick."""
        L = len(req.prompt) + len(req.generated)
        if self._prefix is None:
            return L
        key = (self._prefix.epoch, L)
        hit = self._price_cache.get(req.rid)
        if hit is not None and hit[0] == key:
            return hit[1]
        # touch=False: a pricing read must not bump the match path's LRU
        # rank — a request deferred every tick would otherwise keep its
        # prefix artificially hot and starve eviction of real traffic
        m = self._prefix.match(self._req_tokens(req), touch=False)
        if m >= L and hasattr(self.core, "decode_verify_paged"):
            price = 1
        else:
            price = L - (min(m, L - 1) // self.page_size) * self.page_size
        if len(self._price_cache) > 4 * self.max_batch + 1024:
            self._price_cache.clear()          # bound stale-rid growth
        self._price_cache[req.rid] = (key, price)
        return price

    def _insert_prefix(self, slot: int, req: _Request) -> None:
        """Donate the slot's completed full pages (prompt + committed
        generations) to the radix tree. Ranges the tree already covers
        stay the slot's private duplicates; new nodes join the slot's
        lock at ref 1 so the uniform release path owns them."""
        toks = self._req_tokens(req)
        n_ins = len(toks) // self.page_size
        if n_ins == 0:
            return
        lock = self._tree_locks[slot]
        if lock is None:
            lock = self._tree_locks[slot] = self._prefix.new_lock()
        self._prefix.insert(toks[:n_ins * self.page_size],
                            [int(p) for p in self.tables[slot, :n_ins]],
                            lock)

    def _cow_page(self, src: int, dst: int) -> None:
        """Copy page ``src`` → ``dst`` across every layer's K/V pool (one
        jitted dispatch, page ids traced): the COW primitive for decode
        diverging into a shared page."""
        if self._cow_fn is None:
            def run(pools, src, dst):
                return [_entry_page_copy(e, src, dst) for e in pools]
            self._cow_fn = jax.jit(run, donate_argnums=(0,))
        self.pools = self._cow_fn(self.pools, jnp.int32(src),
                                  jnp.int32(dst))
        self.prefix_cow_copies += 1

    def _tail_logits_fn(self):
        """The full-prompt-hit fast path's entire compute, ONE dispatch:
        copy-on-write the shared boundary page (``src`` → ``dst``, every
        layer), then re-forward the single last prompt token — its K/V
        write lands in the private copy and the returned logits row is
        what a full prefill would have produced."""
        if self._tail_fn is None:
            core, model = self.core, self.model
            head = model.logits if hasattr(model, "logits") else \
                (lambda h: h)

            def run(params, tok, pos, pools, tables1, src, dst):
                pools = [_entry_page_copy(e, src, dst) for e in pools]
                ctx = model._bind(params) if hasattr(model, "_bind") \
                    else None
                with ctx if ctx is not None else _null():
                    h, pools = core.decode_verify_paged(tok, pos, pools,
                                                        tables1)
                    logits = head(h[0, 0, :])
                return logits, pools

            self._tail_fn = jax.jit(run, donate_argnums=(3,))
        return self._tail_fn

    def _admit(self):
        lat, prices, q_snap = None, {}, None
        while self._queue:
            slot = next((i for i, s in enumerate(self._slots) if s is None),
                        None)
            if slot is None:
                return
            if self._admission is not None:
                if lat is None:
                    lat = self.latency_stats()

                # price each queued request at most once per _admit call
                # (select() re-runs per admitted slot; without the memo a
                # deep queue costs admits x queue tree walks per tick).
                # Prices can go stale within the call — an earlier
                # admit's insertion may raise a later request's hit —
                # which only costs ordering accuracy, never correctness.
                def _price(r):
                    v = prices.get(r.rid)
                    if v is None:
                        v = prices[r.rid] = self._uncached_tokens(r)
                    return v
                q_snap = list(self._queue)
                qi = self._admission.select(q_snap, _price, lat)
                if qi is None:
                    return                   # SLO defer: none this tick
                req = q_snap[qi]
            else:
                qi, req = 0, self._queue[0]
            L = len(req.prompt) + len(req.generated)
            need = -(-self._bucket(L) // self.page_size)
            toks = self._req_tokens(req)
            # prefix sharing: map every FULLY matched page; a full-prompt
            # match keeps the boundary page shared too and COWs it (the
            # last token is re-forwarded for its logits), otherwise the
            # page holding the first unmatched token is recomputed by the
            # suffix prefill. n_lock*page_size is always < L, so decode
            # positions land strictly beyond the shared region.
            n_lock, fast, m = 0, False, 0
            if self._prefix is not None:
                m = self._prefix.match(toks)
                fast = (m >= L
                        and hasattr(self.core, "decode_verify_paged"))
                n_lock = (L - 1) // self.page_size if m >= L \
                    else m // self.page_size
            pages = self._alloc_pages(need - n_lock,
                                      protect=toks if m else None)
            if pages is None:
                if any(s is not None for s in self._slots):
                    return                   # wait for pages to free up
                if m:
                    # nothing running, and the free pool + evictable
                    # tree can't cover the private remainder because
                    # the protected match path holds the pages: admit
                    # COLD instead (evict everything, full prefill)
                    n_lock, fast = 0, False
                    pages = self._alloc_pages(need)
                if pages is None:
                    # nothing running that could ever free pages: a
                    # replay grew past the pool (the submit-time check
                    # covers only the original prompt)
                    raise RuntimeError(
                        f"request {req.rid} needs {need} pages but the "
                        f"pool holds {self._total_pages}; raise num_pages")
            if self._admission is not None:
                # pages really claimed: NOW the passed-over requests are
                # charged a starvation skip (a pool-blocked tick above
                # returned without charging anyone)
                self._admission.note_admitted(q_snap, qi)
                del self._queue[qi]
            else:
                self._queue.popleft()
            if self._prefix is not None:
                lock = (self._prefix.lock_prefix(toks, n_lock) if n_lock
                        else self._prefix.new_lock())
                self._tree_locks[slot] = lock
                self.tables[slot, :n_lock] = lock.pages()
                self._prefix_prompt_tokens += L
                self.prefix_hit_tokens += (L - 1) if fast \
                    else n_lock * self.page_size
            self.tables[slot, n_lock:n_lock + len(pages)] = pages
            self._tables_dirty = True
            self._slots[slot] = req
            req.slot = slot
            if req.tspans is not None:
                ts = req.tspans
                q = ts.pop("queue", None)
                if q is not None:     # absent on preemption re-admits
                    q.tag(outcome="admitted", slot=slot).end()
                rsp = ts["tr"].start("replica::resident",
                                     parent=ts["parent"],
                                     tags={"slot": slot})
                if rsp is not None:
                    ts["res"] = rsp
                ts["last"] = time.time()
            self._dosample[slot] = req.do_sample
            req.prefill_target = L
            if fast:
                # COW the shared page holding token L-1 (positions >= L-1
                # in the copy are ours to overwrite; positions < L-1 in
                # it matched, so their KV is exactly what we'd compute),
                # then re-forward ONLY that token for the logits row.
                src = self._prefix.page_at(toks, n_lock)
                assert src is not None, "matched tail page vanished"
                self.prefix_cow_copies += 1
                psp = self._prefill_span(req, "cow")
                with RecordEvent("serving::prefill"):
                    logits, self.pools = self._tail_logits_fn()(
                        self._params,
                        jnp.asarray(toks[L - 1:L].reshape(1, 1)),
                        jnp.full((1,), L - 1, jnp.int32), self.pools,
                        jnp.asarray(self.tables[slot:slot + 1]),
                        jnp.int32(src), jnp.int32(pages[0]))
                if psp is not None:
                    psp.end()
                req.prefilled = L
                self._activate(slot, req, logits)
                self._insert_prefix(slot, req)
                continue
            if self.chunked_prefill:
                # pages claimed now; KV written one chunk per tick,
                # starting AFTER the shared prefix (page-aligned offset)
                req.prefilled = n_lock * self.page_size
                self.pos[slot] = 0
                self._proj_pos[slot] = 0
                self._proj_gen[slot] = 0
                continue
            bucket = self._bucket(L)
            off = n_lock * self.page_size
            req.prefilled = L
            psp = self._prefill_span(req, "suffix" if off else "full")
            with RecordEvent("serving::prefill"):
                if off:
                    # suffix-only prefill from the page-aligned offset:
                    # the existing chunked-prefill extend attends over
                    # the mapped shared history plus itself. The ids
                    # width (bucket - off) is a page multiple, so the
                    # executable set this jit retraces over is bounded
                    # by pages_per_seq — the same bound the per-bucket
                    # cold-prefill cache already lives with.
                    ids = np.zeros((1, bucket - off), np.int32)
                    ids[0, :L - off] = toks[off:]
                    if self._chunk_fn is None:
                        self._chunk_fn = self._build_chunk_fn()
                    logits, self.pools = self._chunk_fn(
                        self._params, jnp.asarray(ids), jnp.int32(off),
                        self.pools,
                        jnp.asarray(self.tables[slot:slot + 1]),
                        jnp.int32(L - 1))
                else:
                    ids = np.zeros((1, bucket), np.int32)
                    ids[0, :L] = toks
                    logits, self.pools = self._prefill_fn(bucket)(
                        self._params, jnp.asarray(ids), self.pools,
                        jnp.asarray(self.tables[slot:slot + 1]),
                        jnp.int32(L - 1))
            if psp is not None:
                psp.end()
            self._activate(slot, req, logits)
            if self._prefix is not None:
                self._insert_prefix(slot, req)

    @staticmethod
    def _prefill_span(req: _Request, kind: str):
        """Open a replica::prefill span under ``req``'s resident span
        (None when untraced — callers guard the matching end)."""
        ts = req.tspans
        if ts is None:
            return None
        parent = ts.get("res") or ts["parent"]
        return ts["tr"].start("replica::prefill", parent=parent,
                              tags={"kind": kind})

    def _decode_ready(self, req) -> bool:
        return req is not None and req.prefilled >= req.prefill_target

    def _build_chunk_fn(self):
        core, model = self.core, self.model
        head = model.logits if hasattr(model, "logits") else (lambda h: h)

        def run(params, ids, offset, pools, tables1, last_idx):
            ctx = model._bind(params) if hasattr(model, "_bind") else None
            with ctx if ctx is not None else _null():
                hidden, pools = core.prefill_chunk_paged(
                    ids, offset, pools, tables1)
                # logits at the prompt's true last index — meaningful on
                # the FINAL chunk only (a single-row head matmul, cheap
                # to compute unconditionally)
                logits = head(hidden[0, last_idx - offset, :])
            return logits, pools

        return jax.jit(run, donate_argnums=(3,))

    def _prefill_tick(self):
        """Advance the oldest in-prefill slot by ONE chunk."""
        cand = [(self._slots[s].rid, s) for s in range(self.max_batch)
                if self._slots[s] is not None
                and not self._decode_ready(self._slots[s])]
        if not cand:
            return
        slot = min(cand)[1]
        req = self._slots[slot]
        C = self.prefill_chunk
        off = req.prefilled
        toks = self._req_tokens(req)
        ids = np.zeros((1, C), np.int32)
        chunk = toks[off:off + C]
        ids[0, :len(chunk)] = chunk
        if self._chunk_fn is None:
            self._chunk_fn = self._build_chunk_fn()
        last_idx = req.prefill_target - 1
        psp = self._prefill_span(req, "chunk")
        with RecordEvent("serving::prefill"):
            logits, self.pools = self._chunk_fn(
                self._params, jnp.asarray(ids), jnp.int32(off), self.pools,
                jnp.asarray(self.tables[slot:slot + 1]),
                jnp.int32(min(last_idx, off + C - 1)))
        if psp is not None:
            psp.tag(off=off).end()
        req.prefilled = min(off + C, self._bucket(req.prefill_target))
        if req.prefilled >= req.prefill_target:
            self._activate(slot, req, logits)
            if self._prefix is not None:
                self._insert_prefix(slot, req)

    # -- decode -------------------------------------------------------------

    def _build_decode(self, K: int, any_sample: bool, attn_impl: str):
        """K sample+decode steps chained in one compiled lax.scan: one
        dispatch + one async [K, B] token readback per scheduler tick.
        The scan body samples with per-slot knob arrays, then runs the
        ON-DEVICE stop update: a slot that emits its eos or exhausts its
        budget deactivates for the REST of the scan (and for any
        speculatively dispatched later block — the carry's active mask is
        the block-to-block state), its tokens masked to pad and its K/V
        routed to the garbage page via the per-step table mask.
        ``any_sample=False`` compiles the argmax-only body (no full-vocab
        sorts in the scan) — the all-greedy common case keeps its old
        cost; the flag is host state, so at most two executables per K.
        ``attn_impl`` ('dense'|'paged') is baked in at TRACE time via
        force_decode_impl — the context-aware dispatch choice."""
        core, model = self.core, self.model
        head = model.logits if hasattr(model, "logits") else (lambda h: h)
        from ..ops.pallas.paged_attention import force_decode_impl

        def run(params, pools, tables, base_key, state, knobs):
            ctx = model._bind(params) if hasattr(model, "_bind") else None
            with ctx if ctx is not None else _null(), \
                    force_decode_impl(attn_impl):
                def body(carry, _):
                    logits, pos, active, budget, gen = carry[0]
                    pools = carry[1]
                    lf = logits.astype(jnp.float32)
                    if any_sample:
                        # key = f(seed, request, token index): sampled
                        # streams are schedule- and replay-independent
                        keys = fold_sampling_keys(base_key,
                                                  knobs["rseed"], gen)
                        tok = sample_logits_per_slot(
                            lf, knobs["temp"], knobs["topk"],
                            knobs["topp"], knobs["dosample"], keys)
                    else:
                        tok = jnp.argmax(lf, axis=-1)
                    tok = jnp.where(active, tok, 0).astype(jnp.int32)
                    # inactive rows masked to the garbage page: mid-prefill
                    # slots HOLD real pages, stopped slots' speculative
                    # writes must be unreachable — one mask serves both
                    tbl = tables * active[:, None].astype(tables.dtype)
                    h, pools = core.decode_step_paged(tok, pos, pools, tbl)
                    new_logits = head(h[:, 0, :])
                    new_active, budget = decode_stop_update(
                        tok, active, budget, knobs["eos"])
                    adv = active.astype(jnp.int32)
                    new_state = (new_logits, pos + adv, new_active,
                                 budget, gen + adv)
                    return (new_state, pools), (tok, active)

                (state, pools), (toks, kept) = jax.lax.scan(
                    body, (state, pools), None, length=K)
            return toks, kept, state, pools

        return jax.jit(run, donate_argnums=(1,))

    def _build_spec_decode(self, k: int, any_sample: bool):
        """One speculative tick, fully on device: draft k tokens from the
        slot's history (DraftProvider, no model cost for n-gram lookup),
        verify all k in ONE (k+1)-wide forward (``decode_verify_paged``),
        and commit the agreeing prefix — 1..k+1 tokens per weight pass.

        Acceptance reuses the replay-exact per-(seed, rid, token_index)
        keys: the target token at in-tick offset j is sampled (or argmax)
        from the verify logits with the SAME key the non-speculative scan
        would use at that token index, and a draft is accepted iff it
        EQUALS that target. The committed stream is therefore the
        non-speculative stream token for token (greedy and sampled), and
        a rejection just means next tick re-derives the correction as its
        first token from the carried logits row — same logits, same key,
        same token, no rollback.

        Rejected suffixes fold into the existing ``decode_stop_update``
        carry exactly like retired slots do: their tokens leave the tick
        as pad with ``kept=False`` (the drain's prefix-mask contract is
        unchanged) and their K/V is either overwritten by the next verify
        chunk (positions only advance by the committed prefix) or routed
        to the garbage page (beyond the table span) — so a speculatively
        dispatched NEXT block self-masks what this block rejected and the
        depth-2 in-flight window is preserved."""
        core, model = self.core, self.model
        head = model.logits if hasattr(model, "logits") else (lambda h: h)
        provider = self._draft

        def run(params, pools, tables, base_key, state, knobs, hist):
            ctx = model._bind(params) if hasattr(model, "_bind") else None
            with ctx if ctx is not None else _null():
                logits, pos, active, budget, gen = state
                B = logits.shape[0]
                H = hist.shape[1]
                b_idx = jnp.arange(B)

                def keys_at(off):
                    # token index gen+off: identical to the key the
                    # non-spec scan folds at that stream position
                    return fold_sampling_keys(base_key, knobs["rseed"],
                                              gen + off)

                def pick(lf, off):
                    if any_sample:
                        return sample_logits_per_slot(
                            lf, knobs["temp"], knobs["topk"],
                            knobs["topp"], knobs["dosample"], keys_at(off))
                    return jnp.argmax(lf, axis=-1)

                # tick's first token: sampled from the carried logits —
                # committed unconditionally (it IS the non-spec token)
                tok0 = pick(logits.astype(jnp.float32), 0)
                tok0 = jnp.where(active, tok0, 0).astype(jnp.int32)
                # draft conditioned on history INCLUDING tok0
                wp = jnp.minimum(pos, H - 1)
                hist = hist.at[b_idx, wp].set(
                    jnp.where(active, tok0, hist[b_idx, wp]))
                drafts = provider.propose(
                    hist, pos + active.astype(jnp.int32), k)
                drafts = jnp.where(active[:, None], drafts, 0)
                inputs = jnp.concatenate([tok0[:, None], drafts], axis=1)
                # inactive rows (mid-prefill or stopped by an earlier
                # in-flight block) write to the garbage page, as always
                tbl = tables * active[:, None].astype(tables.dtype)
                h, pools = core.decode_verify_paged(inputs, pos, pools,
                                                    tbl)
                logits_all = head(h)               # [B, k+1, V]
                lf_all = logits_all.astype(jnp.float32)
                # target token at each draft position, with its stream key
                targets = jnp.stack(
                    [pick(lf_all[:, j - 1], j) for j in range(1, k + 1)],
                    axis=1).astype(jnp.int32)      # [B, k]
                acc = drafts == targets
                n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1),
                                axis=1)
                n_commit = 1 + n_acc               # [B] in 1..k+1
                # fold the 1..k+1 candidate commits through the SAME stop
                # update the non-spec scan carries: eos/budget landing
                # mid-accepted-run truncates the run on device (later
                # tokens pad, row retires), rejection truncates via the
                # (j < n_commit) prefix — one mask, rollback free
                alive, bud = active, budget
                toks_rows, kept_rows = [], []
                for j in range(k + 1):
                    tj = inputs[:, j]
                    commit = alive & (j < n_commit)
                    toks_rows.append(jnp.where(commit, tj, 0))
                    kept_rows.append(commit)
                    cont, bud = decode_stop_update(tj, commit, bud,
                                                   knobs["eos"])
                    alive = jnp.where(commit, cont, alive)
                toks = jnp.stack(toks_rows)        # [k+1, B]
                kept = jnp.stack(kept_rows)        # [k+1, B] prefix mask
                nkept = jnp.sum(kept.astype(jnp.int32), axis=0)
                # append committed drafts to history (tok0 already there)
                for j in range(1, k + 1):
                    wp = jnp.minimum(pos + j, H - 1)
                    hist = hist.at[b_idx, wp].set(
                        jnp.where(kept[j], toks[j], hist[b_idx, wp]))
                # carry logits: the row after the last ACCEPT-committed
                # token — valid because its whole input prefix matched
                # the committed stream. (A stop-truncated row retires, so
                # its junk carry is never read.)
                sel = jnp.minimum(n_commit - 1, k)
                new_logits = jnp.take_along_axis(
                    logits_all, sel[:, None, None], axis=1)[:, 0]
                new_state = (new_logits, pos + nkept, alive, bud,
                             gen + nkept)
            return toks, kept, new_state, pools, hist
        # hist is threaded input→output every tick like pools: donate it
        # so the [B, max_len] buffer updates in place (nothing else holds
        # the old history — in-flight blocks only reference toks/kept/
        # pos/active)
        return jax.jit(run, donate_argnums=(1, 6))

    def _participants(self) -> List[Tuple[int, _Request]]:
        """Slots the NEXT block decodes for: prefill done and not yet
        scheduled through their whole token budget (a slot whose budget
        is fully in flight has nothing left to dispatch — the device
        would mask every step anyway)."""
        if self.spec_k:
            # variable-stride: _proj_gen assumes the MAX stride per
            # in-flight block, but the device may commit fewer — a slot
            # excluded on the over-count would keep decoding on device
            # (its row is still active in the carry) and its committed
            # tokens would never be drained. Exclude only when the
            # MINIMUM the device can have committed (>= 1 per in-flight
            # block while the row lives) already exhausts the budget; a
            # slot that actually finished early just drains an all-False
            # kept column, like any stopped slot.
            def _done(s, r):
                # count only THIS request's in-flight blocks: a reused
                # slot may appear in stale blocks of its previous
                # occupant (they drain all-False for it)
                min_gen = len(r.generated) + sum(
                    1 for b in self._inflight
                    if any(s2 == s and r2 is r
                           for s2, r2 in b.participants))
                return min_gen >= r.max_new_tokens
            return [(s, r) for s in range(self.max_batch)
                    if self._decode_ready(r := self._slots[s])
                    and not _done(s, r)]
        return [(s, r) for s in range(self.max_batch)
                if self._decode_ready(r := self._slots[s])
                and int(self._proj_gen[s]) < r.max_new_tokens]

    def _ensure_decode_pages(self, K: int = 1):
        """Claim every page any active slot may KEEP writes in within the
        next K decode steps (against the in-flight PROJECTION of its
        position); preempt (recompute policy) when the pool is dry. A
        slot's claim span is capped by its remaining max_new budget —
        in-block steps past that are masked on device, so claiming for
        them would evict victims for pages never legitimately written.
        With speculative blocks outstanding a dry pool raises _PoolDry
        instead: draining may retire slots and free pages without an
        eviction."""
        for slot in range(self.max_batch):
            req = self._slots[slot]
            if not self._decode_ready(req):
                continue              # mid-prefill slots claim at admission
            pos = int(self._proj_pos[slot])
            span = min(K, req.max_new_tokens - int(self._proj_gen[slot]))
            if span <= 0:
                continue              # budget fully in flight already
            first = pos // self.page_size    # ceil == floor at a boundary;
            # a mid-page pos's current page is already held (tables check)
            last = (pos + span - 1) // self.page_size
            for pidx in range(first, last + 1):
                if pidx >= self.pages_per_seq:
                    raise RuntimeError("sequence exceeded engine max_len")
                existing = int(self.tables[slot, pidx])
                if existing != 0:
                    if self._prefix is not None \
                            and self._prefix.owns(existing):
                        # decode is about to write into a tree-owned
                        # page: copy-on-write it into a private page.
                        # (Admission keeps the mapped prefix strictly
                        # below the first decode position, so today
                        # this only guards future mapping policies —
                        # but the write-a-shared-page hazard is fatal
                        # enough to keep the net under it.)
                        assert self._tree_locks[slot] is None or all(
                            existing not in n.pages
                            for n in self._tree_locks[slot].nodes), \
                            "decode diverged inside its own locked prefix"
                        self.tables[slot, pidx] = self._claim_one(slot)
                        self._cow_page(existing, int(self.tables[slot,
                                                                 pidx]))
                        self._tables_dirty = True
                    continue                  # already holds this page
                self.tables[slot, pidx] = self._claim_one(slot)
                self._tables_dirty = True

    def _claim_one(self, exclude_slot: int) -> int:
        """One page for a decode-time claim; recompute-preempts (policy
        victim when configured, newest-rid otherwise) once the pool AND
        the evictable prefix tree are dry, raising _PoolDry first while
        speculative blocks are still in flight."""
        page = self._alloc_pages(1)
        while page is None:
            if self._inflight:
                raise _PoolDry()
            cands = [i for i in range(self.max_batch)
                     if self._slots[i] is not None and i != exclude_slot]
            if not cands:
                raise RuntimeError("page pool too small for one request")
            if self._admission is not None:
                infos = []
                for i in cands:
                    r = self._slots[i]
                    priv = shared = 0
                    for p in self.tables[i]:
                        if p == 0:
                            continue
                        if self._prefix is not None \
                                and self._prefix.owns(int(p)):
                            shared += 1
                        else:
                            priv += 1
                    infos.append(VictimInfo(slot=i, rid=r.rid,
                                            progress=len(r.generated),
                                            private_pages=priv,
                                            shared_pages=shared))
                victim = self._admission.choose_victim(infos)
            else:
                victim = max(cands, key=lambda i: self._slots[i].rid)
            self.preemptions += 1
            vreq = self._slots[victim]
            self._deactivate(victim)
            # donate the victim's completed pages (prefix mode): its
            # replay re-maps them instead of re-prefilling, and at ref 0
            # they stay first in line for LRU eviction if pressure holds
            self._free_slot(victim, cache=True)
            self._queue.appendleft(vreq)
            page = self._alloc_pages(1)
        return page[0]

    def _dispatch_block(self, emitted: List[tuple]) -> bool:
        """Issue the next decode block WITHOUT waiting for in-flight
        ones. Returns False when no decode-ready slot has budget left."""
        while True:
            parts = self._participants()
            if not parts:
                return False
            if self.spec_k:
                # spec tick: a fixed (spec_k+1)-row block — page claims
                # use the same budget-capped span; draft writes past the
                # table span garbage-route inside decode_verify_paged
                K = self.spec_k + 1
            else:
                # block length this tick: the configured K, capped so no
                # slot's in-block writes can run past its page-table
                # capacity
                cap = self.pages_per_seq * self.page_size
                K = min(self.decode_block,
                        min(cap - int(self._proj_pos[s]) for s, _ in parts))
                K = max(K, 1)
            try:
                self._ensure_decode_pages(K)
            except _PoolDry:
                # drain the pipeline: retirements it reveals may free
                # pages; only preempt once the engine is fully caught up
                self.pool_dry_drains += 1
                emitted.extend(self._drain_all())
                continue
            # a preemption may have emptied or reshuffled the slots
            parts = self._participants()
            if not parts:
                return False
            break
        any_sample = bool(any(self._dosample[s] for s, _ in parts))
        # context-aware dense/paged choice: the batch's max context after
        # this block (projection includes in-flight steps) vs the measured
        # crossover — short contexts keep the dense gather path's edge,
        # long contexts get the paged kernel's 1.45-3.6x win
        spec = bool(self.spec_k)
        # kv_quant folds into the executable key (PR 5 stale-executable
        # posture): pool layout is constructor-fixed today, but an engine
        # whose pools are ever swapped (resharded resume, pool migration)
        # must never reuse a tick compiled for the other layout
        if spec:
            # the verify forward has its own chunk attention (gathers the
            # paged history directly) — no dense/paged fork, so neither
            # the executable key nor attn_path_ticks may depend on it
            fkey = ("spec", K, any_sample, self.kv_quant)
        else:
            ctx_len = max(int(self._proj_pos[s]) for s, _ in parts) + K
            attn_impl = ("dense" if ctx_len <= self.attn_crossover
                         else "paged")
            self.attn_path_ticks[attn_impl] += 1
            fkey = (K, any_sample, attn_impl, self.kv_quant)
        if self.kv_quant:
            self.kv_quant_ticks += 1
        # tables upload BEFORE executable resolution: the cost-observatory
        # eager compile below lowers on the concrete args of this dispatch
        if self._tables_dirty:
            self._tables_dev = jnp.asarray(self.tables)
            self._tables_dirty = False
        fn = self._decode_fns.get(fkey)
        if fn is None:
            jfn = (self._build_spec_decode(self.spec_k, any_sample)
                   if spec else self._build_decode(K, any_sample,
                                                   attn_impl))
            fn = self._decode_fns[fkey] = \
                self._maybe_compile_with_costs(jfn, spec)
        with RecordEvent("serving::dispatch"):
            if spec:
                toks, kept, self._state, self.pools, self._hist = fn(
                    *self._decode_args(True))
            else:
                toks, kept, self._state, self.pools = fn(
                    *self._decode_args(False))
            # start the device→host copies NOW so reconciliation (one or
            # more blocks later) finds the bytes already on host
            for arr in (toks, kept, self._state[1], self._state[2]):
                copy = getattr(arr, "copy_to_host_async", None)
                if copy is not None:
                    copy()
        stride: Optional[Dict[int, int]] = {} if spec else None
        for s, req in parts:
            steps = min(K, req.max_new_tokens - int(self._proj_gen[s]))
            if spec:
                # the min-stride participant rule can dispatch a slot
                # whose projection is already saturated (stride 0): it
                # rides along so its device commits drain, claiming and
                # projecting nothing new
                steps = max(0, steps)
                stride[s] = steps
            self._proj_gen[s] += steps
            self._proj_pos[s] += steps
        self._inflight.append(_InflightBlock(
            toks, kept, self._state[1], self._state[2], parts, K,
            steps=stride))
        return True

    def _block_ready(self, blk: _InflightBlock) -> bool:
        try:
            return bool(blk.toks.is_ready()) and bool(blk.active.is_ready())
        except Exception:
            return False

    def _drain_all(self) -> List[tuple]:
        emitted: List[tuple] = []
        while self._inflight:
            emitted.extend(self._reconcile_one())
        return emitted

    def _reconcile_one(self) -> List[tuple]:
        """Drain the OLDEST in-flight block and run the host bookkeeping
        the device already moved past: append kept tokens, retire slots
        whose done flag came back, record arrival-time latency metrics."""
        blk = self._inflight.popleft()
        with RecordEvent("serving::drain"):
            toks = np.asarray(blk.toks)            # [K, B]
            kept = np.asarray(blk.kept)            # [K, B] prefix mask
            pos_after = np.asarray(blk.pos)
            active_after = np.asarray(blk.active)
        emitted: List[tuple] = []
        # TTFT/ITL stamp at token-ARRIVAL time: under pipelining a
        # block's tokens only exist on host once its drain completes, so
        # percentiles stay honest about what a client would observe
        now = time.perf_counter()
        if _REG.enabled:
            # cost observatory: drain-to-drain gaps are the measured
            # seconds-per-block its breakdown divides
            self._drain_stamps.append(now)
        for slot, req in blk.participants:
            if self._slots[slot] is not req or req.done:
                continue      # retired by an earlier block's reconcile
            nk = 0
            for j in range(blk.K):
                if not kept[j, slot]:
                    break     # active only falls within a block: prefix
                t = int(toks[j, slot])
                req.generated.append(t)
                nk += 1
                if req.first_tok_t == 0.0:
                    req.first_tok_t = now
                emitted.append((req.rid, t))
            if nk:
                self._tokens_emitted += nk
                if self.spec_k:
                    # acceptance accounting: every committing drain
                    # scored spec_k drafts; commits beyond the tick's
                    # one guaranteed token are accepted drafts (stop
                    # truncation undercounts — that's the honest number,
                    # it measures tokens a client actually got)
                    self._spec_drains += 1
                    self.spec_tokens_proposed += self.spec_k
                    self.spec_tokens_accepted += nk - 1
                # per-TOKEN inter-token latency: a multi-token drain
                # (decode_block>1, or nk accepted speculative tokens)
                # emits together, so the drain interval is divided
                # across its tokens; an nk==1 drain keeps the old
                # per-tick gap bit-for-bit. The stall a long peer
                # prefill or a preemption inflicts still shows up — as
                # nk equal shares instead of one outsized gap.
                if req.last_emit_t:
                    gap = (now - req.last_emit_t) / nk
                    req.itl_gaps.extend([gap] * nk)
                req.last_emit_t = now
                if req.tspans is not None:
                    # one replica::decode span per committing drain,
                    # covering [previous commit -> this one]: ITL gap
                    # attribution sees decode as contiguous ownership
                    ts = req.tspans
                    wnow = time.time()
                    sp = ts["tr"].start(
                        "replica::decode",
                        parent=ts.get("res") or ts["parent"],
                        start=ts.get("last", wnow), tags={"n": nk})
                    if sp is not None:
                        sp.end(wnow)
                    ts["last"] = wnow
            if not active_after[slot]:
                # the device's done flag: eos or budget hit inside this
                # block. Tokens past the stop were masked on device and
                # their KV routed to the garbage page; _free_slot resets
                # tables so even the kept KV becomes unreachable.
                req.done = True
                req.done_t = now
                self._requests_retired += 1
                self._latencies.append(
                    (req.first_tok_t - req.submit_t,
                     req.done_t - req.submit_t,
                     len(req.generated)))
                self._itl_gaps.extend(req.itl_gaps)
                # cache=True: donate the whole conversation's completed
                # pages to the prefix tree before the slot's lock
                # releases (prefix mode; no-op otherwise)
                self._free_slot(slot, cache=True)
            else:
                self.pos[slot] = int(pos_after[slot])
        if self.spec_k:
            # variable-stride reconciliation: the dispatch-time
            # projection assumed the MAX stride (spec_k+1) per block;
            # the device may have committed fewer. Re-anchor at the
            # drained truth plus the recorded strides of blocks still in
            # flight. Claims stay safe through corrections: tables keep
            # every page ever claimed (coverage is monotone), and a
            # budget-capped stride only ever occurs once the claim
            # frontier has already reached the slot's full budget span.
            for slot, req in blk.participants:
                if self._slots[slot] is not req or req.done:
                    continue
                extra = sum((b2.steps or {}).get(slot, 0)
                            for b2 in self._inflight)
                self._proj_gen[slot] = len(req.generated) + extra
                self._proj_pos[slot] = int(pos_after[slot]) + extra
        return emitted

    def _check_page_invariants(self) -> None:
        """Test hook (fuzz-asserted): every pool page is exactly one of
        free / privately owned by ONE table / tree-owned with
        ``node.ref == number of tables mapping it`` — the refcount
        invariant prefix sharing lives or dies by."""
        from collections import Counter as _Counter
        free = [int(p) for p in self._free]
        assert len(set(free)) == len(free), "duplicate pages in free list"
        assert 0 not in free, "garbage page leaked into the free list"
        mapped = _Counter(int(p) for row in self.tables for p in row if p)
        tree = dict(self._prefix._pages) if self._prefix is not None \
            else {}
        assert not set(free) & set(mapped), "page both free and mapped"
        assert not set(free) & set(tree), "page both free and tree-owned"
        for p, node in tree.items():
            assert mapped.get(p, 0) == node.ref, (
                f"tree page {p}: refcount {node.ref} != "
                f"{mapped.get(p, 0)} mapping tables")
        for p, c in mapped.items():
            if p not in tree:
                assert c == 1, f"private page {p} mapped by {c} tables"
        accounted = (len(free) + len(tree)
                     + sum(1 for p in mapped if p not in tree))
        assert accounted == self._total_pages, (
            f"page leak: {self._total_pages - accounted} unaccounted")
        if self._prefix is not None:
            self._prefix.check()

    def reset_latency_stats(self) -> None:
        """Drop the retired-request latency window (e.g. after a warmup
        phase whose TTFTs include one-time jit compiles)."""
        self._latencies.clear()
        self._itl_gaps.clear()

    def latency_stats(self) -> Dict[str, float]:
        """TTFT / end-to-end latency percentiles over a sliding window of
        the most recent 10,000 retired requests (survives run()'s request
        release; ``requests``/``tokens`` count the window, not lifetime) —
        the serving SLO numbers (reference: PaddleNLP llm serving
        benchmarks report the same trio: throughput, TTFT, p99).
        Timestamps are token-ARRIVAL times (post-drain), so pipelined
        dispatch cannot flatter the percentiles."""
        if not self._latencies:
            return {}
        arr = np.asarray(self._latencies, np.float64)
        ttft, total = arr[:, 0], arr[:, 1]
        out = {
            "requests": int(arr.shape[0]),
            "tokens": int(arr[:, 2].sum()),
            "ttft_p50_s": float(np.percentile(ttft, 50)),
            "ttft_p99_s": float(np.percentile(ttft, 99)),
            "latency_p50_s": float(np.percentile(total, 50)),
            "latency_p99_s": float(np.percentile(total, 99)),
        }
        if self._itl_gaps:
            gaps = np.asarray(self._itl_gaps, np.float64)
            # per-TOKEN gaps: a multi-token drain (decode_block>1 or an
            # accepted speculative run) divides its interval across the
            # tokens it delivered, so percentiles describe what a client
            # streaming tokens observes. The fairness signal
            # chunked_prefill exists to bound still shows — a long peer
            # prefill or a preemption raises every share in its drain.
            out["itl_p50_s"] = float(np.percentile(gaps, 50))
            out["itl_p99_s"] = float(np.percentile(gaps, 99))
        return out


class _null:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


__all__ = ["ContinuousBatchingEngine", "HANDOFF_FMT", "HANDOFF_FMT_V1"]
