"""Continuous-batching serving engine over the paged-KV decode path.

Reference capability: the block/paged KV-cache serving stack
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu and the
fleet dist-inference helpers). The reference exposes the kernel; serving
systems built on it (vLLM-style) add a page allocator + request scheduler.
This module is that scheduler, TPU-shaped:

- ONE compiled decode step over ``max_batch`` fixed slots (static shapes;
  no recompilation as requests come and go). Inactive slots write their
  K/V into a reserved garbage page and their sampled token is ignored.
- A host-side free-list page allocator over a global pool. Prompt pages
  are claimed at admission; decode pages are claimed LAZILY when a
  sequence's position crosses a page boundary, so short completions never
  reserve worst-case memory (the point of paged attention).
- Recompute-style preemption: if the pool is exhausted when a running
  sequence needs its next page, the most recently admitted active slot is
  evicted back to the queue (pages freed, generated tokens kept for
  replay) — vLLM's "recompute" policy, which on TPU is just a re-prefill.
- Prefill runs per-slot with the prompt padded up to a page multiple
  (bucketed → bounded executable count); the first-token logits are taken
  at the true last-prompt index.

The engine is exact: greedy outputs match ``generate_scan`` per request
regardless of batching/preemption interleaving (tests/test_serving.py).
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .generation import GenerationConfig, sample_logits_batched


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray                  # [L] int32
    max_new_tokens: int
    # per-request sampling knobs (engine defaults when not overridden)
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    do_sample: bool = False
    eos_token_id: Optional[int] = None
    generated: List[int] = field(default_factory=list)
    done: bool = False
    slot: int = -1                      # active slot, -1 = queued/finished
    submit_t: float = 0.0               # perf_counter at submit
    first_tok_t: float = 0.0            # TTFT timestamp (0 = none yet)
    done_t: float = 0.0                 # completion timestamp
    last_emit_t: float = 0.0            # previous tick's emit timestamp
    itl_gaps: List[float] = field(default_factory=list)  # per-TICK gaps
    prefilled: int = 0                  # KV tokens written (chunked mode)
    prefill_target: int = 0             # prompt+replay length to prefill


class ContinuousBatchingEngine:
    """vLLM-style continuous batching over a model exposing the paged-KV
    trio (``alloc_paged_caches`` / ``prefill_paged`` / ``decode_step_paged``
    on its core, e.g. ``LlamaForCausalLM``)."""

    def __init__(self, model, max_batch: int = 8, page_size: int = 128,
                 max_len: int = 2048, num_pages: Optional[int] = None,
                 generation_config: Optional[GenerationConfig] = None,
                 decode_block: int = 1, chunked_prefill: bool = False,
                 prefill_chunk: Optional[int] = None):
        self.model = model
        self.core = getattr(model, "model", model)
        self.cfg = generation_config or GenerationConfig()
        self.max_batch = max_batch
        self.page_size = page_size
        self.max_len = max_len
        self.pages_per_seq = -(-max_len // page_size)
        # pool: page 0 is the reserved garbage page for inactive slots
        total = (num_pages if num_pages is not None
                 else max_batch * self.pages_per_seq) + 1
        pools, _ = self.core.alloc_paged_caches(
            1, total * page_size, page_size)
        self.pools = pools
        self._total_pages = total - 1
        self._free: List[int] = list(range(total - 1, 0, -1))  # stack; 0 kept
        self.tables = np.zeros((max_batch, self.pages_per_seq), np.int32)
        self.pos = np.zeros((max_batch,), np.int32)
        # per-slot sampling knobs, fed to the compiled block as arrays
        self._temp = np.ones((max_batch,), np.float32)
        self._topk = np.zeros((max_batch,), np.int32)
        self._topp = np.ones((max_batch,), np.float32)
        self._dosample = np.zeros((max_batch,), bool)
        self._slots: List[Optional[_Request]] = [None] * max_batch
        self._queue: List[_Request] = []
        self._requests: Dict[int, _Request] = {}
        self._rid = itertools.count()
        self._params = (model.raw_parameters()
                        if hasattr(model, "raw_parameters") else {})
        self._key = jax.random.PRNGKey(self.cfg.seed)
        self._prefill_cache: Dict[int, object] = {}
        # decode_block = tokens generated per compiled scheduler tick. One
        # tick costs ONE dispatch + ONE host readback regardless of K, so
        # over a high-latency link (tunneled TPU; real pods to a lesser
        # degree) throughput scales ~K until compute dominates. Tokens a
        # slot generates past its own EOS/max_new inside a block are
        # discarded on the host (their garbage KV sits beyond the slot's
        # position and is overwritten by later writes), so outputs are
        # EXACT for any K under greedy decoding.
        self.decode_block = max(1, int(decode_block))
        self._decode_fns: Dict[int, object] = {}  # K -> compiled block
        # chunked prefill (Sarathi/vLLM prefill-extend): admission claims
        # pages but prefill proceeds one chunk per scheduler tick,
        # interleaved with decode of running slots — bounds the per-tick
        # stall a long prompt inflicts on running requests' ITL. The
        # chunk is page-aligned so every chunk writes whole pages.
        self.chunked_prefill = bool(chunked_prefill)
        self.prefill_chunk = int(prefill_chunk or page_size)
        if self.prefill_chunk % page_size:
            raise ValueError(f"prefill_chunk ({self.prefill_chunk}) must "
                             f"be a multiple of page_size ({page_size})")
        self._chunk_fn = None
        self._logits = None                # device [max_batch, vocab]
        self.preemptions = 0
        # bounded window (run() releases _Request objects for the same
        # reason — a long-lived engine must not grow per-request state)
        from collections import deque
        self._latencies = deque(maxlen=10_000)  # (ttft_s, total_s, n_tok)
        # per-tick inter-token gaps of retired requests (incl. stalls a
        # preemption or a long peer prefill inflicted on them)
        self._itl_gaps = deque(maxlen=100_000)

    # -- public API ---------------------------------------------------------

    def submit(self, input_ids, max_new_tokens: Optional[int] = None,
               generation_config: Optional[GenerationConfig] = None) -> int:
        """Queue one request; returns its id.

        ``generation_config`` overrides the engine's sampling knobs
        (do_sample/temperature/top_k/top_p) and eos_token_id for THIS
        request only; the token budget comes from the ``max_new_tokens``
        PARAMETER (falling back to the engine default) — gc's own
        max_new_tokens is deliberately ignored, since a caller passing a
        config just to enable sampling would otherwise silently get the
        dataclass default budget of 32. Knobs are per-slot arrays inside
        the one compiled decode block (sample_logits_batched), so any
        mix of greedy and sampled requests batches together with no
        recompilation — the TPU analogue of the reference's per-row
        top_p_sampling_kernel.cu."""
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        gc = generation_config or self.cfg
        new = (max_new_tokens if max_new_tokens is not None
               else self.cfg.max_new_tokens)
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {new}")
        if len(ids) + new > self.max_len:
            raise ValueError(f"prompt {len(ids)} + max_new {new} exceeds "
                             f"engine max_len {self.max_len}")
        if -(-len(ids) // self.page_size) > self._total_pages:
            raise ValueError(f"prompt needs more pages than the pool holds "
                             f"({self._total_pages}); raise num_pages")
        req = _Request(next(self._rid), ids, new,
                       temperature=float(gc.temperature),
                       top_k=int(gc.top_k), top_p=float(gc.top_p),
                       do_sample=bool(gc.do_sample),
                       eos_token_id=gc.eos_token_id)
        req.submit_t = time.perf_counter()
        self._requests[req.rid] = req
        self._queue.append(req)
        return req.rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def step(self) -> List[tuple]:
        """Admit what fits, advance at most one prefill chunk (chunked
        mode), decode a block for every decode-ready slot. Returns
        [(rid, token), ...] emitted this step."""
        self._admit()
        if self.chunked_prefill:
            self._prefill_tick()
        return self._decode()

    def run(self) -> Dict[int, np.ndarray]:
        """Drive until all submitted requests complete; returns
        {rid: np.ndarray of generated tokens} for the requests finished by
        this call and RELEASES them (a long-lived engine must not retain
        every request it ever served)."""
        while self.has_work():
            self.step()
        out = {rid: np.asarray(r.generated, np.int32)
               for rid, r in self._requests.items() if r.done}
        for rid in out:
            del self._requests[rid]
        return out

    def stats(self) -> Dict[str, int]:
        return {"free_pages": len(self._free),
                "active": sum(s is not None for s in self._slots),
                "queued": len(self._queue),
                "preemptions": self.preemptions}

    # -- page allocator -----------------------------------------------------

    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        if len(self._free) < n:
            return None
        return [self._free.pop() for _ in range(n)]

    def _free_slot(self, slot: int):
        req = self._slots[slot]
        # free every held page (page 0 == unset): counting from pos would
        # leak a boundary page granted earlier in the same scheduling pass
        self._free.extend(int(p) for p in self.tables[slot] if p != 0)
        self.tables[slot] = 0
        self.pos[slot] = 0
        self._slots[slot] = None
        if req is not None:
            req.slot = -1
            req.prefilled = 0     # freed pages took the written KV along

    # -- admission / prefill ------------------------------------------------

    def _bucket(self, L: int) -> int:
        return -(-L // self.page_size) * self.page_size

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_cache.get(bucket)
        if fn is not None:
            return fn
        core, model = self.core, self.model
        head = model.logits if hasattr(model, "logits") else (lambda h: h)

        def run(params, ids, pools, tables1, last_idx):
            ctx = model._bind(params) if hasattr(model, "_bind") else None
            with ctx if ctx is not None else _null():
                hidden, pools = core.prefill_paged(ids, pools, tables1)
                logits = head(hidden[0, last_idx, :])
            return logits, pools

        fn = jax.jit(run, donate_argnums=(2,))
        self._prefill_cache[bucket] = fn
        return fn

    def _admit(self):
        while self._queue:
            slot = next((i for i, s in enumerate(self._slots) if s is None),
                        None)
            if slot is None:
                return
            req = self._queue[0]
            L = len(req.prompt) + len(req.generated)
            need = -(-self._bucket(L) // self.page_size)
            pages = self._alloc_pages(need)
            if pages is None:
                if not any(s is not None for s in self._slots):
                    # nothing running that could ever free pages: a replay
                    # grew past the pool (the submit-time check covers only
                    # the original prompt)
                    raise RuntimeError(
                        f"request {req.rid} needs {need} pages but the pool "
                        f"holds {self._total_pages}; raise num_pages")
                return                       # wait for pages to free up
            self._queue.pop(0)
            # replay = prompt + anything generated before a preemption
            toks = np.concatenate([req.prompt,
                                   np.asarray(req.generated, np.int32)])
            self.tables[slot, :len(pages)] = pages
            self._slots[slot] = req
            req.slot = slot
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._topp[slot] = req.top_p
            self._dosample[slot] = req.do_sample
            if self.chunked_prefill:
                # pages claimed now; KV written one chunk per tick
                req.prefilled = 0
                req.prefill_target = L
                self.pos[slot] = 0
                continue
            bucket = self._bucket(L)
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :L] = toks
            self.pos[slot] = L
            req.prefilled = req.prefill_target = L
            logits, self.pools = self._prefill_fn(bucket)(
                self._params, jnp.asarray(ids), self.pools,
                jnp.asarray(self.tables[slot:slot + 1]),
                jnp.int32(L - 1))
            self._set_slot_logits(slot, logits)

    def _decode_ready(self, req) -> bool:
        return req is not None and req.prefilled >= req.prefill_target

    def _build_chunk_fn(self):
        core, model = self.core, self.model
        head = model.logits if hasattr(model, "logits") else (lambda h: h)

        def run(params, ids, offset, pools, tables1, last_idx):
            ctx = model._bind(params) if hasattr(model, "_bind") else None
            with ctx if ctx is not None else _null():
                hidden, pools = core.prefill_chunk_paged(
                    ids, offset, pools, tables1)
                # logits at the prompt's true last index — meaningful on
                # the FINAL chunk only (a single-row head matmul, cheap
                # to compute unconditionally)
                logits = head(hidden[0, last_idx - offset, :])
            return logits, pools

        return jax.jit(run, donate_argnums=(3,))

    def _prefill_tick(self):
        """Advance the oldest in-prefill slot by ONE chunk."""
        cand = [(self._slots[s].rid, s) for s in range(self.max_batch)
                if self._slots[s] is not None
                and not self._decode_ready(self._slots[s])]
        if not cand:
            return
        slot = min(cand)[1]
        req = self._slots[slot]
        C = self.prefill_chunk
        off = req.prefilled
        toks = np.concatenate([req.prompt,
                               np.asarray(req.generated, np.int32)])
        ids = np.zeros((1, C), np.int32)
        chunk = toks[off:off + C]
        ids[0, :len(chunk)] = chunk
        if self._chunk_fn is None:
            self._chunk_fn = self._build_chunk_fn()
        last_idx = req.prefill_target - 1
        logits, self.pools = self._chunk_fn(
            self._params, jnp.asarray(ids), jnp.int32(off), self.pools,
            jnp.asarray(self.tables[slot:slot + 1]),
            jnp.int32(min(last_idx, off + C - 1)))
        req.prefilled = min(off + C, self._bucket(req.prefill_target))
        if req.prefilled >= req.prefill_target:
            self.pos[slot] = req.prefill_target
            self._set_slot_logits(slot, logits)

    def _set_slot_logits(self, slot: int, logits):
        if self._logits is None:
            vocab = logits.shape[-1]
            self._logits = jnp.zeros((self.max_batch, vocab), logits.dtype)
        self._logits = self._logits.at[slot].set(logits)

    # -- decode -------------------------------------------------------------

    def _build_decode(self, K: int, any_sample: bool):
        """K sample+decode steps chained in one compiled lax.scan: one
        dispatch + one [K, B] token readback per scheduler tick. Sampling
        happens IN the scan via sample_logits_batched with per-slot knob
        arrays — mixed greedy/sampled batches share one executable.
        ``any_sample=False`` compiles the argmax-only body (no full-vocab
        sorts in the scan) — the all-greedy common case keeps its old
        cost; the flag is host state, so at most two executables per K."""
        core, model = self.core, self.model
        head = model.logits if hasattr(model, "logits") else (lambda h: h)

        def run(params, logits, pos, pools, tables, active, key,
                temp, topk, topp, dosample):
            ctx = model._bind(params) if hasattr(model, "_bind") else None
            with ctx if ctx is not None else _null():
                def body(carry, _):
                    logits, pos, pools, key = carry
                    key, sub = jax.random.split(key)
                    lf = logits.astype(jnp.float32)
                    if any_sample:
                        tok = sample_logits_batched(lf, temp, topk, topp,
                                                    dosample, sub)
                    else:
                        tok = jnp.argmax(lf, axis=-1)
                    tok = jnp.where(active, tok, 0)
                    h, pools = core.decode_step_paged(tok, pos, pools,
                                                      tables)
                    new_logits = head(h[:, 0, :])
                    pos = jnp.where(active, pos + 1, pos)
                    return (new_logits, pos, pools, key), tok

                (logits, pos, pools, key), toks = jax.lax.scan(
                    body, (logits, pos, pools, key), None, length=K)
            return toks, logits, pools

        return jax.jit(run, donate_argnums=(3,))

    def _ensure_decode_pages(self, K: int = 1):
        """Claim every page any active slot will KEEP writes in within the
        next K decode steps; preempt (recompute policy) when the pool is
        dry. A slot's claim span is capped by its remaining max_new
        budget — in-block steps past that produce discarded tokens whose
        KV lands in the garbage page (tables entry 0), so claiming for
        them would evict victims for pages never legitimately written."""
        for slot in range(self.max_batch):
            req = self._slots[slot]
            if not self._decode_ready(req):
                continue              # mid-prefill slots claim at admission
            pos = int(self.pos[slot])
            span = min(K, req.max_new_tokens - len(req.generated))
            first = pos // self.page_size    # ceil == floor at a boundary;
            # a mid-page pos's current page is already held (tables check)
            last = (pos + span - 1) // self.page_size
            for pidx in range(first, last + 1):
                if pidx >= self.pages_per_seq:
                    raise RuntimeError("sequence exceeded engine max_len")
                if self.tables[slot, pidx] != 0:
                    continue                  # already holds this page
                page = self._alloc_pages(1)
                while page is None:
                    victim = max((i for i in range(self.max_batch)
                                  if self._slots[i] is not None
                                  and i != slot),
                                 key=lambda i: self._slots[i].rid,
                                 default=None)
                    if victim is None:
                        raise RuntimeError(
                            "page pool too small for one request")
                    self.preemptions += 1
                    vreq = self._slots[victim]
                    self._free_slot(victim)
                    self._queue.insert(0, vreq)
                    page = self._alloc_pages(1)
                self.tables[slot, pidx] = page[0]

    def _decode(self) -> List[tuple]:
        active_slots = [i for i, s in enumerate(self._slots)
                        if self._decode_ready(s)]
        if not active_slots:
            return []
        # block length this tick: the configured K, capped so no slot's
        # in-block writes can run past its page-table capacity
        cap = self.pages_per_seq * self.page_size
        K = min(self.decode_block,
                min(cap - int(self.pos[i]) for i in active_slots))
        K = max(K, 1)
        self._ensure_decode_pages(K)
        # a preemption may have emptied every slot
        active_slots = [i for i, s in enumerate(self._slots)
                        if self._decode_ready(s)]
        if not active_slots:
            return []
        any_sample = bool(self._dosample[active_slots].any())
        fn = self._decode_fns.get((K, any_sample))
        if fn is None:
            fn = self._decode_fns[(K, any_sample)] = self._build_decode(
                K, any_sample)
        active = np.zeros((self.max_batch,), bool)
        active[active_slots] = True
        # inactive rows masked to the garbage page: a mid-prefill slot
        # HOLDS real pages, and the compiled block writes KV for every
        # slot — without the mask those writes would corrupt its prefix
        tables_arg = self.tables * active[:, None]
        self._key, sub = jax.random.split(self._key)
        toks, self._logits, self.pools = fn(
            self._params, self._logits, jnp.asarray(self.pos), self.pools,
            jnp.asarray(tables_arg), jnp.asarray(active), sub,
            jnp.asarray(self._temp), jnp.asarray(self._topk),
            jnp.asarray(self._topp), jnp.asarray(self._dosample))
        toks_host = np.asarray(toks)          # [K, max_batch]
        emitted = []
        now = time.perf_counter()
        for slot in active_slots:
            req = self._slots[slot]
            # inter-token latency, measured per SCHEDULER TICK (a K-token
            # block emits together; the stall a long prefill inflicts on
            # running requests shows up as one big gap here — the metric
            # chunked_prefill exists to bound)
            if req.last_emit_t:
                req.itl_gaps.append(now - req.last_emit_t)
            req.last_emit_t = now
            # per-request eos wins over the engine default (the stop check
            # is host-side per token, so honoring it costs nothing)
            eos = req.eos_token_id if req.eos_token_id is not None \
                else self.cfg.eos_token_id
            kept = 0
            for j in range(K):
                t = int(toks_host[j, slot])
                req.generated.append(t)
                kept += 1
                if req.first_tok_t == 0.0:
                    req.first_tok_t = now
                emitted.append((req.rid, t))
                if (len(req.generated) >= req.max_new_tokens
                        or (eos is not None and t == eos)):
                    req.done = True
                    break
            if req.done:
                req.done_t = now
                self._latencies.append(
                    (req.first_tok_t - req.submit_t,
                     req.done_t - req.submit_t,
                     len(req.generated)))
                self._itl_gaps.extend(req.itl_gaps)
                # tokens past the stop point (and their KV) are dropped;
                # _free_slot resets pos/tables so the garbage is unreachable
                self._free_slot(slot)
            else:
                self.pos[slot] += kept        # kept == K here
        return emitted

    def reset_latency_stats(self) -> None:
        """Drop the retired-request latency window (e.g. after a warmup
        phase whose TTFTs include one-time jit compiles)."""
        self._latencies.clear()
        self._itl_gaps.clear()

    def latency_stats(self) -> Dict[str, float]:
        """TTFT / end-to-end latency percentiles over a sliding window of
        the most recent 10,000 retired requests (survives run()'s request
        release; ``requests``/``tokens`` count the window, not lifetime) —
        the serving SLO numbers (reference: PaddleNLP llm serving
        benchmarks report the same trio: throughput, TTFT, p99)."""
        if not self._latencies:
            return {}
        arr = np.asarray(self._latencies, np.float64)
        ttft, total = arr[:, 0], arr[:, 1]
        out = {
            "requests": int(arr.shape[0]),
            "tokens": int(arr[:, 2].sum()),
            "ttft_p50_s": float(np.percentile(ttft, 50)),
            "ttft_p99_s": float(np.percentile(ttft, 99)),
            "latency_p50_s": float(np.percentile(total, 50)),
            "latency_p99_s": float(np.percentile(total, 99)),
        }
        if self._itl_gaps:
            gaps = np.asarray(self._itl_gaps, np.float64)
            # per-TICK gaps (decode_block tokens emit together): the
            # fairness number chunked_prefill exists to bound — a long
            # peer prefill or a preemption shows up as one big gap
            out["itl_p50_s"] = float(np.percentile(gaps, 50))
            out["itl_p99_s"] = float(np.percentile(gaps, 99))
        return out


class _null:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


__all__ = ["ContinuousBatchingEngine"]
