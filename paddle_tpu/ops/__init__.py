"""paddle_tpu.ops — the op library.

TPU-native replacement for the reference's PHI kernel library
(reference: paddle/phi/kernels/ — 415K LoC of CUDA/C++). Here most ops are
jnp/lax compositions that XLA fuses; the hot set (flash attention, fused
norms, rope, MoE dispatch) has Pallas TPU kernels under ops/pallas/ selected
at dispatch time (ops/registry.py) — the analogue of PHI's KernelFactory
(backend,dtype)-keyed dispatch (paddle/phi/core/kernel_factory.h:314) reduced
to the one decision XLA doesn't make for us: hand-written kernel vs compiler.
"""

from . import attention, norm, rope
from .registry import dispatch, register_kernel, backend_kind

# Pallas TPU kernels register themselves for backend "tpu" on import; the
# XLA compositions above remain the "any" fallback and the test oracle.
try:
    from .pallas import flash_attention as _pallas_flash_attention  # noqa: F401
    from .pallas import fused_norm as _pallas_fused_norm  # noqa: F401
    from .pallas import fused_vocab_ce as _pallas_fused_vocab_ce  # noqa: F401
    from .pallas import int8_matmul as _pallas_int8_matmul  # noqa: F401
except ImportError:  # pragma: no cover — jaxlib without pallas
    pass
