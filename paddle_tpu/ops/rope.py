"""Rotary position embedding.

Reference analogue: paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu and
python/paddle/incubate/nn/functional/fused_rotary_position_embedding.py.

Implements the NEOX/Llama rotate-half convention on [b, s, h, d] tensors;
cos/sin are computed once per (seq, dim) and broadcast — XLA fuses the
elementwise rotation into adjacent matmuls.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rope_freqs(head_dim: int, max_seq: int, base: float = 10000.0,
               scaling_factor: float = 1.0, dtype=jnp.float32):
    """Precompute (cos, sin) tables [max_seq, head_dim]."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32) / scaling_factor
    freqs = jnp.outer(t, inv_freq)                 # [s, d/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [s, d]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_pos_emb(q, k, cos, sin, position_ids=None):
    """q,k: [b, s, h, d]; cos/sin: [max_seq, d] or [s, d].

    Mirrors fused_rotary_position_embedding(use_neox_rotary_style=True).
    """
    s = q.shape[1]
    if position_ids is None:
        out = _try_pallas_rope(q, k, cos[:s], sin[:s])
        if out is not None:
            return out
    if position_ids is not None:
        cos = cos[position_ids]          # [b, s, d]
        sin = sin[position_ids]
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    else:
        cos = cos[:s][None, :, None, :]  # [1, s, 1, d]
        sin = sin[:s][None, :, None, :]
    cos = cos.astype(q.dtype)
    sin = sin.astype(q.dtype)
    q_out = q * cos + _rotate_half(q) * sin
    k_out = k * cos + _rotate_half(k) * sin
    return q_out, k_out


def _try_pallas_rope(q, k, cos, sin):
    """Fused q+k rotation in one Pallas kernel (training path, contiguous
    positions); None -> XLA composition. The custom_vjp applies the
    transpose rotation (cos, -sin) to the q/k cotangents and computes
    EXACT table cotangents from the saved inputs (q, k, cos, sin are the
    residuals); when the tables are buffers — every model here — the
    table-grad computation and its residual use are dead and XLA's DCE
    removes them under jit."""
    from .registry import backend_kind, pallas_disabled
    from ..core.flags import flag
    if (pallas_disabled() or not flag("use_pallas_kernels")
            or backend_kind() != "tpu" or q.ndim != 4):
        return None
    from .pallas.fused_rope import (fused_rope_pallas, rope_supported,
                                    tuned_block_s)
    if not rope_supported(tuple(q.shape), tuple(k.shape)):
        return None
    bs = tuned_block_s(q.shape[1], q.shape[3], q.dtype)
    try:
        return _rope_fwd_bwd(q, k, cos, sin, bs)
    except Exception:
        return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _rope_fwd_bwd(q, k, cos, sin, block_s):
    from .pallas.fused_rope import fused_rope_pallas
    return fused_rope_pallas(q, k, cos, sin, block_s=block_s)


def _rope_fwd(q, k, cos, sin, block_s):
    out = _rope_fwd_bwd(q, k, cos, sin, block_s)
    return out, (q, k, cos, sin)


def _rope_bwd(block_s, res, g):
    # rotation matrix transpose: R(theta)^T = R(-theta) -> (cos, -sin)
    from .pallas.fused_rope import fused_rope_pallas
    q, k, cos, sin = res
    gq, gk = g
    dq, dk = fused_rope_pallas(gq, gk, cos, -sin, block_s=block_s)
    # table cotangents (exact; XLA DCEs these when the tables are
    # buffers/stop_gradient'd, the common case): out = x*cos + rot(x)*sin
    f32 = jnp.float32
    dcos = (jnp.sum(gq.astype(f32) * q.astype(f32), axis=(0, 2))
            + jnp.sum(gk.astype(f32) * k.astype(f32), axis=(0, 2)))
    dsin = (jnp.sum(gq.astype(f32) * _rotate_half(q).astype(f32),
                    axis=(0, 2))
            + jnp.sum(gk.astype(f32) * _rotate_half(k).astype(f32),
                      axis=(0, 2)))
    return dq, dk, dcos.astype(cos.dtype), dsin.astype(sin.dtype)


_rope_fwd_bwd.defvjp(_rope_fwd, _rope_bwd)


def fused_rotary_position_embedding(q, k, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """API-parity wrapper (reference:
    python/paddle/incubate/nn/functional/fused_rotary_position_embedding.py).
    Note argument order (sin, cos) follows the reference."""
    if cos is None or sin is None:
        raise ValueError("cos/sin tables required")
    if cos.ndim == 4:  # reference passes [1, s, 1, d]
        cos = cos[0, :, 0, :]
        sin = sin[0, :, 0, :]
    q_out, k_out = apply_rotary_pos_emb(q, k, cos, sin, position_ids)
    return q_out, k_out, v
