"""Pallas TPU fused RMSNorm (forward + backward, custom_vjp).

Reference analogue: paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu
(rms-norm path) and python surface incubate/nn/functional/fused_rms_norm.py.

TPU-first design: the norm is HBM-bandwidth-bound, so the win is a single
pass per tensor — each row block is read once into VMEM, the mean-square
reduction and the scale multiply happen in-register, and (for backward) the
saved per-row rstd avoids recomputing the reduction. The weight gradient is
a cross-row reduction, which Pallas handles with a per-row-block partial
that XLA sums afterwards (keeps the kernel race-free without atomics —
which TPUs don't have).

Falls back to the XLA composition for ragged shapes / non-TPU backends.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BLOCK_R = 256


def _vmem(shape, index_map):
    return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)


# ---------------------------------------------------------------------------
# kernels ([R, D] layout; grid over row blocks)
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, o_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)              # [br, D]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)                  # [br, 1]
    o_ref[...] = (x * rstd * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)
    rstd_ref[...] = rstd                            # [br, 1]


def _bwd_kernel(x_ref, w_ref, rstd_ref, dy_ref, dx_ref, dwp_ref):
    x = x_ref[...].astype(jnp.float32)              # [br, D]
    dy = dy_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)              # [1, D]-broadcastable
    rstd = rstd_ref[...]                            # [br, 1]
    xhat = x * rstd
    wdy = dy * w
    c = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx_ref[...] = ((wdy - xhat * c) * rstd).astype(dx_ref.dtype)
    # per-block partial weight grad, padded to a full (8, D) sublane tile
    # (a (1, D) block over an (nblocks, D) array violates Mosaic's sublane
    # rule — the round-2 bench died here); only sublane 0 carries data
    part = jnp.sum(dy * xhat, axis=0, keepdims=True)          # [1, D] fp32
    sub = jax.lax.broadcasted_iota(jnp.int32, (8, part.shape[1]), 0)
    dwp_ref[...] = jnp.where(sub == 0, jnp.broadcast_to(part, sub.shape), 0.0)


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rms_norm_p(x2d, w, eps, block_r, interpret):
    out, _ = _rms_fwd(x2d, w, eps, block_r, interpret)
    return out


def _rms_fwd(x2d, w, eps, block_r, interpret):
    R, D = x2d.shape
    br = min(block_r, R)
    grid = (R // br,)
    out, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[_vmem((br, D), lambda r: (r, 0)),
                  _vmem((1, D), lambda r: (0, 0))],
        out_specs=[_vmem((br, D), lambda r: (r, 0)),
                   # rstd kept 2-D [R, 1]: rank-1 outputs trip an XLA-vs-
                   # Mosaic tiling mismatch (T(1024) vs T(256)) on real TPU
                   _vmem((br, 1), lambda r: (r, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, D), x2d.dtype),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32)],
        compiler_params=(pltpu.CompilerParams(
            dimension_semantics=("parallel",)) if pltpu else None),
        interpret=interpret,
    )(x2d, w.reshape(1, D))
    return out, rstd


def _rms_fwd_rule(x2d, w, eps, block_r, interpret):
    out, rstd = _rms_fwd(x2d, w, eps, block_r, interpret)
    return out, (x2d, w, rstd)


def _rms_bwd_rule(eps, block_r, interpret, res, dy):
    x2d, w, rstd = res
    R, D = x2d.shape
    br = min(block_r, R)
    nblocks = R // br
    dx, dwp = pl.pallas_call(
        _bwd_kernel,
        grid=(nblocks,),
        in_specs=[_vmem((br, D), lambda r: (r, 0)),
                  _vmem((1, D), lambda r: (0, 0)),
                  _vmem((br, 1), lambda r: (r, 0)),
                  _vmem((br, D), lambda r: (r, 0))],
        out_specs=[_vmem((br, D), lambda r: (r, 0)),
                   _vmem((8, D), lambda r: (r, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, D), x2d.dtype),
                   jax.ShapeDtypeStruct((nblocks * 8, D), jnp.float32)],
        compiler_params=(pltpu.CompilerParams(
            dimension_semantics=("parallel",)) if pltpu else None),
        interpret=interpret,
    )(x2d, w.reshape(1, D), rstd, dy)
    dw = jnp.sum(dwp, axis=0).astype(w.dtype)
    return dx, dw


_rms_norm_p.defvjp(_rms_fwd_rule, _rms_bwd_rule)


def _pick_block_r(R: int, D: int, block_r: int = DEFAULT_BLOCK_R) -> int:
    """Largest row block that (a) divides R, (b) is sublane-aligned, and
    (c) keeps the BACKWARD kernel's VMEM working set under budget.

    The bwd kernel holds ~6 fp32 [br, D] temporaries (x, dy, xhat, wdy
    plus in/out copies) ≈ 30·br·D bytes of scoped VMEM; the hard limit is
    16 MB (observed live: br=256 at D=4096 allocates 22.6 MB and Mosaic
    aborts the compile — the Llama-3-8B hidden size). Budget 8 MB leaves
    headroom for Mosaic's own stack."""
    budget = 8 * 1024 * 1024
    br = min(block_r, R)
    while br > 8 and (R % br or 30 * br * D > budget):
        br //= 2
    return max(br, 8)


def pallas_rms_supported(x, weight) -> bool:
    from ..registry import pallas_disabled
    if not _HAS_PLTPU or weight is None or pallas_disabled():
        return False
    D = x.shape[-1]
    R = max(x.size // D, 1)
    br = _pick_block_r(R, D)
    return D % 128 == 0 and R % br == 0 and br % 8 == 0


def rms_norm_pallas(x, weight, epsilon: float = 1e-6,
                    block_r: int = DEFAULT_BLOCK_R, interpret: bool = False):
    """Fused RMS norm; XLA fallback when the shape doesn't tile."""
    if not pallas_rms_supported(x, weight):
        from ..norm import _rms_norm_xla
        return _rms_norm_xla(x, weight, epsilon)
    shape = x.shape
    D = shape[-1]
    x2d = x.reshape(-1, D)
    out = _rms_norm_p(x2d, weight, float(epsilon),
                      _pick_block_r(x2d.shape[0], D, block_r), interpret)
    return out.reshape(shape)


from ..registry import register_kernel  # noqa: E402


@register_kernel("rms_norm", "tpu")
def _rms_norm_tpu(x, weight=None, epsilon: float = 1e-6):
    return rms_norm_pallas(x, weight, epsilon)
