"""Pallas TPU flash attention (forward + backward), with segment + dropout
support.

Reference analogue: paddle/phi/kernels/gpu/flash_attn_kernel.cu (FA2 via
dynload — flash_attn_fwd/bwd, incl. the varlen entry at :91, in-kernel
dropout via the philox args at :91-117) and its python surface
python/paddle/nn/functional/flash_attention.py. Re-designed for the TPU
memory hierarchy instead of translated: the kernel streams K/V blocks
through VMEM with the online-softmax recurrence (running max m, denominator
l) carried in VMEM scratch across the innermost sequential grid dimension,
keeping the [sq, sk] score matrix out of HBM entirely; fp32 accumulation on
the MXU via preferred_element_type.

TPU layout (the round-2 fix): Mosaic requires the last two dims of every
block to be (sublane, lane) = (8k, 128k) aligned or equal to the array
dims, so the kernel computes in [b, h, s, d] — blocks are
(1, 1, block_q, d). The public API keeps the paddle/FA convention
[b, s, h, d]; the transposes sit at the pallas boundary where XLA fuses
them. Per-row logsumexp rides in a [b, h, s, LSE_LANES] array (scalar
broadcast across a small lane dim) for the same reason.

GQA: h_kv <= h mapped via BlockSpec index arithmetic — no materialized head
expansion in the forward, and dk/dv are accumulated AT KV-HEAD RESOLUTION
inside the backward kernel by folding the query-head group into the
innermost sequential grid dim.

Varlen / packed sequences: integer ``segment_ids`` ([b, sq] / [b, sk])
mask cross-segment attention inside the kernel — the TPU equivalent of the
reference's cu_seqlens varlen API (flash_attn_kernel.cu:91).

Dropout: in-kernel counter-based PRNG — each score cell hashes its global
(batch, head, q-pos, k-pos) coordinates with the seed (murmur3 finalizer,
plain uint32 vector ops), so the forward and both backward kernels
regenerate the identical keep-mask from one scalar seed on any backend and
under any block-size choice — the TPU analogue of FA2's philox offset
replay (flash_attn_kernel.cu dropout path). No O(s^2) mask ever hits HBM.

Backward = two kernels (dq; dk+dv) using the saved per-row logsumexp, plus
a delta = rowsum(out * dout) precomputed in XLA.

Falls back to the XLA composition (ops/attention.py) for arbitrary dense
masks or block-indivisible sequence lengths.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports cleanly on TPU-enabled jaxlib builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..registry import register_kernel


def _tpu_params(*semantics):
    """Megacore: mark independent grid dims parallel; only the innermost
    (k/q accumulation) dim is sequential ("arbitrary")."""
    if pltpu is None:
        return None
    return pltpu.CompilerParams(dimension_semantics=tuple(semantics))

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30  # large-negative instead of -inf: avoids inf-inf=nan in exp
LSE_LANES = 8    # lane width for per-row scalars (lse/delta); Mosaic wants
                 # the last block dim == the array dim, 8 keeps HBM cost low


def _block_spec(shape, index_map):
    return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)


def _causal_mask(qi, ki, offset, block_q, block_k):
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return (cols + ki * block_k) <= (rows + qi * block_q + offset)


def _mask_scores(s, causal, qs_ref, ks_ref, qi, ki, offset, block_q, block_k):
    """Apply causal and/or segment masking to a [bq, bk] score block.

    qs_ref: [1, block_q, LSE_LANES] tile; ks_ref: [1, LSE_LANES, block_k]
    tile (segment ids lane/sublane-broadcast outside the kernel) — all
    reads stay 2-D, which Mosaic vectorizes cleanly."""
    mask = None
    if causal:
        mask = _causal_mask(qi, ki, offset, block_q, block_k)
    if qs_ref is not None:
        qseg = qs_ref[0, :, :1]            # [bq, 1]
        kseg = ks_ref[0, :1, :]            # [1, bk]
        seg = qseg == kseg
        mask = seg if mask is None else (mask & seg)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    return s


def _dropout_keep(seed_ref, bi, h, qi, ki, dropout_p, block_q, block_k, sk):
    """Regenerable keep-mask for one [block_q, block_k] score block.

    Counter-based: each (batch, head, query-pos, key-pos) CELL hashes its
    global coordinates with the seed through the murmur3 finalizer — plain
    uint32 vector ops, so the same bits come out of Mosaic on TPU and of
    the interpreters on CPU, and out of the forward, dq and dkv kernels
    regardless of grid order or autotuned block sizes. (pltpu.prng_* was
    rejected: the TPU-interpret simulator stubs it to zeros, which would
    make dropout untestable off-hardware.)"""
    rows = jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 1)
    cell = ((qi * block_q).astype(jnp.uint32) + rows) * jnp.uint32(sk) \
        + (ki * block_k).astype(jnp.uint32) + cols
    key = (seed_ref[0].astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
           + bi.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
           + h.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D))
    x = cell ^ key
    # murmur3 fmix32: full-avalanche mixing of the 32-bit cell id
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    threshold = jnp.uint32(min(int(dropout_p * 4294967296.0), 4294967295))
    return x >= threshold                                # P(keep) = 1 - p


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, scale, causal, has_seg, dropout_p, sq, sk,
                block_q, block_k):
    """Grid: (b, h, nq, nk) — nk innermost/sequential; scratch carries the
    online-softmax state across nk iterations. All tensor blocks are
    [1, 1, block, d]-shaped over [b, h, s, d] arrays."""
    refs = list(refs)
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    if has_seg:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref,
         o_ref, lse_ref, m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        qs_ref = ks_ref = None
    bi = pl.program_id(0)
    hi = pl.program_id(1)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip blocks strictly above the diagonal (bottom-right aligned)
    offset = sk - sq
    first_masked_col = qi * block_q + offset + block_q  # col >= this masked

    @pl.when(jnp.logical_not(causal) | (ki * block_k < first_masked_col))
    def _compute():
        q = q_ref[0, 0, :, :]                      # [bq, d]
        k = k_ref[0, 0, :, :]                      # [bk, d]
        v = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        s = _mask_scores(s, causal, qs_ref, ks_ref, qi, ki, offset,
                         block_q, block_k)
        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        # masked entries must be EXACTLY zero even when the whole row is
        # masked (m_new == NEG_INF would make exp(s - m_new) = 1, turning
        # a fully-masked row into a mean over V)
        p = jnp.where(s <= NEG_INF * 0.5, 0.0,
                      jnp.exp(s - m_new))          # [bq, bk]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_p > 0.0:
            # l accumulates the true softmax denominator; dropout scales the
            # numerator only (dropout(P)·V == (Σ p·M/(1-r)·v)/l)
            keep = _dropout_keep(seed_ref, bi, hi, qi, ki, dropout_p,
                                 block_q, block_k, sk)
            p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(safe_l), (block_q, LSE_LANES))


def _seg_inputs(q_seg, kv_seg):
    """Lift [b, s] segment ids into lane/sublane-broadcast 3-D arrays whose
    blocks satisfy the Mosaic (8, 128) rule: q as [b, sq, LSE_LANES]
    (lane-broadcast), kv as [b, LSE_LANES, sk] (sublane-broadcast)."""
    qs = jnp.broadcast_to(q_seg[:, :, None],
                          (*q_seg.shape, LSE_LANES))
    ks = jnp.broadcast_to(kv_seg[:, None, :],
                          (kv_seg.shape[0], LSE_LANES, kv_seg.shape[1]))
    return qs, ks


def _qseg_spec(block_q, index_map):
    return _block_spec((1, block_q, LSE_LANES), index_map)


def _kseg_spec(block_k, index_map):
    return _block_spec((1, LSE_LANES, block_k), index_map)


def _fwd(q, k, v, q_seg, kv_seg, seed, dropout_p, scale, causal,
         block_q, block_k, interpret):
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    nq = sq // block_q
    nk = sk // block_k
    grid = (b, h, nq, nk)
    has_seg = q_seg is not None

    qt = jnp.swapaxes(q, 1, 2)                     # [b, h, sq, d]
    kt = jnp.swapaxes(k, 1, 2)                     # [b, h_kv, sk, d]
    vt = jnp.swapaxes(v, 1, 2)

    q_spec = _block_spec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kv_spec = _block_spec((1, 1, block_k, d),
                          lambda bi, hi, qi, ki: (bi, hi // group, ki, 0))
    o_spec = q_spec
    lse_spec = _block_spec((1, 1, block_q, LSE_LANES),
                           lambda bi, hi, qi, ki: (bi, hi, qi, 0))

    in_specs = []
    inputs = []
    if dropout_p > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        inputs.append(seed)
    in_specs += [q_spec, kv_spec, kv_spec]
    inputs += [qt, kt, vt]
    if has_seg:
        qs, ks = _seg_inputs(q_seg, kv_seg)
        in_specs += [
            _qseg_spec(block_q, lambda bi, hi, qi, ki: (bi, qi, 0)),
            _kseg_spec(block_k, lambda bi, hi, qi, ki: (bi, 0, ki))]
        inputs += [qs, ks]

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               has_seg=has_seg, dropout_p=dropout_p,
                               sq=sq, sk=sk, block_q=block_q, block_k=block_k)
    scratch = [pltpu.VMEM((block_q, 128), jnp.float32),
               pltpu.VMEM((block_q, 128), jnp.float32),
               pltpu.VMEM((block_q, d), jnp.float32)]
    out_t, lse4 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[o_spec, lse_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, sq, LSE_LANES), jnp.float32)],
        scratch_shapes=scratch,
        compiler_params=_tpu_params("parallel", "parallel", "parallel",
                                    "arbitrary"),
        interpret=interpret,
    )(*inputs)
    return jnp.swapaxes(out_t, 1, 2), lse4[..., 0]   # [b,sq,h,d], [b,h,sq]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(*refs, scale, causal, has_seg, dropout_p, sq, sk,
                   block_q, block_k):
    """Grid (b, h, nq, nk): accumulate dq over kv blocks."""
    refs = list(refs)
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dq_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_scr) = refs
        qs_ref = ks_ref = None
    bi = pl.program_id(0)
    hi = pl.program_id(1)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    offset = sk - sq
    first_masked_col = qi * block_q + offset + block_q

    @pl.when(jnp.logical_not(causal) | (ki * block_k < first_masked_col))
    def _compute():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :1]                 # [bq, 1]
        delta = delta_ref[0, 0, :, :1]             # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, causal, qs_ref, ks_ref, qi, ki, offset,
                         block_q, block_k)
        # masked entries exactly zero (a fully-masked row has lse=NEG_INF;
        # exp(NEG_INF - NEG_INF) = 1 would corrupt dq/dk/dv)
        p = jnp.where(s <= NEG_INF * 0.5, 0.0,
                      jnp.exp(s - lse))            # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _dropout_keep(seed_ref, bi, hi, qi, ki, dropout_p,
                                 block_q, block_k, sk)
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, has_seg, dropout_p, sq, sk,
                    block_q, block_k, group, nq):
    """Grid (b, h_kv, nk, nq*group): accumulate dk/dv at KV-HEAD resolution.

    The innermost sequential dim enumerates (query-head-in-group, q-block)
    pairs, so the GQA group sum happens in the VMEM accumulator instead of
    as a group-times-larger fp32 intermediate in HBM (round-1 weak item:
    FA2 accumulates at kv-head resolution; flash_attn_kernel.cu)."""
    refs = list(refs)
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        qs_ref = ks_ref = None
    bi = pl.program_id(0)
    hkv = pl.program_id(1)
    ki = pl.program_id(2)
    qg = pl.program_id(3)
    nqg = pl.num_programs(3)
    qi = qg % nq          # q-block index (group-major enumeration)
    h = hkv * group + qg // nq   # semantic query head for dropout replay

    @pl.when(qg == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    offset = sk - sq
    # causal: this (ki, qi) pair contributes unless the whole block is
    # masked: masked iff min col in block > max row+offset in block
    max_row = qi * block_q + block_q - 1 + offset

    @pl.when(jnp.logical_not(causal) | (ki * block_k <= max_row))
    def _compute():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :1]
        delta = delta_ref[0, 0, :, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, causal, qs_ref, ks_ref, qi, ki, offset,
                         block_q, block_k)
        # masked entries exactly zero (a fully-masked row has lse=NEG_INF;
        # exp(NEG_INF - NEG_INF) = 1 would corrupt dq/dk/dv)
        p = jnp.where(s <= NEG_INF * 0.5, 0.0,
                      jnp.exp(s - lse))            # [bq, bk]
        pd = p
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _dropout_keep(seed_ref, bi, h, qi, ki, dropout_p,
                                 block_q, block_k, sk)
            inv = 1.0 / (1.0 - dropout_p)
            pd = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        dv_scr[:] += jax.lax.dot_general(pd.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale              # [bq, bk]
        dk_scr[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(qg == nqg - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(dropout_p, scale, causal, block_q, block_k, interpret, res, dout):
    q, k, v, q_seg, kv_seg, seed, out, lse = res
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    has_seg = q_seg is not None
    delta = jnp.sum(out.astype(jnp.float32) * dout.astype(jnp.float32),
                    axis=-1)                        # [b, sq, h]
    delta = jnp.moveaxis(delta, -1, 1)              # [b, h, sq]

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    dot = jnp.swapaxes(dout, 1, 2)                  # [b, h, sq, d]
    lse4 = jnp.broadcast_to(lse[..., None], (b, h, sq, LSE_LANES))
    delta4 = jnp.broadcast_to(delta[..., None], (b, h, sq, LSE_LANES))

    nq, nk = sq // block_q, sk // block_k
    q_spec = _block_spec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kv_spec = _block_spec((1, 1, block_k, d),
                          lambda bi, hi, qi, ki: (bi, hi // group, ki, 0))
    lse_spec = _block_spec((1, 1, block_q, LSE_LANES),
                           lambda bi, hi, qi, ki: (bi, hi, qi, 0))

    dq_inputs = [qt, kt, vt, dot, lse4, delta4]
    dq_specs = [q_spec, kv_spec, kv_spec, q_spec, lse_spec, lse_spec]
    if dropout_p > 0.0:
        dq_inputs.insert(0, seed)
        dq_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
    if has_seg:
        qs, ks = _seg_inputs(q_seg, kv_seg)
        dq_specs += [
            _qseg_spec(block_q, lambda bi, hi, qi, ki: (bi, qi, 0)),
            _kseg_spec(block_k, lambda bi, hi, qi, ki: (bi, 0, ki))]
        dq_inputs += [qs, ks]

    dq_t = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, dropout_p=dropout_p, sq=sq, sk=sk,
                          block_q=block_q, block_k=block_k),
        grid=(b, h, nq, nk),
        in_specs=dq_specs,
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_tpu_params("parallel", "parallel", "parallel",
                                    "arbitrary"),
        interpret=interpret,
    )(*dq_inputs)[0]

    # dk/dv accumulated at kv-head resolution: grid (b, h_kv, nk, nq*group);
    # the q-head for inner index qg is hkv*group + qg//nq (group-major)
    q_spec2 = _block_spec(
        (1, 1, block_q, d),
        lambda bi, hi, ki, qg: (bi, hi * group + qg // nq, qg % nq, 0))
    kv_spec2 = _block_spec((1, 1, block_k, d),
                           lambda bi, hi, ki, qg: (bi, hi, ki, 0))
    kvout_spec = kv_spec2
    lse_spec2 = _block_spec(
        (1, 1, block_q, LSE_LANES),
        lambda bi, hi, ki, qg: (bi, hi * group + qg // nq, qg % nq, 0))

    dkv_inputs = [qt, kt, vt, dot, lse4, delta4]
    dkv_specs = [q_spec2, kv_spec2, kv_spec2, q_spec2, lse_spec2, lse_spec2]
    if dropout_p > 0.0:
        dkv_inputs.insert(0, seed)
        dkv_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
    if has_seg:
        qs, ks = _seg_inputs(q_seg, kv_seg)
        dkv_specs += [
            _qseg_spec(block_q, lambda bi, hi, ki, qg: (bi, qg % nq, 0)),
            _kseg_spec(block_k, lambda bi, hi, ki, qg: (bi, 0, ki))]
        dkv_inputs += [qs, ks]

    dk_t, dv_t = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, dropout_p=dropout_p, sq=sq, sk=sk,
                          block_q=block_q, block_k=block_k, group=group,
                          nq=nq),
        grid=(b, h_kv, nk, nq * group),
        in_specs=dkv_specs,
        out_specs=[kvout_spec, kvout_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h_kv, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h_kv, sk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_tpu_params("parallel", "parallel", "parallel",
                                    "arbitrary"),
        interpret=interpret,
    )(*dkv_inputs)

    dq = jnp.swapaxes(dq_t, 1, 2)
    dk = jnp.swapaxes(dk_t, 1, 2)
    dv = jnp.swapaxes(dv_t, 1, 2)

    import numpy as _np
    if has_seg:
        # int cotangents are symbolically zero (float0) in jax
        zseg = (_np.zeros(q_seg.shape, jax.dtypes.float0),
                _np.zeros(kv_seg.shape, jax.dtypes.float0))
    else:
        zseg = (None, None)
    dseed = (_np.zeros(seed.shape, jax.dtypes.float0)
             if seed is not None else None)
    return (dq, dk, dv) + zseg + (dseed,)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def _flash_attention(q, k, v, q_seg, kv_seg, seed, dropout_p, scale, causal,
                     block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, q_seg, kv_seg, seed, dropout_p, scale, causal,
                  block_q, block_k, interpret)
    return out


def _flash_fwd_rule(q, k, v, q_seg, kv_seg, seed, dropout_p, scale, causal,
                    block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, q_seg, kv_seg, seed, dropout_p, scale, causal,
                    block_q, block_k, interpret)
    return out, (q, k, v, q_seg, kv_seg, seed, out, lse)


def _flash_bwd_rule(dropout_p, scale, causal, block_q, block_k, interpret,
                    res, dout):
    return _bwd(dropout_p, scale, causal, block_q, block_k, interpret, res,
                dout)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _normalize_segments(segment_ids, b, sq, sk):
    """segment_ids: [b, s] (self-attn) or (q_seg [b, sq], kv_seg [b, sk])."""
    if segment_ids is None:
        return None, None
    if isinstance(segment_ids, (tuple, list)):
        q_seg, kv_seg = segment_ids
    else:
        q_seg = kv_seg = segment_ids
    q_seg = jnp.asarray(q_seg, jnp.int32)
    kv_seg = jnp.asarray(kv_seg, jnp.int32)
    if q_seg.shape != (b, sq) or kv_seg.shape != (b, sk):
        raise ValueError(f"segment_ids shapes {q_seg.shape}/{kv_seg.shape} "
                         f"do not match (b={b}, sq={sq}, sk={sk})")
    return q_seg, kv_seg


def pallas_supported(q, k, v, attn_mask, dropout_p, causal=False,
                     block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                     segment_ids=None, interpret=False) -> bool:
    """Static-shape gate encoding the Mosaic lowering rules for OUR block
    layout (the round-2 failure was selecting configs Mosaic rejects):
    blocks are [1, 1, block, d] over [b, h, s, d] arrays, so block_q/block_k
    need 8-alignment (sublane dim of the q/kv tiles), and when segment ids
    are present block_k additionally needs 128-alignment or to equal sk
    (it is the LANE dim of the kv-segment tile). ``interpret`` relaxes the
    alignment rules (no Mosaic involved) so CPU tests can run small blocks."""
    from ..registry import pallas_disabled
    if not _HAS_PLTPU or pallas_disabled():
        return False
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    bq, bk = min(block_q, sq), min(block_k, sk)
    # causal with sq > sk would leave fully-masked query rows whose
    # online-softmax state never initializes — keep those on the XLA path
    ok = (attn_mask is None
          and 0.0 <= dropout_p < 1.0
          and sq % bq == 0 and sk % bk == 0
          and not (causal and sq > sk)
          and h % h_kv == 0)
    if not ok:
        return False
    if interpret:
        return True
    ok = (bq % 8 == 0 and bk % 8 == 0 and d in (32, 64, 128, 256))
    if ok and segment_ids is not None:
        ok = bk % 128 == 0 or bk == sk
    return ok


@functools.lru_cache(maxsize=1)
def _tpu_lowering_ok() -> bool:
    """One-shot compile probe on the real backend: if the representative
    kernel fails Mosaic lowering (driver env drift, jax upgrade), dispatch
    degrades to the XLA path instead of poisoning every downstream jit
    (round-2: one lowering error zeroed the whole bench)."""
    from ..registry import backend_kind
    if backend_kind() != "tpu":
        return False
    try:
        q = jax.ShapeDtypeStruct((1, 256, 4, 128), jnp.bfloat16)
        jax.jit(functools.partial(
            _flash_attention, dropout_p=0.0, scale=0.088, causal=True,
            block_q=128, block_k=128, interpret=False)
        ).lower(q, q, q, None, None, None).compile()
        return True
    except Exception as e:  # pragma: no cover - only on env drift
        import warnings
        warnings.warn(f"Pallas flash attention failed TPU lowering; "
                      f"falling back to XLA attention: {e}")
        return False


def flash_attention_pallas(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                           causal: bool = False, scale: Optional[float] = None,
                           segment_ids=None,
                           block_q: Optional[int] = None,
                           block_k: Optional[int] = None,
                           interpret: bool = False,
                           dropout_seed=None):
    """TPU flash attention; falls back to the XLA path when unsupported.

    ``segment_ids`` ([b, s] ints, or a (q_seg, kv_seg) pair) restricts
    attention to equal-id positions — packed-sequence (varlen) and padding
    masking without a dense mask (reference varlen entry:
    flash_attn_kernel.cu:91).

    ``dropout_p`` > 0 runs IN-KERNEL dropout from a counter-based PRNG
    (reference: the philox dropout path of flash_attn_kernel.cu) — the
    O(s^2) keep-mask is regenerated block-wise in VMEM, never stored.
    ``dropout_seed`` (int or int32 array) pins the mask; defaults to the
    framework RNG stream.

    ``block_q``/``block_k`` default to the autotune database's choice for
    this (shape, dtype, device) — see ops/pallas/autotune.py and
    tools/tune_kernels.py (reference: phi/kernels/autotune/cache.h)."""
    from ..attention import _sdpa_xla
    if block_q is None or block_k is None:
        from .autotune import flash_attention_config
        tq, tk = flash_attention_config(q.shape[1], k.shape[1], q.shape[3],
                                        str(q.dtype), causal)
        block_q = block_q if block_q is not None else tq
        block_k = block_k if block_k is not None else tk
    supported = pallas_supported(q, k, v, attn_mask, dropout_p, causal,
                                 block_q, block_k, segment_ids=segment_ids,
                                 interpret=interpret)
    if supported and not interpret:
        supported = _tpu_lowering_ok()
    if not supported:
        if segment_ids is not None:
            # one shared segment->mask fold lives in _sdpa_xla
            segment_ids = _normalize_segments(segment_ids, q.shape[0],
                                              q.shape[1], k.shape[1])
        return _sdpa_xla(q, k, v, attn_mask=attn_mask, dropout_p=dropout_p,
                         causal=causal, scale=scale,
                         segment_ids=segment_ids)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    q_seg, kv_seg = _normalize_segments(segment_ids, q.shape[0], q.shape[1],
                                        k.shape[1])
    seed = None
    if dropout_p > 0.0:
        if dropout_seed is None:
            from ...core.rng import rng_tracker, GLOBAL_STREAM
            key = rng_tracker().next_key(GLOBAL_STREAM)
            seed = jax.random.randint(key, (1,), 0, 2**31 - 1, jnp.int32)
        else:
            seed = jnp.asarray(dropout_seed, jnp.int32).reshape((1,))
    return _flash_attention(q, k, v, q_seg, kv_seg, seed, dropout_p, scale,
                            causal, bq, bk, interpret)


@register_kernel("flash_attention", "tpu")
def _flash_attention_tpu(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                         causal: bool = False, scale: Optional[float] = None,
                         segment_ids=None):
    return flash_attention_pallas(q, k, v, attn_mask=attn_mask,
                                  dropout_p=dropout_p, causal=causal,
                                  scale=scale, segment_ids=segment_ids)


# ---------------------------------------------------------------------------
# block-level entry points (building blocks for ring attention — the ring
# composes per-device flash blocks and hand-writes the ring VJP, so it needs
# the raw fwd (with lse) and bwd kernels rather than the custom_vjp wrapper)
# ---------------------------------------------------------------------------

def flash_fwd_block(q, k, v, scale, causal, block_q, block_k,
                    interpret=False, q_seg=None, kv_seg=None):
    """Forward flash block returning (out [b,sq,h,d], lse [b,h,sq]).

    ``q_seg`` [b, sq] / ``kv_seg`` [b, sk] restrict attention to
    equal-id positions (the ring's packed-sequence path); a q row whose
    segment has no match in this kv block comes back with lse=NEG_INF,
    which the ring's normalized merge treats as weight zero."""
    return _fwd(q, k, v, q_seg, kv_seg, None, 0.0, scale, causal,
                block_q, block_k, interpret)


def flash_bwd_block(q, k, v, out, lse, dout, scale, causal, block_q, block_k,
                    interpret=False, q_seg=None, kv_seg=None):
    """Backward flash block given the GLOBAL (out, lse) of the full
    attention (delta = rowsum(out*dout) is computed inside, as FA2 does).
    Returns (dq, dk, dv) for this q/kv block pair."""
    res = (q, k, v, q_seg, kv_seg, None, out, lse)
    outs = _bwd(0.0, scale, causal, block_q, block_k, interpret, res, dout)
    return outs[0], outs[1], outs[2]
