"""Pallas TPU flash attention (forward + backward).

Reference analogue: paddle/phi/kernels/gpu/flash_attn_kernel.cu (FA2 via
dynload — flash_attn_fwd/bwd) and its python surface
python/paddle/nn/functional/flash_attention.py. Re-designed for the TPU
memory hierarchy instead of translated: the kernel streams K/V blocks
through VMEM with the online-softmax recurrence (running max m, denominator
l) carried in VMEM scratch across the innermost sequential grid dimension,
keeping the [sq, sk] score matrix out of HBM entirely; fp32 accumulation on
the MXU via preferred_element_type.

Layout: q [b, sq, h, d], k/v [b, sk, h_kv, d] (GQA: h_kv <= h, mapped via
BlockSpec index arithmetic — no materialized head expansion in the forward).
Backward = two kernels (dq; dk+dv) using the saved per-row logsumexp, plus a
delta = rowsum(out * dout) precomputed in XLA.

Falls back to the XLA composition (ops/attention.py) for dropout, arbitrary
masks, or block-indivisible sequence lengths.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports cleanly on TPU-enabled jaxlib builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..registry import register_kernel


def _tpu_params(*semantics):
    """Megacore: mark independent grid dims parallel; only the innermost
    (k/q accumulation) dim is sequential ("arbitrary")."""
    if pltpu is None:
        return None
    return pltpu.CompilerParams(dimension_semantics=tuple(semantics))

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30  # large-negative instead of -inf: avoids inf-inf=nan in exp


def _block_spec(shape, index_map):
    return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, sq, sk,
                block_q, block_k):
    """Grid: (b, h, nq, nk) — nk innermost/sequential; scratch carries the
    online-softmax state across nk iterations."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip blocks strictly above the diagonal (bottom-right aligned)
    offset = sk - sq
    first_masked_col = qi * block_q + offset + block_q  # col >= this is masked

    @pl.when(jnp.logical_not(causal) | (ki * block_k < first_masked_col))
    def _compute():
        q = q_ref[0, :, 0, :]                      # [bq, d]
        k = k_ref[0, :, 0, :]                      # [bk, d]
        v = v_ref[0, :, 0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (cols + ki * block_k) <= (rows + qi * block_q + offset)
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        p = jnp.exp(s - m_new)                     # [bq, bk]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, :, 0, :] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, :] = (m_scr[:, 0] + jnp.log(safe_l[:, 0]))


def _fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    nq = sq // block_q
    nk = sk // block_k
    grid = (b, h, nq, nk)

    q_spec = _block_spec((1, block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0))
    kv_spec = _block_spec((1, block_k, 1, d),
                          lambda bi, hi, qi, ki: (bi, ki, hi // group, 0))
    o_spec = _block_spec((1, block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0))
    lse_spec = _block_spec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, hi, qi))

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               sq=sq, sk=sk, block_q=block_q, block_k=block_k)
    scratch = [pltpu.VMEM((block_q, 128), jnp.float32),
               pltpu.VMEM((block_q, 128), jnp.float32),
               pltpu.VMEM((block_q, d), jnp.float32)]
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[o_spec, lse_spec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((b, h, sq), jnp.float32)],
        scratch_shapes=scratch,
        compiler_params=_tpu_params("parallel", "parallel", "parallel",
                                    "arbitrary"),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, causal, sq, sk, block_q, block_k):
    """Grid (b, h, nq, nk): accumulate dq over kv blocks."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    offset = sk - sq
    first_masked_col = qi * block_q + offset + block_q

    @pl.when(jnp.logical_not(causal) | (ki * block_k < first_masked_col))
    def _compute():
        q = q_ref[0, :, 0, :]
        k = k_ref[0, :, 0, :]
        v = v_ref[0, :, 0, :]
        do = do_ref[0, :, 0, :]
        lse = lse_ref[0, 0, :][:, None]            # [bq, 1]
        delta = delta_ref[0, 0, :][:, None]        # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (cols + ki * block_k) <= (rows + qi * block_q + offset)
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, :, 0, :] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, sq, sk,
                    block_q, block_k):
    """Grid (b, h, nk, nq): accumulate dk/dv over q blocks (per q-head; the
    caller group-sums to kv heads)."""
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    offset = sk - sq
    # causal: this (ki, qi) pair contributes unless the whole block is masked:
    # masked iff min col in block > max row+offset in block
    max_row = qi * block_q + block_q - 1 + offset

    @pl.when(jnp.logical_not(causal) | (ki * block_k <= max_row))
    def _compute():
        q = q_ref[0, :, 0, :]
        k = k_ref[0, :, 0, :]
        v = v_ref[0, :, 0, :]
        do = do_ref[0, :, 0, :]
        lse = lse_ref[0, 0, :][:, None]
        delta = delta_ref[0, 0, :][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (cols + ki * block_k) <= (rows + qi * block_q + offset)
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale              # [bq, bk]
        dk_scr[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, :, 0, :] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, :, 0, :] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, interpret, res, dout):
    q, k, v, out, lse = res
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    delta = jnp.sum(out.astype(jnp.float32) * dout.astype(jnp.float32),
                    axis=-1)                        # [b, sq, h]
    delta = jnp.moveaxis(delta, -1, 1)              # [b, h, sq]

    nq, nk = sq // block_q, sk // block_k
    q_spec = _block_spec((1, block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0))
    kv_spec = _block_spec((1, block_k, 1, d),
                          lambda bi, hi, qi, ki: (bi, ki, hi // group, 0))
    lse_spec = _block_spec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, hi, qi))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          sq=sq, sk=sk, block_q=block_q, block_k=block_k),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, lse_spec, lse_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_tpu_params("parallel", "parallel", "parallel",
                                    "arbitrary"),
        interpret=interpret,
    )(q, k, v, dout, lse, delta)[0]

    # dk/dv at q-head resolution; kv blocks indexed per q-head
    q_spec2 = _block_spec((1, block_q, 1, d), lambda bi, hi, ki, qi: (bi, qi, hi, 0))
    kv_spec2 = _block_spec((1, block_k, 1, d),
                           lambda bi, hi, ki, qi: (bi, ki, hi // group, 0))
    kvout_spec = _block_spec((1, block_k, 1, d),
                             lambda bi, hi, ki, qi: (bi, ki, hi, 0))
    lse_spec2 = _block_spec((1, 1, block_q), lambda bi, hi, ki, qi: (bi, hi, qi))
    dk_full, dv_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          sq=sq, sk=sk, block_q=block_q, block_k=block_k),
        grid=(b, h, nk, nq),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, lse_spec2, lse_spec2],
        out_specs=[kvout_spec, kvout_spec],
        out_shape=[jax.ShapeDtypeStruct((b, sk, h, d), jnp.float32),
                   jax.ShapeDtypeStruct((b, sk, h, d), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_tpu_params("parallel", "parallel", "parallel",
                                    "arbitrary"),
        interpret=interpret,
    )(q, k, v, dout, lse, delta)

    if group > 1:  # GQA: sum grads over the query-head group
        dk_full = dk_full.reshape(b, sk, h_kv, group, d).sum(axis=3)
        dv_full = dv_full.reshape(b, sk, h_kv, group, d).sum(axis=3)
    return dq, dk_full.astype(k.dtype), dv_full.astype(v.dtype)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, interpret, res, dout):
    return _bwd(scale, causal, block_q, block_k, interpret, res, dout)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def pallas_supported(q, k, v, attn_mask, dropout_p, causal=False,
                     block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K) -> bool:
    if not _HAS_PLTPU:
        return False
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    bq, bk = min(block_q, sq), min(block_k, sk)
    # block sizes must be sublane-aligned (fp32 min tile 8x128) and divide
    # seq; causal with sq > sk would leave fully-masked query rows whose
    # online-softmax state never initializes — keep those on the XLA path
    return (attn_mask is None and dropout_p == 0.0
            and bq % 8 == 0 and bk % 8 == 0
            and sq % bq == 0 and sk % bk == 0
            and not (causal and sq > sk)
            and h % h_kv == 0 and d in (32, 64, 128, 256))


def flash_attention_pallas(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                           causal: bool = False, scale: Optional[float] = None,
                           block_q: int = DEFAULT_BLOCK_Q,
                           block_k: int = DEFAULT_BLOCK_K,
                           interpret: bool = False):
    """TPU flash attention; falls back to the XLA path when unsupported."""
    from ..attention import _sdpa_xla
    if not pallas_supported(q, k, v, attn_mask, dropout_p, causal,
                            block_q, block_k):
        return _sdpa_xla(q, k, v, attn_mask=attn_mask, dropout_p=dropout_p,
                         causal=causal, scale=scale)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    return _flash_attention(q, k, v, scale, causal, bq, bk, interpret)


@register_kernel("flash_attention", "tpu")
def _flash_attention_tpu(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                         causal: bool = False, scale: Optional[float] = None):
    return flash_attention_pallas(q, k, v, attn_mask=attn_mask,
                                  dropout_p=dropout_p, causal=causal,
                                  scale=scale)
