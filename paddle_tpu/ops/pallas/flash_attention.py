"""Pallas TPU flash attention (forward + backward), with segment support.

Reference analogue: paddle/phi/kernels/gpu/flash_attn_kernel.cu (FA2 via
dynload — flash_attn_fwd/bwd, incl. the varlen entry at :91) and its python
surface python/paddle/nn/functional/flash_attention.py. Re-designed for the
TPU memory hierarchy instead of translated: the kernel streams K/V blocks
through VMEM with the online-softmax recurrence (running max m, denominator
l) carried in VMEM scratch across the innermost sequential grid dimension,
keeping the [sq, sk] score matrix out of HBM entirely; fp32 accumulation on
the MXU via preferred_element_type.

Layout: q [b, sq, h, d], k/v [b, sk, h_kv, d] (GQA: h_kv <= h, mapped via
BlockSpec index arithmetic — no materialized head expansion in the forward,
and dk/dv are accumulated AT KV-HEAD RESOLUTION inside the backward kernel
by folding the query-head group into the innermost sequential grid dim, so
no group-times-larger intermediate ever hits HBM).

Varlen / packed sequences: integer ``segment_ids`` ([b, sq] / [b, sk])
mask cross-segment attention inside the kernel — the TPU equivalent of the
reference's cu_seqlens varlen API (flash_attn_kernel.cu:91): pack multiple
sequences into one row, give each a distinct id (padding gets its own id).

Backward = two kernels (dq; dk+dv) using the saved per-row logsumexp, plus
a delta = rowsum(out * dout) precomputed in XLA.

Falls back to the XLA composition (ops/attention.py) for dropout, arbitrary
dense masks, or block-indivisible sequence lengths.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports cleanly on TPU-enabled jaxlib builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..registry import register_kernel


def _tpu_params(*semantics):
    """Megacore: mark independent grid dims parallel; only the innermost
    (k/q accumulation) dim is sequential ("arbitrary")."""
    if pltpu is None:
        return None
    return pltpu.CompilerParams(dimension_semantics=tuple(semantics))

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30  # large-negative instead of -inf: avoids inf-inf=nan in exp


def _block_spec(shape, index_map):
    return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)


def _mask_scores(s, causal, qseg, kseg, qi, ki, offset, block_q, block_k):
    """Apply causal and/or segment masking to a [bq, bk] score block."""
    mask = None
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = (cols + ki * block_k) <= (rows + qi * block_q + offset)
    if qseg is not None:
        seg = qseg[:, None] == kseg[None, :]
        mask = seg if mask is None else (mask & seg)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, scale, causal, has_seg, sq, sk, block_q, block_k):
    """Grid: (b, h, nq, nk) — nk innermost/sequential; scratch carries the
    online-softmax state across nk iterations."""
    if has_seg:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref,
         o_ref, lse_ref, m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        qs_ref = ks_ref = None
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip blocks strictly above the diagonal (bottom-right aligned)
    offset = sk - sq
    first_masked_col = qi * block_q + offset + block_q  # col >= this masked

    @pl.when(jnp.logical_not(causal) | (ki * block_k < first_masked_col))
    def _compute():
        q = q_ref[0, :, 0, :]                      # [bq, d]
        k = k_ref[0, :, 0, :]                      # [bk, d]
        v = v_ref[0, :, 0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        s = _mask_scores(s, causal,
                         qs_ref[0, :] if has_seg else None,
                         ks_ref[0, :] if has_seg else None,
                         qi, ki, offset, block_q, block_k)
        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        # masked entries must be EXACTLY zero even when the whole row is
        # masked (m_new == NEG_INF would make exp(s - m_new) = 1, turning
        # a fully-masked row into a mean over V)
        p = jnp.where(s <= NEG_INF * 0.5, 0.0,
                      jnp.exp(s - m_new))          # [bq, bk]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, :, 0, :] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, :] = (m_scr[:, 0] + jnp.log(safe_l[:, 0]))


def _fwd(q, k, v, q_seg, kv_seg, scale, causal, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    nq = sq // block_q
    nk = sk // block_k
    grid = (b, h, nq, nk)
    has_seg = q_seg is not None

    q_spec = _block_spec((1, block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0))
    kv_spec = _block_spec((1, block_k, 1, d),
                          lambda bi, hi, qi, ki: (bi, ki, hi // group, 0))
    o_spec = _block_spec((1, block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0))
    lse_spec = _block_spec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, hi, qi))

    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [q, k, v]
    if has_seg:
        in_specs += [
            _block_spec((1, block_q), lambda bi, hi, qi, ki: (bi, qi)),
            _block_spec((1, block_k), lambda bi, hi, qi, ki: (bi, ki))]
        inputs += [q_seg, kv_seg]

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               has_seg=has_seg, sq=sq, sk=sk,
                               block_q=block_q, block_k=block_k)
    scratch = [pltpu.VMEM((block_q, 128), jnp.float32),
               pltpu.VMEM((block_q, 128), jnp.float32),
               pltpu.VMEM((block_q, d), jnp.float32)]
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[o_spec, lse_spec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((b, h, sq), jnp.float32)],
        scratch_shapes=scratch,
        compiler_params=_tpu_params("parallel", "parallel", "parallel",
                                    "arbitrary"),
        interpret=interpret,
    )(*inputs)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(*refs, scale, causal, has_seg, sq, sk, block_q, block_k):
    """Grid (b, h, nq, nk): accumulate dq over kv blocks."""
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dq_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_scr) = refs
        qs_ref = ks_ref = None
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    offset = sk - sq
    first_masked_col = qi * block_q + offset + block_q

    @pl.when(jnp.logical_not(causal) | (ki * block_k < first_masked_col))
    def _compute():
        q = q_ref[0, :, 0, :]
        k = k_ref[0, :, 0, :]
        v = v_ref[0, :, 0, :]
        do = do_ref[0, :, 0, :]
        lse = lse_ref[0, 0, :][:, None]            # [bq, 1]
        delta = delta_ref[0, 0, :][:, None]        # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, causal,
                         qs_ref[0, :] if has_seg else None,
                         ks_ref[0, :] if has_seg else None,
                         qi, ki, offset, block_q, block_k)
        # masked entries exactly zero (a fully-masked row has lse=NEG_INF;
        # exp(NEG_INF - NEG_INF) = 1 would corrupt dq/dk/dv)
        p = jnp.where(s <= NEG_INF * 0.5, 0.0,
                      jnp.exp(s - lse))            # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, :, 0, :] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, has_seg, sq, sk, block_q, block_k,
                    group, nq):
    """Grid (b, h_kv, nk, nq*group): accumulate dk/dv at KV-HEAD resolution.

    The innermost sequential dim enumerates (query-head-in-group, q-block)
    pairs, so the GQA group sum happens in the VMEM accumulator instead of
    as a group-times-larger fp32 intermediate in HBM (round-1 weak item:
    FA2 accumulates at kv-head resolution; flash_attn_kernel.cu)."""
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        qs_ref = ks_ref = None
    ki = pl.program_id(2)
    qg = pl.program_id(3)
    nqg = pl.num_programs(3)
    qi = qg % nq          # q-block index (group-major enumeration)

    @pl.when(qg == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    offset = sk - sq
    # causal: this (ki, qi) pair contributes unless the whole block is
    # masked: masked iff min col in block > max row+offset in block
    max_row = qi * block_q + block_q - 1 + offset

    @pl.when(jnp.logical_not(causal) | (ki * block_k <= max_row))
    def _compute():
        q = q_ref[0, :, 0, :]
        k = k_ref[0, :, 0, :]
        v = v_ref[0, :, 0, :]
        do = do_ref[0, :, 0, :]
        lse = lse_ref[0, 0, :][:, None]
        delta = delta_ref[0, 0, :][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, causal,
                         qs_ref[0, :] if has_seg else None,
                         ks_ref[0, :] if has_seg else None,
                         qi, ki, offset, block_q, block_k)
        # masked entries exactly zero (a fully-masked row has lse=NEG_INF;
        # exp(NEG_INF - NEG_INF) = 1 would corrupt dq/dk/dv)
        p = jnp.where(s <= NEG_INF * 0.5, 0.0,
                      jnp.exp(s - lse))            # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale              # [bq, bk]
        dk_scr[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(qg == nqg - 1)
    def _finalize():
        dk_ref[0, :, 0, :] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, :, 0, :] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, interpret, res, dout):
    q, k, v, q_seg, kv_seg, out, lse = res
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    has_seg = q_seg is not None
    delta = jnp.sum(out.astype(jnp.float32) * dout.astype(jnp.float32),
                    axis=-1)                        # [b, sq, h]
    delta = jnp.moveaxis(delta, -1, 1)              # [b, h, sq]

    nq, nk = sq // block_q, sk // block_k
    q_spec = _block_spec((1, block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0))
    kv_spec = _block_spec((1, block_k, 1, d),
                          lambda bi, hi, qi, ki: (bi, ki, hi // group, 0))
    lse_spec = _block_spec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, hi, qi))

    dq_inputs = [q, k, v, dout, lse, delta]
    dq_specs = [q_spec, kv_spec, kv_spec, q_spec, lse_spec, lse_spec]
    if has_seg:
        dq_specs += [
            _block_spec((1, block_q), lambda bi, hi, qi, ki: (bi, qi)),
            _block_spec((1, block_k), lambda bi, hi, qi, ki: (bi, ki))]
        dq_inputs += [q_seg, kv_seg]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, sq=sq, sk=sk,
                          block_q=block_q, block_k=block_k),
        grid=(b, h, nq, nk),
        in_specs=dq_specs,
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_tpu_params("parallel", "parallel", "parallel",
                                    "arbitrary"),
        interpret=interpret,
    )(*dq_inputs)[0]

    # dk/dv accumulated at kv-head resolution: grid (b, h_kv, nk, nq*group);
    # the q-head for inner index qg is hkv*group + qg//nq (group-major)
    q_spec2 = _block_spec(
        (1, block_q, 1, d),
        lambda bi, hi, ki, qg: (bi, qg % nq, hi * group + qg // nq, 0))
    kv_spec2 = _block_spec((1, block_k, 1, d),
                           lambda bi, hi, ki, qg: (bi, ki, hi, 0))
    kvout_spec = _block_spec((1, block_k, 1, d),
                             lambda bi, hi, ki, qg: (bi, ki, hi, 0))
    lse_spec2 = _block_spec(
        (1, 1, block_q),
        lambda bi, hi, ki, qg: (bi, hi * group + qg // nq, qg % nq))

    dkv_inputs = [q, k, v, dout, lse, delta]
    dkv_specs = [q_spec2, kv_spec2, kv_spec2, q_spec2, lse_spec2, lse_spec2]
    if has_seg:
        dkv_specs += [
            _block_spec((1, block_q), lambda bi, hi, ki, qg: (bi, qg % nq)),
            _block_spec((1, block_k), lambda bi, hi, ki, qg: (bi, ki))]
        dkv_inputs += [q_seg, kv_seg]

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, sq=sq, sk=sk, block_q=block_q,
                          block_k=block_k, group=group, nq=nq),
        grid=(b, h_kv, nk, nq * group),
        in_specs=dkv_specs,
        out_specs=[kvout_spec, kvout_spec],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_tpu_params("parallel", "parallel", "parallel",
                                    "arbitrary"),
        interpret=interpret,
    )(*dkv_inputs)

    if has_seg:
        # int cotangents are symbolically zero (float0) in jax
        import numpy as _np
        zseg = (_np.zeros(q_seg.shape, jax.dtypes.float0),
                _np.zeros(kv_seg.shape, jax.dtypes.float0))
    else:
        zseg = (None, None)
    return (dq, dk, dv) + zseg


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_attention(q, k, v, q_seg, kv_seg, scale, causal, block_q,
                     block_k, interpret):
    out, _ = _fwd(q, k, v, q_seg, kv_seg, scale, causal, block_q, block_k,
                  interpret)
    return out


def _flash_fwd_rule(q, k, v, q_seg, kv_seg, scale, causal, block_q, block_k,
                    interpret):
    out, lse = _fwd(q, k, v, q_seg, kv_seg, scale, causal, block_q, block_k,
                    interpret)
    return out, (q, k, v, q_seg, kv_seg, out, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, interpret, res, dout):
    return _bwd(scale, causal, block_q, block_k, interpret, res, dout)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _normalize_segments(segment_ids, b, sq, sk):
    """segment_ids: [b, s] (self-attn) or (q_seg [b, sq], kv_seg [b, sk])."""
    if segment_ids is None:
        return None, None
    if isinstance(segment_ids, (tuple, list)):
        q_seg, kv_seg = segment_ids
    else:
        q_seg = kv_seg = segment_ids
    q_seg = jnp.asarray(q_seg, jnp.int32)
    kv_seg = jnp.asarray(kv_seg, jnp.int32)
    if q_seg.shape != (b, sq) or kv_seg.shape != (b, sk):
        raise ValueError(f"segment_ids shapes {q_seg.shape}/{kv_seg.shape} "
                         f"do not match (b={b}, sq={sq}, sk={sk})")
    return q_seg, kv_seg


def pallas_supported(q, k, v, attn_mask, dropout_p, causal=False,
                     block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K) -> bool:
    if not _HAS_PLTPU:
        return False
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    bq, bk = min(block_q, sq), min(block_k, sk)
    # block sizes must be sublane-aligned (fp32 min tile 8x128) and divide
    # seq; causal with sq > sk would leave fully-masked query rows whose
    # online-softmax state never initializes — keep those on the XLA path
    return (attn_mask is None and dropout_p == 0.0
            and bq % 8 == 0 and bk % 8 == 0
            and sq % bq == 0 and sk % bk == 0
            and not (causal and sq > sk)
            and h % h_kv == 0 and d in (32, 64, 128, 256))


def flash_attention_pallas(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                           causal: bool = False, scale: Optional[float] = None,
                           segment_ids=None,
                           block_q: Optional[int] = None,
                           block_k: Optional[int] = None,
                           interpret: bool = False):
    """TPU flash attention; falls back to the XLA path when unsupported.

    ``segment_ids`` ([b, s] ints, or a (q_seg, kv_seg) pair) restricts
    attention to equal-id positions — packed-sequence (varlen) and padding
    masking without a dense mask (reference varlen entry:
    flash_attn_kernel.cu:91).

    ``block_q``/``block_k`` default to the autotune database's choice for
    this (shape, dtype, device) — see ops/pallas/autotune.py and
    tools/tune_kernels.py (reference: phi/kernels/autotune/cache.h)."""
    from ..attention import _sdpa_xla
    if block_q is None or block_k is None:
        from .autotune import flash_attention_config
        tq, tk = flash_attention_config(q.shape[1], k.shape[1], q.shape[3],
                                        str(q.dtype), causal)
        block_q = block_q if block_q is not None else tq
        block_k = block_k if block_k is not None else tk
    if not pallas_supported(q, k, v, attn_mask, dropout_p, causal,
                            block_q, block_k):
        if segment_ids is not None:
            q_seg, kv_seg = _normalize_segments(segment_ids, q.shape[0],
                                                q.shape[1], k.shape[1])
            seg_mask = (q_seg[:, :, None] == kv_seg[:, None, :])[:, None]
            if attn_mask is None:
                m = seg_mask
            elif attn_mask.dtype == jnp.bool_:
                m = attn_mask & seg_mask
            else:  # additive float mask: add a large-negative segment term
                m = attn_mask + jnp.where(seg_mask, 0.0, NEG_INF).astype(
                    attn_mask.dtype)
            return _sdpa_xla(q, k, v, attn_mask=m, dropout_p=dropout_p,
                             causal=causal, scale=scale)
        return _sdpa_xla(q, k, v, attn_mask=attn_mask, dropout_p=dropout_p,
                         causal=causal, scale=scale)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    q_seg, kv_seg = _normalize_segments(segment_ids, q.shape[0], q.shape[1],
                                        k.shape[1])
    return _flash_attention(q, k, v, q_seg, kv_seg, scale, causal, bq, bk,
                            interpret)


@register_kernel("flash_attention", "tpu")
def _flash_attention_tpu(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                         causal: bool = False, scale: Optional[float] = None,
                         segment_ids=None):
    return flash_attention_pallas(q, k, v, attn_mask=attn_mask,
                                  dropout_p=dropout_p, causal=causal,
                                  scale=scale, segment_ids=segment_ids)
