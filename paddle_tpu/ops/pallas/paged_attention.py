"""Pallas TPU paged-KV decode attention (vLLM-style PagedAttention).

Reference analogue: paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu (the paged decode kernel behind
incubate block_multihead_attention). TPU redesign: one Pallas kernel whose
grid walks each sequence's pages via a SCALAR-PREFETCHED block table — the
BlockSpec index_map reads the table to stream the right physical page from
HBM into VMEM, so the gather never materializes [B, max_pages*page_size]
in HBM (which is what the XLA composition's jnp.take does). Online softmax
(running max/denominator in VMEM scratch) across pages; the GQA query-head
group is processed together per kv head ([group, d] x [page, d] MXU
contractions).

Pool layout is HEAD-MAJOR: k/v pools are [H_kv, num_pages, page_size, D]
(round-3 fix). Mosaic requires each block's last two dims to be
(sublane, lane)-aligned or equal to the array dims, so the streamed page
block must be (page_size, D)-shaped in the trailing dims — the round-2
token-major layout [num_pages, page_size, H_kv, D] put (H_kv, D) last and
was rejected at lowering for any H_kv > 1. Head-major is also what the
page stream wants: consecutive pages of one kv head are contiguous.

Semantics match incubate.nn.functional.block_multihead_attention: scores
over positions 0..seq_len INCLUSIVE (the new token was just written at
offset seq_len).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


def _decode_kernel(*args, scale, page_size, group, n_fetch, quant):
    """Grid (B, H_kv, max_pages // n_fetch); innermost sequential over page
    GROUPS. Each step streams ``n_fetch`` (possibly scattered) pages via
    n_fetch independent block specs — one page per spec, since a single
    BlockSpec can only address one pool offset — amortizing the per-step
    grid/DMA-issue overhead that made the one-page-per-step version
    latency-bound (~8us/step measured on v5).

    ``quant``: int8 pools with per-page fp32 scales (ISSUE 17). The scale
    arrays ride in as two extra SCALAR-PREFETCH refs (SMEM, indexed by the
    physical page id the table already prefetches); int8 K/V pages widen
    to the query dtype in VMEM (int8 is exact in bf16) and the page's
    scale multiplies the f32 scores / weighted-V accumulator — the same
    epilogue placement as int8_matmul's _kernel, so the fused dequant
    costs one scalar multiply per page, not a dequantized page in HBM."""
    if quant:
        tables_ref, lens_ref, kscale_ref, vscale_ref, q_ref = args[:5]
        refs = args[5:]
    else:
        tables_ref, lens_ref, q_ref = args[:3]
        refs = args[3:]
        kscale_ref = vscale_ref = None
    k_refs = refs[:n_fetch]
    v_refs = refs[n_fetch:2 * n_fetch]
    o_ref = refs[2 * n_fetch]
    m_scr, l_scr, acc_scr = refs[2 * n_fetch + 1:]
    b = pl.program_id(0)
    pg = pl.program_id(2)
    npg = pl.num_programs(2)
    seq_len = lens_ref[b]

    @pl.when(pg == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # group fully past the sequence (and unmapped table slots) is skipped
    @pl.when(pg * n_fetch * page_size <= seq_len)
    def _compute():
        q = q_ref[0, 0, :, :]                     # [group, d]
        for i in range(n_fetch):
            p = pg * n_fetch + i
            k = k_refs[i][0, 0, :, :]             # [page, d]
            v = v_refs[i][0, 0, :, :]
            k_scale = scale
            if quant:
                pid = tables_ref[b, p]
                k = k.astype(q.dtype)             # widen int8 in VMEM
                v = v.astype(q.dtype)
                k_scale = scale * kscale_ref[pid]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * k_scale  # [grp, page]
            pos = p * page_size + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(pos <= seq_len, s, NEG_INF)
            m_prev = m_scr[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            pr = jnp.exp(s - m_new)
            l_scr[:] = jnp.broadcast_to(
                alpha * l_scr[:, :1] + jnp.sum(pr, axis=-1, keepdims=True),
                l_scr.shape)
            pv = jax.lax.dot_general(
                pr.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if quant:
                pv = pv * vscale_ref[pid]
            acc_scr[:] = acc_scr[:] * alpha + pv
            m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(pg == npg - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, block_tables, seq_lens,
                           scale: Optional[float] = None,
                           k_scales=None, v_scales=None,
                           interpret: bool = False):
    """One decode step of attention over a paged KV cache.

    q:            [B, H, D] — the new token's queries
    k/v_pages:    [H_kv, num_pages, page_size, D] head-major block pools
    block_tables: [B, max_pages] int32; logical page i -> pool id (-1 unused)
    seq_lens:     [B] int32 tokens already cached (new token at this offset)
    k/v_scales:   [num_pages] fp32 per-page dequant scales for int8 pools
                  (both or neither; ISSUE 17)

    Returns [B, H, D].
    """
    B, H, D = q.shape
    H_kv, num_pages, page_size, _ = k_pages.shape
    max_pages = block_tables.shape[1]
    group = H // H_kv
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    quant = k_scales is not None
    if quant != (v_scales is not None):
        raise ValueError("k_scales and v_scales must be given together")
    # pages streamed per grid step (divisor of max_pages)
    n_fetch = next((n for n in (8, 4, 2, 1) if max_pages % n == 0), 1)

    tables = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)
    lens = jnp.asarray(seq_lens, jnp.int32)
    qg = q.reshape(B, H_kv, group, D)
    n_pref = 4 if quant else 2

    def page_spec(i):
        # index maps receive all scalar-prefetch refs after the grid ids;
        # only the table is read (scales are consumed in the kernel body)
        return pl.BlockSpec(
            (1, 1, page_size, D),
            lambda b, h, pg, tables, *rest, i=i: (
                h, tables[b, pg * n_fetch + i], 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_pref,
        grid=(B, H_kv, max_pages // n_fetch),
        in_specs=[
            pl.BlockSpec((1, 1, group, D),
                         lambda b, h, pg, *rest: (b, h, 0, 0)),
            *[page_spec(i) for i in range(n_fetch)],
            *[page_spec(i) for i in range(n_fetch)],
        ],
        out_specs=pl.BlockSpec((1, 1, group, D),
                               lambda b, h, pg, *rest: (b, h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((group, 128), jnp.float32),
                        pltpu.VMEM((group, 128), jnp.float32),
                        pltpu.VMEM((group, D), jnp.float32)],
    )
    prefetch = (tables, lens)
    if quant:
        prefetch += (jnp.asarray(k_scales, jnp.float32),
                     jnp.asarray(v_scales, jnp.float32))
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, page_size=page_size,
                          group=group, n_fetch=n_fetch, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H_kv, group, D), q.dtype),
        compiler_params=_tpu_params(),
        interpret=interpret,
    )(*prefetch, qg, *([k_pages] * n_fetch), *([v_pages] * n_fetch))
    return out.reshape(B, H, D)


def _tpu_params():
    if pltpu is None:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def paged_decode_xla(q, k_pages, v_pages, block_tables, seq_lens,
                     scale: Optional[float] = None,
                     k_scales=None, v_scales=None):
    """XLA gather composition with identical semantics to the kernel —
    the fallback for unsupported shapes/backends and the test oracle.
    Int8 pools (``k_scales``/``v_scales`` [num_pages]) dequantize in the
    gather: convert + per-page scale."""
    B, H, D = q.shape
    H_kv, _, page_size, _ = k_pages.shape
    T = block_tables.shape[1] * page_size
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    safe = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)

    def gather(pages, pscales):
        g = pages[:, safe]                    # [H_kv, B, mp, page, D]
        if pscales is not None:
            g = (g.astype(jnp.float32)
                 * pscales[safe][None, :, :, None, None])
        return jnp.moveaxis(g.reshape(H_kv, B, T, D), 0, 2)
    ks = gather(k_pages, k_scales)
    vs = gather(v_pages, v_scales)
    ks = jnp.repeat(ks, H // H_kv, axis=2)
    vs = jnp.repeat(vs, H // H_kv, axis=2)
    lens = jnp.asarray(seq_lens, jnp.int32)
    lg = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                    ks.astype(jnp.float32)) * scale
    lg = jnp.where(jnp.arange(T)[None, None, :] <= lens[:, None, None],
                   lg, -jnp.inf)
    p = jax.nn.softmax(lg, axis=-1)
    out = jnp.einsum("bht,bthd->bhd", p, vs.astype(jnp.float32))
    return out.astype(q.dtype)


_FORCED_IMPL = [None]  # None = auto; "dense" | "paged" (context-aware dispatch)


class force_decode_impl:
    """Trace-time override of the paged-decode attention path.

    ``"dense"`` routes decode through the XLA gather composition
    (``paged_decode_xla`` — the dense contiguous-attention cost profile),
    ``"paged"``/None keeps the auto choice (Pallas kernel on TPU when
    supported). The serving engine wraps each decode-block TRACE in this
    scope to bake the measured dense/paged crossover into the executable
    (inference/serving.py; crossover from autotune.paged_decode_crossover):
    the bench sweep shows dense ahead at short contexts and the paged
    kernel 1.45-3.6x ahead at 8K-16K, so one static choice per compiled
    block is exactly the right granularity."""

    def __init__(self, impl):
        if impl not in (None, "dense", "paged"):
            raise ValueError(f"impl must be None|'dense'|'paged', "
                             f"got {impl!r}")
        self.impl = impl

    def __enter__(self):
        _FORCED_IMPL.append(self.impl)
        return self

    def __exit__(self, *exc):
        _FORCED_IMPL.pop()
        return False


def forced_decode_impl():
    return _FORCED_IMPL[-1]


def paged_decode_supported(q, k_pages) -> bool:
    """Mosaic-rule gate for the head-major pool layout: page blocks are
    (1, 1, page_size, D) == the trailing array dims, and the q/out blocks
    are (1, 1, group, D) == theirs, so only divisibility and a sane D
    remain to check."""
    from ..registry import pallas_disabled
    if not _HAS_PLTPU or pallas_disabled():
        return False
    B, H, D = q.shape
    H_kv = k_pages.shape[0]
    page_size = k_pages.shape[2]
    # int8 pages need the int8 sublane multiple (32); floats need 8
    sublane = 32 if k_pages.dtype == jnp.int8 else 8
    return (H % H_kv == 0 and D in (32, 64, 128, 256)
            and page_size % sublane == 0)


__all__ = ["paged_decode_attention", "paged_decode_supported",
           "paged_decode_xla", "force_decode_impl", "forced_decode_impl"]
