"""Fused vocab-projection + cross-entropy loss head (logits never exist).

Reference analogue: c_softmax_with_cross_entropy_op.cu (the reference fuses
the softmax+CE over model-parallel-sharded logits); prior art for the FULL
fusion — projection INCLUDED — is Liger-kernel's fused_linear_cross_entropy
and Apple's Cut Cross-Entropy. At Llama-3's 128K vocab the fp32 logits
tensor ``[B, S, V]`` is the single largest activation of a training step
(B*S*128256*4 bytes); even the tensor-parallel CE path only shards it. This
module computes

    loss = CE(hidden @ W, labels)

blockwise over the vocab dimension so the logits tensor NEVER materializes:
peak loss-head memory drops from O(N*V) to O(N*block_v) with N = B*S.

Design:

- The primitive is ``lse_and_target(hidden, w, labels) -> (lse, tgt)``:
  per-row log-sum-exp of the logits and the logit at the label (0 when the
  label is outside ``[0, V)`` — which encodes both ignore_index and a TP
  shard's out-of-range labels with one rule). ``nll = lse - tgt``; any
  reduction/weighting composes outside, and the TP composition in
  parallel/mp_layers.py combines per-shard (lse, tgt) with pmax/psum.
- Forward: online log-sum-exp over vocab blocks (running max m, running
  denominator s — the flash-attention recurrence applied to the class dim)
  plus a masked target-logit accumulation, fp32 throughout.
- Backward (custom_vjp): RECOMPUTES each block's logits from the saved
  per-row lse — softmax p = exp(logits - lse) — and accumulates
  ``dhidden += dlog @ W_j^T`` and ``dW_j = hidden^T @ dlog`` with
  ``dlog = g_lse * p + g_tgt * onehot``. One extra blockwise matmul versus
  the naive backward buys O(block) memory.
- Two interchangeable implementations behind one numerics contract:
  a Pallas TPU kernel set (forward; dhidden; dW — each streaming vocab
  blocks through VMEM with fp32 scratch accumulators) and a pure-XLA
  ``lax.scan`` over vocab blocks that keeps the same O(block) memory on
  CPU/GPU and is the test oracle. ``ops/pallas/autotune.py`` picks block
  sizes (TuneDB-consulted like flash_attention).

Vocab not divisible by the block size: W is padded to the block multiple
and padded columns are masked to NEG_INF inside the kernels (their softmax
weight is exactly 0 in the backward recompute).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu only imports cleanly on TPU-enabled jaxlib builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..registry import register_kernel

NEG_INF = -1e30  # large-negative instead of -inf: avoids inf-inf=nan in exp
LANES = 8        # lane width for per-row scalars (lse/tgt/labels tiles)


def _tpu_params(*semantics):
    if pltpu is None:
        return None
    return pltpu.CompilerParams(dimension_semantics=tuple(semantics))


def _block_spec(shape, index_map):
    return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)


def _pad_vocab(w, block_v: int):
    """Pad W's vocab (last) dim up to a block multiple; padded columns are
    masked in-kernel so they contribute exactly 0."""
    v = w.shape[-1]
    vp = -(-v // block_v) * block_v
    if vp == v:
        return w
    return jnp.pad(w, ((0, 0), (0, vp - v)))


# ---------------------------------------------------------------------------
# XLA fallback: lax.scan over vocab blocks (same O(block_v) memory)
# ---------------------------------------------------------------------------

def _fwd_xla(h, w, labels, block_v, unroll=False):
    n, hd = h.shape
    v = w.shape[1]
    wp = _pad_vocab(w, block_v)
    nb = wp.shape[1] // block_v

    def body(carry, j):
        m, s, t = carry
        wj = jax.lax.dynamic_slice(wp, (0, j * block_v), (hd, block_v))
        logits = jax.lax.dot_general(
            h, wj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [n, block_v]
        cols = j * block_v + jnp.arange(block_v, dtype=jnp.int32)[None, :]
        logits = jnp.where(cols < v, logits, NEG_INF)
        t = t + jnp.sum(jnp.where(cols == labels[:, None], logits, 0.0), -1)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.where(logits <= NEG_INF * 0.5, 0.0,
                      jnp.exp(logits - m_new[:, None]))
        s = s * jnp.exp(m - m_new) + jnp.sum(p, axis=-1)
        return (m_new, s, t), None

    carry = (jnp.full((n,), NEG_INF, jnp.float32),
             jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
    if unroll:
        # Python loop (no while op): required inside partial-auto
        # shard_map regions, whose SPMD partitioning rejects scan
        for j in range(nb):
            carry, _ = body(carry, jnp.int32(j))
    else:
        carry, _ = jax.lax.scan(body, carry,
                                jnp.arange(nb, dtype=jnp.int32))
    m, s, t = carry
    safe = jnp.where(s == 0.0, 1.0, s)
    return m + jnp.log(safe), t


def _bwd_xla(h, w, labels, lse, g_lse, g_tgt, block_v, unroll=False):
    n, hd = h.shape
    v = w.shape[1]
    wp = _pad_vocab(w, block_v)
    nb = wp.shape[1] // block_v

    def body(dh, j):
        wj = jax.lax.dynamic_slice(wp, (0, j * block_v), (hd, block_v))
        logits = jax.lax.dot_general(
            h, wj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        cols = j * block_v + jnp.arange(block_v, dtype=jnp.int32)[None, :]
        logits = jnp.where(cols < v, logits, NEG_INF)
        p = jnp.where(logits <= NEG_INF * 0.5, 0.0,
                      jnp.exp(logits - lse[:, None]))
        dlog = g_lse[:, None] * p \
            + jnp.where(cols == labels[:, None], g_tgt[:, None], 0.0)
        dh = dh + jax.lax.dot_general(
            dlog, wj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dwj = jax.lax.dot_general(
            h, dlog, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [hd, block_v]
        return dh, dwj.astype(w.dtype)

    dh0 = jnp.zeros((n, hd), jnp.float32)
    if unroll:
        dh, blocks = dh0, []
        for j in range(nb):
            dh, dwj = body(dh, jnp.int32(j))
            blocks.append(dwj)
        dw = jnp.concatenate(blocks, axis=1)[:, :v]
    else:
        dh, dw_blocks = jax.lax.scan(body, dh0,
                                     jnp.arange(nb, dtype=jnp.int32))
        dw = jnp.moveaxis(dw_blocks, 0, 1).reshape(hd, nb * block_v)[:, :v]
    return dh.astype(h.dtype), dw


# ---------------------------------------------------------------------------
# Pallas TPU kernels
# ---------------------------------------------------------------------------

def _lift_rows(x, dtype):
    """[n] per-row scalars -> lane-broadcast [n, LANES] tiles (Mosaic wants
    the last block dim aligned or equal to the array dim)."""
    return jnp.broadcast_to(jnp.asarray(x, dtype)[:, None],
                            (x.shape[0], LANES))


def _fwd_kernel(h_ref, w_ref, lab_ref, lse_ref, tgt_ref, m_scr, s_scr, t_scr,
                *, vocab, block_v):
    """Grid (nN, nV) — nV innermost/sequential; scratch carries the online
    log-sum-exp state (m, s) and the target-logit accumulator across it."""
    vi = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        s_scr[:] = jnp.zeros_like(s_scr)
        t_scr[:] = jnp.zeros_like(t_scr)

    h = h_ref[...]
    wb = w_ref[...]
    logits = jax.lax.dot_general(
        h, wb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bn, bv]
    cols = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(cols < vocab, logits, NEG_INF)
    lab = lab_ref[:, :1]                                 # [bn, 1]
    t_new = t_scr[:, :1] + jnp.sum(
        jnp.where(cols == lab, logits, 0.0), axis=-1, keepdims=True)
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    p = jnp.where(logits <= NEG_INF * 0.5, 0.0, jnp.exp(logits - m_new))
    s_new = jnp.exp(m_prev - m_new) * s_scr[:, :1] \
        + jnp.sum(p, axis=-1, keepdims=True)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    s_scr[:] = jnp.broadcast_to(s_new, s_scr.shape)
    t_scr[:] = jnp.broadcast_to(t_new, t_scr.shape)

    @pl.when(vi == nv - 1)
    def _finalize():
        s = s_scr[:, :1]
        safe = jnp.where(s == 0.0, 1.0, s)
        lse_ref[...] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(safe),
                                        lse_ref.shape)
        tgt_ref[...] = jnp.broadcast_to(t_scr[:, :1], tgt_ref.shape)


def _fwd_pallas(h, w, labels, block_n, block_v, interpret):
    n, hd = h.shape
    v = w.shape[1]
    wp = _pad_vocab(w, block_v)
    nb = wp.shape[1] // block_v
    nn = n // block_n
    lab2 = _lift_rows(labels, jnp.int32)

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, vocab=v, block_v=block_v),
        grid=(nn, nb),
        in_specs=[
            _block_spec((block_n, hd), lambda ni, vi: (ni, 0)),
            _block_spec((hd, block_v), lambda ni, vi: (0, vi)),
            _block_spec((block_n, LANES), lambda ni, vi: (ni, 0)),
        ],
        out_specs=[_block_spec((block_n, LANES), lambda ni, vi: (ni, 0)),
                   _block_spec((block_n, LANES), lambda ni, vi: (ni, 0))],
        out_shape=[jax.ShapeDtypeStruct((n, LANES), jnp.float32),
                   jax.ShapeDtypeStruct((n, LANES), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block_n, 128), jnp.float32),
                        pltpu.VMEM((block_n, 128), jnp.float32),
                        pltpu.VMEM((block_n, 128), jnp.float32)],
        compiler_params=_tpu_params("parallel", "arbitrary"),
        interpret=interpret,
    )(h, wp, lab2)
    return out[0][:, 0], out[1][:, 0]


def _dlog_block(h, wb, lab_ref, lse_ref, glse_ref, gtgt_ref, vi, vocab,
                block_v):
    """Recompute one [bn, bv] softmax block from the saved lse and form the
    logits cotangent dlog = g_lse * p + g_tgt * onehot (shared by the
    dhidden and dW backward kernels)."""
    logits = jax.lax.dot_general(
        h, wb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    cols = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(cols < vocab, logits, NEG_INF)
    p = jnp.where(logits <= NEG_INF * 0.5, 0.0,
                  jnp.exp(logits - lse_ref[:, :1]))
    return glse_ref[:, :1] * p + jnp.where(cols == lab_ref[:, :1],
                                           gtgt_ref[:, :1], 0.0)


def _bwd_dh_kernel(h_ref, w_ref, lab_ref, lse_ref, glse_ref, gtgt_ref,
                   dh_ref, acc_scr, *, vocab, block_v):
    """Grid (nN, nV): accumulate dhidden over vocab blocks."""
    vi = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    wb = w_ref[...]
    dlog = _dlog_block(h_ref[...], wb, lab_ref, lse_ref, glse_ref, gtgt_ref,
                       vi, vocab, block_v)
    acc_scr[:] += jax.lax.dot_general(
        dlog.astype(wb.dtype), wb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vi == nv - 1)
    def _finalize():
        dh_ref[...] = acc_scr[:].astype(dh_ref.dtype)


def _bwd_dw_kernel(h_ref, w_ref, lab_ref, lse_ref, glse_ref, gtgt_ref,
                   dw_ref, acc_scr, *, vocab, block_v):
    """Grid (nV, nN): accumulate dW at vocab-block resolution over rows."""
    vi = pl.program_id(0)
    ni = pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(ni == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    h = h_ref[...]
    dlog = _dlog_block(h, w_ref[...], lab_ref, lse_ref, glse_ref, gtgt_ref,
                       vi, vocab, block_v)
    acc_scr[:] += jax.lax.dot_general(
        h, dlog.astype(h.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ni == nn - 1)
    def _finalize():
        dw_ref[...] = acc_scr[:].astype(dw_ref.dtype)


def _bwd_pallas(h, w, labels, lse, g_lse, g_tgt, block_n, block_v, interpret):
    n, hd = h.shape
    v = w.shape[1]
    wp = _pad_vocab(w, block_v)
    vp = wp.shape[1]
    nb = vp // block_v
    nn = n // block_n
    lab2 = _lift_rows(labels, jnp.int32)
    lse2 = _lift_rows(lse, jnp.float32)
    glse2 = _lift_rows(g_lse, jnp.float32)
    gtgt2 = _lift_rows(g_tgt, jnp.float32)

    row_specs = [
        _block_spec((block_n, hd), lambda ni, vi: (ni, 0)),
        _block_spec((hd, block_v), lambda ni, vi: (0, vi)),
        _block_spec((block_n, LANES), lambda ni, vi: (ni, 0)),
        _block_spec((block_n, LANES), lambda ni, vi: (ni, 0)),
        _block_spec((block_n, LANES), lambda ni, vi: (ni, 0)),
        _block_spec((block_n, LANES), lambda ni, vi: (ni, 0)),
    ]
    dh = pl.pallas_call(
        functools.partial(_bwd_dh_kernel, vocab=v, block_v=block_v),
        grid=(nn, nb),
        in_specs=row_specs,
        out_specs=[_block_spec((block_n, hd), lambda ni, vi: (ni, 0))],
        out_shape=[jax.ShapeDtypeStruct((n, hd), h.dtype)],
        scratch_shapes=[pltpu.VMEM((block_n, hd), jnp.float32)],
        compiler_params=_tpu_params("parallel", "arbitrary"),
        interpret=interpret,
    )(h, wp, lab2, lse2, glse2, gtgt2)[0]

    # dW: grid transposed (vocab blocks parallel, rows sequential) so the
    # [hd, block_v] fp32 accumulator lives in VMEM across the row sweep
    col_specs = [
        _block_spec((block_n, hd), lambda vi, ni: (ni, 0)),
        _block_spec((hd, block_v), lambda vi, ni: (0, vi)),
        _block_spec((block_n, LANES), lambda vi, ni: (ni, 0)),
        _block_spec((block_n, LANES), lambda vi, ni: (ni, 0)),
        _block_spec((block_n, LANES), lambda vi, ni: (ni, 0)),
        _block_spec((block_n, LANES), lambda vi, ni: (ni, 0)),
    ]
    dwp = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, vocab=v, block_v=block_v),
        grid=(nb, nn),
        in_specs=col_specs,
        out_specs=[_block_spec((hd, block_v), lambda vi, ni: (0, vi))],
        out_shape=[jax.ShapeDtypeStruct((hd, vp), w.dtype)],
        scratch_shapes=[pltpu.VMEM((hd, block_v), jnp.float32)],
        compiler_params=_tpu_params("parallel", "arbitrary"),
        interpret=interpret,
    )(h, wp, lab2, lse2, glse2, gtgt2)[0]
    return dh, dwp[:, :v]


# ---------------------------------------------------------------------------
# custom_vjp primitive
# ---------------------------------------------------------------------------

def _fwd_impl(h, w, labels, block_n, block_v, impl, interpret):
    if impl == "pallas":
        return _fwd_pallas(h, w, labels, block_n, block_v, interpret)
    return _fwd_xla(h, w, labels, block_v, unroll=(impl == "xla_unroll"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def lse_and_target(h, w, labels, block_n=128, block_v=512, impl="xla",
                   interpret=False):
    """Per-row (logsumexp(h @ w), logit-at-label) over vocab blocks.

    h: [N, H]; w: [H, V]; labels: [N] int32 — a label outside ``[0, V)``
    contributes 0 to ``tgt`` (encodes ignore_index and TP-shard-local
    out-of-range labels). Returns (lse [N] f32, tgt [N] f32); the logits
    tensor is never materialized, in either the forward or the recompute
    backward."""
    return _fwd_impl(h, w, labels, block_n, block_v, impl, interpret)


def _lse_fwd_rule(h, w, labels, block_n, block_v, impl, interpret):
    lse, tgt = _fwd_impl(h, w, labels, block_n, block_v, impl, interpret)
    return (lse, tgt), (h, w, labels, lse)


def _lse_bwd_rule(block_n, block_v, impl, interpret, res, g):
    h, w, labels, lse = res
    g_lse, g_tgt = g
    if impl == "pallas":
        dh, dw = _bwd_pallas(h, w, labels, lse, g_lse, g_tgt,
                             block_n, block_v, interpret)
    else:
        dh, dw = _bwd_xla(h, w, labels, lse, g_lse, g_tgt, block_v,
                          unroll=(impl == "xla_unroll"))
    # int labels: symbolically-zero (float0) cotangent
    dlab = np.zeros(labels.shape, jax.dtypes.float0)
    return dh, dw, dlab


lse_and_target.defvjp(_lse_fwd_rule, _lse_bwd_rule)


# ---------------------------------------------------------------------------
# support gates + public entry
# ---------------------------------------------------------------------------

VMEM_BUDGET = 14 * 2 ** 20


def kernel_vmem_bytes(block_n, block_v, hd, itemsize) -> int:
    """Worst-case per-kernel VMEM for one (block_n, block_v) config — the
    dW backward kernel is the pacer. The ONE formula shared by the support
    gate and the default block chooser (autotune.fused_vocab_ce_config):
    two inconsistent estimates would let the chooser pick configs the gate
    then rejects, silently routing every TPU call to the XLA fallback."""
    return (hd * block_v * 4                  # dW accumulator (fp32)
            + hd * block_v * itemsize         # W block
            + block_n * hd * (itemsize + 4)   # h block + dh accumulator
            + block_n * block_v * 4)          # dlog block


def default_blocks(n, hd, dtype_str) -> Tuple[Optional[int], int]:
    """VMEM-fitting (block_n, block_v) defaults: the largest row block
    dividing N (None → no Pallas), then the largest 128-multiple vocab
    block that keeps the shared estimate under budget, shrinking the row
    block if even bv=128 won't fit."""
    itemsize = {"float32": 4}.get(dtype_str, 2)
    for bn in (256, 128, 64, 32, 16, 8):
        if n % bn:
            continue
        bv = next((c for c in (2048, 1024, 512, 256, 128)
                   if kernel_vmem_bytes(bn, c, hd, itemsize)
                   <= VMEM_BUDGET), None)
        if bv is not None:
            return bn, bv
    return None, 512


def fused_ce_supported(n, hd, v, dtype, block_n, block_v,
                       interpret=False) -> bool:
    """Static gate encoding the Mosaic lowering rules for this block
    layout: row blocks are [block_n, H] (H is the full lane dim), vocab
    blocks [H, block_v]; the dW kernel's fp32 [H, block_v] accumulator is
    the VMEM pacer. ``interpret`` relaxes alignment so CPU tests can run
    tiny blocks."""
    from ..registry import pallas_disabled
    if not _HAS_PLTPU or pallas_disabled():
        return False
    if block_n is None or block_v is None:
        return False
    if n % block_n:
        return False
    if interpret:
        return True
    itemsize = jnp.dtype(dtype).itemsize
    return (block_n % 8 == 0 and block_v % 128 == 0 and hd % 128 == 0
            and kernel_vmem_bytes(block_n, block_v, hd, itemsize)
            <= VMEM_BUDGET)


@functools.lru_cache(maxsize=1)
def _tpu_lowering_ok() -> bool:
    """One-shot compile probe on the real backend (same rationale as
    flash_attention: degrade to the XLA path on env drift instead of
    poisoning every downstream jit)."""
    from ..registry import backend_kind
    if backend_kind() != "tpu":
        return False
    try:
        h = jax.ShapeDtypeStruct((128, 128), jnp.bfloat16)
        w = jax.ShapeDtypeStruct((128, 256), jnp.bfloat16)
        lab = jax.ShapeDtypeStruct((128,), jnp.int32)

        def probe(h, w, lab):
            # grad probes BOTH directions: the backward dh/dW kernels use
            # different grids (the dW grid is transposed) and larger
            # scratch, so a forward-only probe could pass while the first
            # train step still fails to lower
            lse, tgt = lse_and_target(h, w, lab, block_n=128, block_v=128,
                                      impl="pallas", interpret=False)
            return jnp.sum(lse) + jnp.sum(tgt)

        jax.jit(jax.grad(probe, argnums=(0, 1))).lower(h, w, lab).compile()
        return True
    except Exception as e:  # pragma: no cover - only on env drift
        import warnings
        warnings.warn(f"Pallas fused vocab-CE failed TPU lowering; "
                      f"falling back to the XLA blockwise path: {e}")
        return False


def resolve_impl(n, hd, v, dtype, block_n, block_v,
                 interpret=False) -> str:
    """'pallas' when the TPU kernel path is usable for these shapes (or
    interpret mode is forced), else 'xla'."""
    from ..registry import backend_kind
    if not fused_ce_supported(n, hd, v, dtype, block_n, block_v, interpret):
        return "xla"
    if interpret:
        return "pallas"
    if backend_kind() == "tpu" and _tpu_lowering_ok():
        return "pallas"
    return "xla"


def fused_linear_cross_entropy(hidden, w, labels, ignore_index: int = -100,
                               reduction: str = "mean",
                               block_n: Optional[int] = None,
                               block_v: Optional[int] = None,
                               impl: Optional[str] = None,
                               interpret: bool = False):
    """CE(hidden @ w, labels) without materializing the logits.

    hidden: [..., H]; w: [H, V]; labels: [...] int ids (``ignore_index``
    rows contribute 0 loss and don't count toward the mean). ``reduction``:
    'mean' (token-weighted, fp32 — the causal-LM head convention), 'sum',
    or 'none' (per-token nll, shaped like ``labels``).

    Numerically interchangeable with
    ``F.cross_entropy((hidden @ w).astype(f32), labels)`` to fp32
    tolerance; peak memory is O(N * block_v) instead of O(N * V)."""
    lead = hidden.shape[:-1]
    hd = hidden.shape[-1]
    v = w.shape[-1]
    n = int(np.prod(lead)) if lead else 1
    h2 = hidden.reshape(n, hd)
    lab = labels.reshape(n).astype(jnp.int32)
    valid = lab != ignore_index
    safe = jnp.where(valid, lab, -1)          # out of range -> tgt = 0
    if block_n is None or block_v is None:
        from .autotune import fused_vocab_ce_config
        tn, tv = fused_vocab_ce_config(n, hd, v, str(hidden.dtype))
        block_n = block_n if block_n is not None else tn
        block_v = block_v if block_v is not None else tv
    if impl is None:
        impl = resolve_impl(n, hd, v, hidden.dtype, block_n, block_v,
                            interpret)
    lse, tgt = lse_and_target(h2, w, safe, block_n, block_v, impl, interpret)
    nll = jnp.where(valid, lse - tgt, 0.0)
    if reduction == "none":
        return nll.reshape(lead)
    if reduction == "sum":
        return jnp.sum(nll)
    cnt = jnp.sum(valid.astype(jnp.float32))
    return jnp.sum(nll) / jnp.maximum(cnt, 1.0)


@register_kernel("fused_vocab_ce", "tpu")
def _fused_ce_tpu(hidden, w, labels, **kw):
    return fused_linear_cross_entropy(hidden, w, labels, **kw)


@register_kernel("fused_vocab_ce", "any")
def _fused_ce_any(hidden, w, labels, **kw):
    kw.setdefault("impl", "xla")
    return fused_linear_cross_entropy(hidden, w, labels, **kw)


__all__ = ["fused_linear_cross_entropy", "lse_and_target",
           "fused_ce_supported", "resolve_impl"]
