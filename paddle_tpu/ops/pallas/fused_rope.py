"""Pallas TPU fused rotary embedding (q and k in one kernel).

Reference analogue: paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu —
one kernel applies the rotation to q and k together so the cos/sin tables
cross HBM once.

TPU design: rope is pure VPU work and HBM-bandwidth-bound. The fusion win
over XLA is structural: one pallas_call reads cos/sin ONCE per sequence
block and rotates BOTH q and k tiles while they sit in VMEM, instead of
two elementwise fusions each re-reading the tables. Whether that beats
XLA's fusion on real hardware is an empirical question — bench.py records
pallas-vs-XLA timings (rope_pallas_us / rope_xla_us) and the dispatch
keeps the XLA path unless the kernel is enabled and eligible (training
layout, contiguous positions).

Layout: q,k [b, s, h, d] (d = head_dim, lane-aligned at 128/64); cos/sin
[s, d]. Grid over (b, s/block_s). position_ids path (gathered tables)
stays XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BLOCK_S = 512


def _rope_kernel(q_ref, k_ref, cos_ref, sin_ref, qo_ref, ko_ref):
    # half-sliced form: out1 = x1*c1 - x2*s1, out2 = x2*c2 + x1*s2
    # (identical to concat([-x2, x1]) rotate-half, but never materializes
    # the full-width rot/product temporaries — the concat form blew the
    # 16M scoped-vmem stack limit at block_s=512, h=16, d=128 on v5e)
    half = cos_ref.shape[-1] // 2
    c1 = cos_ref[:, :half][None, :, None, :]     # [1, bs, 1, d/2]
    c2 = cos_ref[:, half:][None, :, None, :]
    s1 = sin_ref[:, :half][None, :, None, :]
    s2 = sin_ref[:, half:][None, :, None, :]
    for ref, out in ((q_ref, qo_ref), (k_ref, ko_ref)):
        x1 = ref[..., :half].astype(jnp.float32)  # [1, bs, h, d/2]
        x2 = ref[..., half:].astype(jnp.float32)
        out[..., :half] = (x1 * c1 - x2 * s1).astype(out.dtype)
        out[..., half:] = (x2 * c2 + x1 * s2).astype(out.dtype)


def fused_rope_pallas(q, k, cos, sin, *, block_s: int = DEFAULT_BLOCK_S,
                      interpret: bool = False):
    """Rotate q and k ([b, s, h, d]) by cos/sin ([s, d]) in one kernel."""
    if not _HAS_PLTPU:
        raise ImportError("pallas.tpu unavailable; use the XLA rope path")
    b, s, h, d = q.shape
    assert k.shape[0] == b and k.shape[1] == s and k.shape[3] == d
    assert cos.shape == (s, d) and sin.shape == (s, d)
    hk = k.shape[2]
    block_s = _fit_block_s(min(block_s, s), h, hk, d)
    if s % block_s:
        raise ValueError(f"seq {s} does not divide block_s {block_s}")
    grid = (b, s // block_s)
    cf = jnp.float32

    qo, ko = pl.pallas_call(
        _rope_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_s, h, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, block_s, hk, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((block_s, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_s, d), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_s, h, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, block_s, hk, d), lambda i, j: (i, j, 0, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct(k.shape, k.dtype)],
        compiler_params=(pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"))
            if not interpret else None),
        interpret=interpret,
    )(q, k, cos.astype(cf), sin.astype(cf))
    return qo, ko


_VMEM_BUDGET = 12 * 2**20  # leave headroom under the 16M scoped-vmem limit


def _fit_block_s(block_s: int, h: int, hk: int, d: int) -> int:
    """Largest power-of-two block_s whose VMEM working set fits.

    Per sequence position: q+k blocks in and out (bf16, double-buffered by
    Mosaic) plus the f32 half-width temporaries the kernel body creates
    (~3 live full-width-f32-equivalents per tensor) plus cos/sin (f32).
    Estimate ~= block_s * [(h+hk)*d*(2B*2*2 + 4B*3) + 2*d*4B]."""
    per_s = (h + hk) * d * (2 * 2 * 2 + 4 * 3) + 2 * d * 4
    while block_s > 8 and block_s * per_s > _VMEM_BUDGET:
        block_s //= 2
    return block_s


def rope_supported(q_shape, k_shape, d_lane: int = 128) -> bool:
    """Training-path eligibility: 4D, same b/s/d, lane-aligned head_dim,
    sublane-aligned seq block."""
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    b, s, h, d = q_shape
    if k_shape[0] != b or k_shape[1] != s or k_shape[3] != d:
        return False
    return d % d_lane == 0 and s % 8 == 0 and s >= 8


def tuned_block_s(s, d, dtype="bfloat16"):
    try:
        from .autotune import _DB
        kind = getattr(jax.devices()[0], "device_kind", "cpu")
        cfg = _DB.lookup(_DB.key("fused_rope", kind, str(dtype), ss=s, d=d))
        # the DB key BUCKETS s, so a recorded block may not divide this
        # exact seq — validate before trusting it
        if cfg and s % int(cfg.get("block_s", DEFAULT_BLOCK_S)) == 0:
            return int(cfg.get("block_s", DEFAULT_BLOCK_S))
    except Exception:
        pass
    bs = next((c for c in (512, 256, 128, 64, 32, 16, 8)
               if s % c == 0), 8)
    return bs


__all__ = ["fused_rope_pallas", "rope_supported", "tuned_block_s"]
