"""Kernel autotuning cache (block-size selection per shape/device).

Reference analogue: the PHI runtime autotuner —
paddle/phi/kernels/autotune/auto_tune_base.h (TuneBase::Run candidate
timing), cache.h (AutoTuneCache keyed on algorithm+shape), and
switch_autotune.h (step-gated tuning) — plus CINN's persistent tuning DB
(paddle/cinn/auto_schedule/database/). TPU redesign: Pallas kernels have a
tiny discrete config space (block_q, block_k), so instead of an in-process
exhaustive timer on first call (bad under jit: retrace per config), tuning
is OFFLINE (tools/tune_kernels.py sweeps on real hardware) and the result
is a JSON database consulted at dispatch time:

    key = op | device_kind | dtype | bucketed shape signature

Shapes bucket to powers of two so one sweep covers a family; lookups fall
back to the nearest recorded bucket, then to the built-in defaults. A
user-writable overlay (PT_TUNE_DB env or ~/.cache/paddle_tpu/) is merged
over the shipped DB so `tools/tune_kernels.py --write` results win.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

_SHIPPED = os.path.join(os.path.dirname(__file__), "tune_db.json")


def _user_db_path() -> str:
    env = os.environ.get("PT_TUNE_DB")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "tune_db.json")


class TuneDB:
    """Merged shipped + user kernel-config database.

    ``shipped_path`` / ``user_path`` parameterize the two merge sources so
    sibling databases (the cost observatory's :class:`OpCostDB`) share the
    exact load/merge/corrupt-warning machinery instead of re-implementing
    it; the defaults keep the original kernel-config behavior."""

    #: human label used in the corrupt-file warning
    db_label = "kernel tune DB"

    def __init__(self, shipped_path: Optional[str] = None,
                 user_path: Optional[str] = None):
        self._db: Dict[str, dict] = {}
        self._loaded = False
        self._dirty = False
        self._shipped_path = shipped_path or _SHIPPED
        self._user_path = user_path

    def user_path(self) -> str:
        return self._user_path or _user_db_path()

    def _load(self):
        if self._loaded:
            return
        for path in (self._shipped_path, self.user_path()):
            try:
                with open(path) as f:
                    self._db.update(json.load(f))
            except OSError:
                pass      # absent DB is normal (no offline sweep run yet)
            except ValueError as e:
                # corrupt JSON: merging nothing SILENTLY would make
                # offline-tuned configs vanish without a trace — say so once
                import warnings
                warnings.warn(
                    f"ignoring corrupt {self.db_label} at {path} ({e}); "
                    f"offline-tuned configs from that file will not be "
                    f"applied", RuntimeWarning, stacklevel=2)
        self._loaded = True

    @staticmethod
    def bucket(n: int) -> int:
        """Round up to the next power of two (min 128)."""
        b = 128
        while b < n:
            b <<= 1
        return b

    @staticmethod
    def key(op: str, device_kind: str, dtype: str, **dims) -> str:
        sig = ",".join(f"{k}={TuneDB.bucket(v) if k.startswith('s') else v}"
                       for k, v in sorted(dims.items()))
        return f"{op}|{device_kind.lower().replace(' ', '_')}|{dtype}|{sig}"

    def lookup(self, key: str) -> Optional[dict]:
        self._load()
        return self._db.get(key)

    def record(self, key: str, config: dict):
        self._load()
        self._db[key] = config
        self._dirty = True

    def save(self, path: Optional[str] = None):
        path = path or self.user_path()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # merge-over-existing so concurrent tuners don't clobber each other
        merged = {}
        try:
            with open(path) as f:
                merged = json.load(f)
        except (OSError, ValueError):
            pass
        merged.update(self._db)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        self._dirty = False


_DB = TuneDB()


def _default_blocks(sq: int, sk: int) -> Tuple[int, int]:
    """Heuristic when the DB has no entry: the v5-chip sweep (round 3)
    showed larger blocks amortize the per-step grid overhead — bq=512/
    bk=1024 ran ~2.8x faster than 128/128 at s=2048 — so pick the largest
    candidate that divides the sequence (divisibility is required for the
    pallas path to be selected at all)."""
    bq = next((c for c in (512, 256, 128) if sq % c == 0), 128)
    bk = next((c for c in (1024, 512, 256, 128) if sk % c == 0), 128)
    return bq, bk


def flash_attention_config(sq: int, sk: int, d: int,
                           dtype: str, causal: bool) -> Tuple[int, int]:
    """(block_q, block_k) for a flash-attention call: tuned if the DB has
    this (bucketed) shape on this device, else shape-aware defaults.
    Batch and head count are deliberately NOT part of the key: they scale
    the parallel grid dims, not the per-block working set the block sizes
    tile, so one sweep covers all (b, h)."""
    from ..registry import backend_kind
    if backend_kind() != "tpu":
        return 128, 128
    key = TuneDB.key("flash_attention", _device_kind(default="tpu"), dtype,
                     sq=sq, sk=sk, d=d, causal=int(causal))
    hit = _DB.lookup(key)
    if hit and sq % int(hit["block_q"]) == 0 and sk % int(hit["block_k"]) == 0:
        return int(hit["block_q"]), int(hit["block_k"])
    return _default_blocks(sq, sk)


def _device_kind(default: str = "cpu") -> str:
    try:
        import jax
        return getattr(jax.devices()[0], "device_kind", default) or default
    except Exception:
        return default


def fused_vocab_ce_config(n: int, h: int, v: int,
                          dtype: str) -> Tuple[Optional[int], int]:
    """(block_n, block_v) for a fused vocab-CE call (ops/pallas/
    fused_vocab_ce.py): tuned if the DB has this (bucketed) shape on this
    device, else VMEM-fitting defaults. ``block_n`` comes back None when no
    candidate divides N — the caller falls through to the XLA path. The dW
    backward kernel's fp32 [H, block_v] accumulator is the VMEM pacer, so
    the default block_v shrinks as H grows."""
    from ..registry import backend_kind
    key = TuneDB.key("fused_vocab_ce", _device_kind(), dtype,
                     h=h, v=v, sn=n)
    hit = _DB.lookup(key)
    if hit:
        bn, bv = int(hit["block_n"]), int(hit["block_v"])
        # a tuned entry the kernel gate would reject (stale DB after a
        # VMEM_BUDGET change, hand-edited config) must fall through to the
        # defaults, not silently downgrade every TPU call to the XLA path
        if n % bn == 0:
            if backend_kind() != "tpu":
                return bn, bv
            from .fused_vocab_ce import fused_ce_supported
            if fused_ce_supported(n, h, v, dtype, bn, bv):
                return bn, bv
    # defaults come from the kernel module's OWN vmem formula — the same
    # one fused_ce_supported gates on, so a default config is never chosen
    # only to be rejected at dispatch (which would silently route every
    # TPU call to the XLA fallback)
    from .fused_vocab_ce import default_blocks
    return default_blocks(n, h, dtype)


def paged_decode_crossover(default: int = 4096) -> int:
    """Context length (tokens) above which the Pallas paged-decode kernel
    beats the dense XLA gather path for one decode step. Measured on v5e
    (bench paged_decode_us_ctx* sweep): dense marginally ahead at ctx 2048,
    paged 1.45x ahead at 8192 and 3.6x at 16K — so the default crossover
    sits between them. A tuned value (op "paged_decode_crossover", config
    key "ctx") in the TuneDB wins; the serving engine consults this per
    dispatched decode block (inference/serving.py)."""
    key = TuneDB.key("paged_decode_crossover", _device_kind(), "any")
    hit = _DB.lookup(key)
    if hit:
        try:
            return int(hit["ctx"])
        except (KeyError, ValueError, TypeError):
            pass
    return default


def get_db() -> TuneDB:
    return _DB


# ---------------------------------------------------------------------------
# OpCostDB: measured op/graph latencies (ISSUE 9 cost observatory)
# ---------------------------------------------------------------------------

_COST_SHIPPED = os.path.join(os.path.dirname(__file__), "op_cost_db.json")


def _user_cost_db_path() -> str:
    env = os.environ.get("PT_OP_COST_DB")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "op_cost_db.json")


class OpCostDB(TuneDB):
    """Measured-latency database the cost observatory calibrates
    (``tools/op_cost_probe.py``) and the sharding planner will read.

    Same persistence discipline as the kernel TuneDB it sits next to —
    shipped + user overlay merge, atomic merge-over-existing save, and the
    corrupt-file warning path (a corrupt calibration file must degrade to
    analytical estimates loudly, never silently) — but keyed on MEASURED
    quantities: ``graph:<name>|<device_kind>|any|`` records a canonical
    graph's min-of-rounds execution seconds + its analytical flop/byte
    attribution, ``dot|<device_kind>|<dtype>|k=...,m=...,n=...`` records a
    dominant matmul shape's microbench seconds. Entries carry the numbers
    the planner prices configs with, so calibration survives restarts."""

    db_label = "op cost DB"

    def __init__(self, user_path: Optional[str] = None):
        super().__init__(shipped_path=_COST_SHIPPED, user_path=user_path)

    def user_path(self) -> str:
        # resolved LAZILY per call, matching TuneDB's PT_TUNE_DB
        # discipline — a PT_OP_COST_DB set after import must still win
        return self._user_path or _user_cost_db_path()

    @staticmethod
    def graph_key(name: str, device_kind: str) -> str:
        return TuneDB.key(f"graph:{name}", device_kind, "any")

    @staticmethod
    def dot_key(m: int, k: int, n: int, dtype: str,
                device_kind: str) -> str:
        return TuneDB.key("dot", device_kind, dtype, m=m, k=k, n=n)


_COST_DB = OpCostDB()


def get_op_cost_db() -> OpCostDB:
    return _COST_DB


__all__ = ["TuneDB", "get_db", "flash_attention_config",
           "fused_vocab_ce_config", "paged_decode_crossover",
           "OpCostDB", "get_op_cost_db"]
