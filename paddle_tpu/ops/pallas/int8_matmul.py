"""Pallas TPU fused weight-only int8 matmul.

Reference analogue: the cutlass weight-only GEMMs behind
python/paddle/nn/quant/quantized_linear.py weight_only_linear:152
(paddle/phi/kernels/fusion/cutlass/...), where dequantization happens in
the GEMM epilogue instead of a separate pass.

TPU-first design: the win at decode time is HBM bandwidth — the weight
crosses HBM as int8 ([n, k], the reference's transposed layout) and is
widened to the activation dtype IN VMEM, right before the MXU dot; the
per-channel scale multiplies the f32 accumulator once per output tile.
XLA's fallback composition (convert + scale folded into dot_general) is
kept for non-TPU backends, group-wise scales, int4, and shapes that do
not tile; dispatch happens in nn/quantized_linear.py via ops.registry.

Block sizes come from the tune DB (`tune_db.json`, op "int8_matmul") with
MXU-shaped defaults.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BLOCK_M = 256
DEFAULT_BLOCK_N = 256
DEFAULT_BLOCK_K = 512


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk):
    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xb = x_ref[...]                                   # [bm, bk] activation
    wb = w_ref[...].astype(xb.dtype)                  # [bn, bk] int8 -> act
    acc_ref[...] += jax.lax.dot_general(
        xb, wb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # [bm, bn] f32

    @pl.when(pl.program_id(2) == nk - 1)
    def _epilogue():
        scale = s_ref[...].astype(jnp.float32)        # [1, bn]
        o_ref[...] = (acc_ref[...] * scale).astype(o_ref.dtype)


def int8_matmul_pallas(x, wq, scale, *, block_m: int = DEFAULT_BLOCK_M,
                       block_n: int = DEFAULT_BLOCK_N,
                       block_k: int = DEFAULT_BLOCK_K,
                       interpret: bool = False):
    """y[m, n] = x[m, k] @ wq[n, k].T * scale[n], dequant fused in VMEM.

    x: float (bf16/f32) [m, k]; wq: int8 [n, k] (transposed reference
    layout); scale: [n] per-channel. Shapes must divide the block sizes —
    the caller (weight_only_linear) checks and falls back otherwise."""
    if not _HAS_PLTPU:
        raise ImportError(
            "pallas.tpu is unavailable in this jax build; use the XLA "
            "weight_only_linear path")
    m, k = x.shape
    n, k2 = wq.shape
    assert k == k2 and scale.shape == (n,)
    block_m = min(block_m, m)
    block_n = min(block_n, n)
    block_k = min(block_k, k)
    if m % block_m or n % block_n or k % block_k:
        raise ValueError(
            f"shape ({m},{k})x({n},{k}) does not divide blocks "
            f"({block_m},{block_n},{block_k}); gate with shapes_supported()")
    nm, nn, nk = m // block_m, n // block_n, k // block_k
    scale2 = scale.reshape(1, n)

    grid = (nm, nn, nk)
    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((block_n, block_k), lambda i, j, kk: (j, kk)),
            pl.BlockSpec((1, block_n), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        compiler_params=(pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
            if not interpret else None),
        interpret=interpret,
    )(x, wq, scale2)
    return out


def shapes_supported(x_shape, w_shape, *, block_m=DEFAULT_BLOCK_M,
                     block_n=DEFAULT_BLOCK_N, block_k=DEFAULT_BLOCK_K,
                     dtype=None):
    """True when the fused kernel can run these shapes without padding:
    every dim divides its (clamped) block."""
    m, k = x_shape
    n, k2 = w_shape
    if k != k2:
        return False
    # m must be sublane-tile-aligned for the ACTIVATION dtype (f32: 8,
    # bf16: 16, int8: 32): Mosaic failures at misaligned block_m surface
    # at jit COMPILE time, after the dispatch fallback has already
    # committed, so the gate has to be conservative (batch-1 decode and
    # ragged m go XLA)
    sublane = 8
    if dtype is not None:
        itemsize = jnp.dtype(dtype).itemsize
        sublane = {4: 8, 2: 16, 1: 32}.get(itemsize, 8)
    if m < sublane or m % sublane:
        return False
    bm, bn, bk = min(block_m, m), min(block_n, n), min(block_k, k)
    return m % bm == 0 and n % bn == 0 and k % bk == 0 and bn >= 128 \
        and bk >= 128


def xla_weight_only(x, wq, scale):
    """XLA composition fallback: widen int8 to the activation dtype
    (exact — ±127 is representable even in bf16) and apply the
    per-channel scale to the f32 ACCUMULATOR, not the [n, k] weight.
    At decode (m ≤ batch) an O(n·k) dequant pass per call would cost
    more than the dot itself; the epilogue multiply is O(m·n) — the
    same scale-the-accumulator contract the Pallas kernel uses.
    x float [..., k]; wq int8 [n, k]; scale [n] or scalar fp32.
    Returns [..., n] in x.dtype — the activation-dtype convention
    every linear in the repo follows."""
    n, k = wq.shape
    scale = jnp.broadcast_to(
        jnp.asarray(scale, jnp.float32).reshape(-1), (n,))
    acc = jax.lax.dot_general(
        x, wq.astype(x.dtype), (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (acc * scale).astype(x.dtype)


@functools.lru_cache(maxsize=1)
def _tpu_lowering_ok() -> bool:
    """One-shot compile probe on the real backend (same rationale as
    fused_vocab_ce: degrade to the XLA path on env drift instead of
    poisoning every downstream jit)."""
    from ..registry import backend_kind
    if backend_kind() != "tpu":
        return False
    try:
        x = jax.ShapeDtypeStruct((256, 512), jnp.bfloat16)
        w = jax.ShapeDtypeStruct((256, 512), jnp.int8)
        s = jax.ShapeDtypeStruct((256,), jnp.float32)

        def probe(x, w, s):
            return int8_matmul_pallas(x, w, s, block_m=256, block_n=256,
                                      block_k=512)

        jax.jit(probe).lower(x, w, s).compile()
        return True
    except Exception as e:  # pragma: no cover - only on env drift
        import warnings
        warnings.warn(f"Pallas int8 matmul failed TPU lowering; falling "
                      f"back to the XLA dequant-matmul path: {e}")
        return False


def _tpu_weight_only(x, wq, scale):
    """Registered TPU impl: the fused Pallas kernel when the shape/env
    gates pass (TuneDB blocks + lowering probe, exactly the
    fused_vocab_ce pattern), else the XLA composition."""
    from ..registry import pallas_disabled
    from ...core.flags import flag
    scale = jnp.asarray(scale, jnp.float32)
    lead, k = x.shape[:-1], x.shape[-1]
    m = 1
    for d in lead:
        m *= d
    n = wq.shape[0]
    if (pallas_disabled() or not flag("use_pallas_kernels")
            or scale.ndim > 1 or db_winner(m, n, k, x.dtype) == "xla"
            or not _tpu_lowering_ok()):
        return xla_weight_only(x, wq, scale)
    bm, bn, bk = tuned_blocks(m, n, k, x.dtype)
    if not shapes_supported((m, k), tuple(wq.shape), block_m=bm,
                            block_n=bn, block_k=bk, dtype=x.dtype):
        return xla_weight_only(x, wq, scale)
    try:
        y = int8_matmul_pallas(x.reshape(m, k),
                               wq, jnp.broadcast_to(scale.reshape(-1),
                                                    (n,)),
                               block_m=bm, block_n=bn, block_k=bk)
    except Exception:
        return xla_weight_only(x, wq, scale)
    return y.reshape(lead + (n,))


def _register():
    # THE one registry op both quantization/functional.int8_matmul and
    # nn/quantized_linear.weight_only_linear resolve through (ISSUE 17
    # dedupe): per-channel weight-only int8, x float [..., k] x wq int8
    # [n, k] -> [..., n] in x.dtype.
    from ..registry import register_kernel
    register_kernel("int8_matmul", "tpu")(_tpu_weight_only)
    register_kernel("int8_matmul", "any")(xla_weight_only)


_register()


def quantized_matmul(x, wq, scale):
    """Dispatch-routed weight-only int8 matmul: the single entry every
    int8 linear call site uses (model weight_dtype='int8' projections,
    Int8Linear, functional.int8_matmul). TuneDB block configs and the
    PT_DISABLE_PALLAS kill-switch apply uniformly because dispatch
    happens here, not at the callers."""
    from ..registry import dispatch
    return dispatch("int8_matmul")(x, wq, scale)


def _db_cfg(m, n, k, dtype):
    from .autotune import _DB
    import jax as _jax
    kind = getattr(_jax.devices()[0], "device_kind", "cpu")
    return _DB.lookup(_DB.key("int8_matmul", kind, str(dtype),
                              sm=m, sn=n, sk=k))


def tuned_blocks(m, n, k, dtype="bfloat16"):
    """Tune-DB lookup for (m, n, k); falls back to the MXU defaults."""
    try:
        cfg = _db_cfg(m, n, k, dtype)
        if cfg:
            return (cfg.get("block_m", DEFAULT_BLOCK_M),
                    cfg.get("block_n", DEFAULT_BLOCK_N),
                    cfg.get("block_k", DEFAULT_BLOCK_K))
    except Exception:
        pass
    return DEFAULT_BLOCK_M, DEFAULT_BLOCK_N, DEFAULT_BLOCK_K


def db_winner(m, n, k, dtype="bfloat16"):
    """Measured dispatch preference for this shape bucket.

    'xla' = on-hardware A/B showed the XLA dequant-matmul at least ties
    the fused kernel (v5e: the op is weight-streaming/overhead bound at
    serving shapes, so fusing the dequant buys nothing measurable —
    amortized scan-loop timings recorded in the DB entry). None = no
    measurement, caller keeps its default."""
    try:
        cfg = _db_cfg(m, n, k, dtype)
        return cfg.get("winner") if cfg else None
    except Exception:
        return None


__all__ = ["int8_matmul_pallas", "shapes_supported", "tuned_blocks",
           "db_winner", "quantized_matmul", "xla_weight_only"]
