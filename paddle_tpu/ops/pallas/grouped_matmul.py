"""Pallas TPU grouped (ragged) matmul for per-expert MoE GEMMs.

Reference analogue: the grouped GEMM behind the reference's fused MoE
dispatch (incubate/nn/functional moe layers lower per-expert FFNs onto
one batched kernel instead of a Python loop over experts).

The op: rows of ``xs [m, k]`` are partitioned into ``g`` contiguous runs
by ``group_sizes [g]`` and run ``i`` multiplies its own ``w[i] [k, n]``.
Per-expert token counts are data-dependent, so the kernel cannot assume
anything divides anything — the TPU-first trick is TILE-ALIGNED PACKING:
scatter each run to a ``block_m``-aligned offset in a statically-bounded
staging buffer, so every grid row-tile belongs to exactly ONE group and
the weight for that tile is picked by a scalar-prefetched tile→group
table in the weight BlockSpec's index_map (the megablox group-metadata
idea, collapsed to its simplest alignment-by-construction form). Padding
rows are zero, multiply into zero rows, and are dropped by the final
gather — no masking in the kernel's hot loop.

Gradients: the backward pass reuses the XLA fallback's vjp (ragged_dot
is linear in both operands, so this is exact, and it guarantees the
gradcheck parity the MoE tests pin). Dispatch is TuneDB-gated with a
one-shot lowering probe and an XLA ``lax.ragged_dot`` fallback, exactly
like fused_vocab_ce and int8_matmul; parallel/moe.py's ``_grouped_matmul``
is the seam that routes here.
"""

from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_N = 128
DEFAULT_BLOCK_K = 128


def xla_grouped_matmul(xs, w, group_sizes):
    """XLA fallback: ``lax.ragged_dot`` when this jax ships it (XLA-
    native; the round-5 v5e A/B measured it 1.7x faster than megablox
    gmm with max|diff|=0 at e=64, d=2048, f=1408); otherwise the bundled
    megablox Pallas kernel (interpret mode off-TPU). Returns f32 — the
    accumulator dtype; callers cast back to the activation dtype."""
    if hasattr(jax.lax, "ragged_dot"):
        return jax.lax.ragged_dot(xs, w, group_sizes,
                                  preferred_element_type=jnp.float32)
    from jax.experimental.pallas.ops.tpu.megablox import gmm
    from ..registry import backend_kind

    def tiling(m, kk, n):
        # largest power-of-two tile <= 128 dividing each dim (gmm
        # requires exact tiling; real configs are 128-multiples, tiny
        # test shapes degrade gracefully)
        g_ = lambda x: math.gcd(x, 128)
        return (g_(m), g_(kk), g_(n))

    return gmm(xs, w, group_sizes, preferred_element_type=jnp.float32,
               tiling=tiling(xs.shape[0], w.shape[1], w.shape[2]),
               interpret=backend_kind() != "tpu")


def _kernel(tg_ref, x_ref, w_ref, o_ref, acc_ref, *, nk):
    # tg_ref is the scalar-prefetched tile→group table; it is consumed
    # by the weight BlockSpec's index_map, not read here
    del tg_ref

    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xb = x_ref[...]                                   # [bm, bk]
    wb = w_ref[0]                                     # [bk, bn] (this tile's expert)
    acc_ref[...] += jax.lax.dot_general(
        xb, wb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # [bm, bn] f32

    @pl.when(pl.program_id(2) == nk - 1)
    def _epilogue():
        o_ref[...] = acc_ref[...]


def _pack_plan(group_sizes, m, block_m, g, nm):
    """Tile-aligned packing metadata (all int32, all traced):
    ``dest [m]`` — packed-buffer row for each source row (each group's
    run starts on a ``block_m`` boundary); ``tile_group [nm]`` — which
    group's weight each packed row-tile multiplies. Tiles past the used
    region keep group g-1: their rows are zero, their output is dead."""
    counts = group_sizes.astype(jnp.int32)
    aligned = ((counts + block_m - 1) // block_m) * block_m
    ends = jnp.cumsum(aligned)
    starts = ends - aligned
    row_ends = jnp.cumsum(counts)
    row_starts = row_ends - counts
    rid = jnp.arange(m, dtype=jnp.int32)
    gi = jnp.searchsorted(row_ends, rid, side="right").astype(jnp.int32)
    gi = jnp.minimum(gi, g - 1)
    dest = starts[gi] + (rid - row_starts[gi])
    tile_start = jnp.arange(nm, dtype=jnp.int32) * block_m
    tile_group = jnp.minimum(
        jnp.searchsorted(ends, tile_start, side="right"),
        g - 1).astype(jnp.int32)
    return dest, tile_group


def grouped_matmul_pallas(xs, w, group_sizes, *,
                          block_m: int = DEFAULT_BLOCK_M,
                          block_n: int = DEFAULT_BLOCK_N,
                          block_k: int = DEFAULT_BLOCK_K,
                          interpret: bool = False):
    """y[m, n] f32 = per-group ``xs_run @ w[group]`` via tile-aligned
    packing + scalar-prefetched weight selection.

    xs: float [m, k]; w: float [g, k, n]; group_sizes: int [g] summing
    to m. ``k``/``n`` must divide the (clamped) blocks — the dispatch
    gate (shapes_supported) checks; ``m`` need not: the packed staging
    buffer is padded to a static ``block_m``-aligned bound."""
    if not _HAS_PLTPU:
        raise ImportError(
            "pallas.tpu is unavailable in this jax build; use "
            "xla_grouped_matmul")
    m, k = xs.shape
    g, k2, n = w.shape
    assert k == k2 and group_sizes.shape == (g,)
    block_n = min(block_n, n)
    block_k = min(block_k, k)
    if n % block_n or k % block_k:
        raise ValueError(
            f"shape ({m},{k})x({g},{k},{n}) does not divide blocks "
            f"({block_m},{block_n},{block_k}); gate with shapes_supported()")
    # static bound on the packed buffer: every group wastes < block_m
    # alignment rows, so ceil(m/bm) + g tiles always suffice
    nm = (m + block_m - 1) // block_m + g
    m_pad = nm * block_m
    nn, nk = n // block_n, k // block_k

    dest, tile_group = _pack_plan(group_sizes, m, block_m, g, nm)
    xp = jnp.zeros((m_pad, k), xs.dtype).at[dest].set(xs)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk, tg: (i, kk)),
            pl.BlockSpec((1, block_k, block_n),
                         lambda i, j, kk, tg: (tg[i], kk, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda i, j, kk, tg: (i, j)),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
    )
    yp = pl.pallas_call(
        functools.partial(_kernel, nk=nk),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_pad, n), jnp.float32),
        compiler_params=(pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "parallel", "arbitrary"))
            if not interpret else None),
        interpret=interpret,
    )(tile_group, xp, w)
    return yp[dest]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _pallas_gmm(xs, w, group_sizes, bm, bn, bk, interpret):
    return grouped_matmul_pallas(xs, w, group_sizes, block_m=bm,
                                 block_n=bn, block_k=bk,
                                 interpret=interpret)


def _pallas_gmm_fwd(xs, w, group_sizes, bm, bn, bk, interpret):
    return (_pallas_gmm(xs, w, group_sizes, bm, bn, bk, interpret),
            (xs, w, group_sizes))


def _pallas_gmm_bwd(bm, bn, bk, interpret, res, gy):
    # backward through the XLA fallback: ragged_dot is linear in both
    # operands so its vjp IS the exact gradient of the grouped matmul —
    # this is what guarantees Pallas/XLA gradcheck parity
    xs, w, group_sizes = res
    _, vjp = jax.vjp(
        lambda a, b: xla_grouped_matmul(a, b, group_sizes), xs, w)
    dxs, dw = vjp(gy.astype(jnp.float32))
    return (dxs.astype(xs.dtype), dw.astype(w.dtype),
            np.zeros(group_sizes.shape, dtype=jax.dtypes.float0))


_pallas_gmm.defvjp(_pallas_gmm_fwd, _pallas_gmm_bwd)


def shapes_supported(x_shape, w_shape, *, block_m=DEFAULT_BLOCK_M,
                     block_n=DEFAULT_BLOCK_N, block_k=DEFAULT_BLOCK_K,
                     dtype=None):
    """True when the fused kernel can run these shapes: k/n divide their
    (clamped) blocks at MXU-worthy widths. m is unconstrained (the
    packing pads it), but block_m must stay sublane-aligned for the
    activation dtype (f32: 8, bf16: 16) — Mosaic failures at misaligned
    tiles surface at COMPILE time, after dispatch already committed."""
    m, k = x_shape
    g, k2, n = w_shape
    if k != k2 or m < 1 or g < 1:
        return False
    sublane = 8
    if dtype is not None:
        itemsize = jnp.dtype(dtype).itemsize
        sublane = {4: 8, 2: 16, 1: 32}.get(itemsize, 8)
    if block_m % sublane:
        return False
    bn, bk = min(block_n, n), min(block_k, k)
    return n % bn == 0 and k % bk == 0 and bn >= 128 and bk >= 128


@functools.lru_cache(maxsize=1)
def _tpu_lowering_ok() -> bool:
    """One-shot compile probe on the real backend (same rationale as
    fused_vocab_ce/int8_matmul: degrade to the XLA path on env drift
    instead of poisoning every downstream jit)."""
    from ..registry import backend_kind
    if backend_kind() != "tpu":
        return False
    try:
        xs = jax.ShapeDtypeStruct((512, 256), jnp.bfloat16)
        w = jax.ShapeDtypeStruct((4, 256, 256), jnp.bfloat16)
        gs = jax.ShapeDtypeStruct((4,), jnp.int32)

        def probe(xs, w, gs):
            return grouped_matmul_pallas(xs, w, gs, block_m=128,
                                         block_n=128, block_k=128)

        jax.jit(probe).lower(xs, w, gs).compile()
        return True
    except Exception as e:  # pragma: no cover - only on env drift
        import warnings
        warnings.warn(f"Pallas grouped matmul failed TPU lowering; "
                      f"falling back to XLA ragged_dot: {e}")
        return False


def _tpu_grouped(xs, w, group_sizes):
    """Registered TPU impl: the tile-aligned Pallas kernel when the
    shape/env gates pass (TuneDB blocks + lowering probe), else the XLA
    ragged_dot composition."""
    from ..registry import pallas_disabled
    from ...core.flags import flag
    m, k = xs.shape
    g, _, n = w.shape
    if (pallas_disabled() or not flag("use_pallas_kernels")
            or db_winner(m, n, k, g, xs.dtype) == "xla"
            or not _tpu_lowering_ok()):
        return xla_grouped_matmul(xs, w, group_sizes)
    bm, bn, bk = tuned_blocks(m, n, k, g, xs.dtype)
    if not shapes_supported((m, k), tuple(w.shape), block_m=bm,
                            block_n=bn, block_k=bk, dtype=xs.dtype):
        return xla_grouped_matmul(xs, w, group_sizes)
    try:
        return _pallas_gmm(xs, w, group_sizes, bm, bn, bk, False)
    except Exception:
        return xla_grouped_matmul(xs, w, group_sizes)


def _register():
    # THE registry op parallel/moe.py's _grouped_matmul seam resolves
    # through: xs float [m, k] x w [g, k, n], group_sizes [g] -> f32
    # [m, n]; dropless routing AND the dropless-EP shard_map body both
    # route here, so TuneDB configs and PT_DISABLE_PALLAS apply to every
    # per-expert GEMM uniformly.
    from ..registry import register_kernel
    register_kernel("grouped_matmul", "tpu")(_tpu_grouped)
    register_kernel("grouped_matmul", "any")(xla_grouped_matmul)


_register()


@jax.custom_vjp
def grouped_matmul(xs, w, group_sizes):
    """Dispatch-routed grouped matmul: the single entry every per-expert
    GEMM call site uses (MoE dropless routing, the EP shard_map body).

    custom_vjp at the dispatch boundary, not just the Pallas path: jax's
    ragged_dot ad rules choke on symbolic-Zero tangents inside a
    shard_map transpose (the dropless-EP body), so BOTH backends take
    the one exact bwd below — custom_vjp instantiates the cotangent
    before bwd runs, and the grouped matmul is linear in each operand,
    so this is the exact gradient either way."""
    from ..registry import dispatch
    return dispatch("grouped_matmul")(xs, w, group_sizes)


def _gmm_fwd(xs, w, group_sizes):
    return grouped_matmul(xs, w, group_sizes), (xs, w, group_sizes)


def _gmm_bwd(res, gy):
    xs, w, group_sizes = res
    _, vjp = jax.vjp(
        lambda a, b: xla_grouped_matmul(a, b, group_sizes), xs, w)
    dxs, dw = vjp(gy.astype(jnp.float32))
    return (dxs.astype(xs.dtype), dw.astype(w.dtype),
            np.zeros(group_sizes.shape, dtype=jax.dtypes.float0))


grouped_matmul.defvjp(_gmm_fwd, _gmm_bwd)


def _db_cfg(m, n, k, g, dtype):
    from .autotune import _DB
    import jax as _jax
    kind = getattr(_jax.devices()[0], "device_kind", "cpu")
    return _DB.lookup(_DB.key("grouped_matmul", kind, str(dtype),
                              sm=m, sn=n, sk=k, g=g))


def tuned_blocks(m, n, k, g, dtype="bfloat16"):
    """Tune-DB lookup for (m, n, k, g); falls back to MXU defaults."""
    try:
        cfg = _db_cfg(m, n, k, g, dtype)
        if cfg:
            return (cfg.get("block_m", DEFAULT_BLOCK_M),
                    cfg.get("block_n", DEFAULT_BLOCK_N),
                    cfg.get("block_k", DEFAULT_BLOCK_K))
    except Exception:
        pass
    return DEFAULT_BLOCK_M, DEFAULT_BLOCK_N, DEFAULT_BLOCK_K


def db_winner(m, n, k, g, dtype="bfloat16"):
    """Measured dispatch preference for this shape bucket ('xla' = the
    on-hardware A/B showed ragged_dot at least ties the Pallas kernel
    for this bucket; None = no measurement, keep the default)."""
    try:
        cfg = _db_cfg(m, n, k, g, dtype)
        return cfg.get("winner") if cfg else None
    except Exception:
        return None


__all__ = ["grouped_matmul", "grouped_matmul_pallas", "xla_grouped_matmul",
           "shapes_supported", "tuned_blocks", "db_winner"]
