"""Pallas TPU kernels (flash attention, fused norms). Importing registers
the TPU-backend kernels with the op registry."""

from ...core.jax_compat import install_pallas_compat

install_pallas_compat()    # pltpu.CompilerParams name on jax<0.6

from . import flash_attention  # noqa: F401,E402
from . import fused_norm  # noqa: F401
from . import fused_vocab_ce  # noqa: F401
from . import grouped_matmul  # noqa: F401
from . import paged_attention  # noqa: F401
