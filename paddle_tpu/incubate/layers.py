"""paddle.incubate.layers — the non-PS subset of the reference's
incubate/layers/nn.py. The PS/recommendation-era ops there
(fused_embedding_seq_pool, search_pyramid_hash, tdm_child/tdm_sampler,
rank_attention, …) are ledgered non-goals (docs/DESIGN_DECISIONS.md
parameter-server entry); the general tensor utilities are real ops here.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["shuffle_batch", "partial_concat", "partial_sum"]


def _col_slice(x, start_index: int, length: int):
    x = jnp.asarray(x)
    n = x.shape[-1]
    start = start_index if start_index >= 0 else n + start_index
    stop = n if length < 0 else start + length
    return x[..., start:stop]


def shuffle_batch(x, seed: Optional[int] = None):
    """Random permutation along the batch dim (reference:
    incubate/layers/nn.py shuffle_batch).

    Static-mode note: with ``seed=None`` a fresh seed is drawn when the
    op is RECORDED, so each call site shuffles differently — but the
    compiled program replays that permutation on every run (compiled
    executables are deterministic; feed an explicit per-run ``seed``
    via a program input if you need per-run reshuffling)."""
    from ..core.rng import rng_tracker
    if isinstance(x, jax.Array) or not hasattr(x, "_build"):
        key = (jax.random.PRNGKey(seed) if seed is not None
               else rng_tracker().next_key())
        return jax.random.permutation(key, jnp.asarray(x), axis=0)
    # program var: record (static-mode path)
    if seed is None:
        import numpy as _np
        seed = int(_np.random.SeedSequence().entropy % (2 ** 31))
    from ..static import lazy_apply
    return lazy_apply(lambda v: shuffle_batch(v, seed=seed), x,
                      name="shuffle_batch")


def _lazy_or(fn, inputs, **kw):
    if any(hasattr(v, "_build") for v in inputs):
        from ..static import lazy_apply
        return lazy_apply(lambda *vs: fn(list(vs), **kw), *inputs,
                          name=fn.__name__)
    return fn(list(inputs), **kw)


def partial_concat(input: Sequence, start_index: int = 0,
                   length: int = -1):
    """Concat the [start_index, start_index+length) column slice of every
    input (reference: incubate/layers/nn.py partial_concat). Works on
    arrays and on static program vars."""
    def run(vals, start_index=start_index, length=length):
        return jnp.concatenate(
            [_col_slice(v, start_index, length) for v in vals], axis=-1)
    run.__name__ = "partial_concat"
    return _lazy_or(run, list(input))


def partial_sum(input: Sequence, start_index: int = 0, length: int = -1):
    """Sum the column slices across inputs (reference: partial_sum)."""
    def run(vals, start_index=start_index, length=length):
        parts = [_col_slice(v, start_index, length) for v in vals]
        return sum(parts[1:], parts[0])
    run.__name__ = "partial_sum"
    return _lazy_or(run, list(input))
