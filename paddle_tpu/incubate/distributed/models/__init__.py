from . import moe
