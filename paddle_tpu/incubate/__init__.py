"""paddle_tpu.incubate — incubating APIs (reference: python/paddle/incubate/).

Hosts the fused-op functional surface (incubate.nn.functional) mirroring the
reference's fused kernels, re-exported ahead of graduation to paddle_tpu.nn.
"""

from . import nn
from . import layers  # noqa: F401
from . import asp
from . import operators
from . import autograd
from . import optimizer
from . import autotune
from . import checkpoint
from . import distributed
from . import tensor

__all__ = ["nn", "asp", "operators"]

# -- round-3 parity batch ---------------------------------------------------
from ..geometric import segment_sum, segment_mean, segment_max, segment_min
from .operators import (softmax_mask_fuse, softmax_mask_fuse_upper_triangle,
                        graph_send_recv)
from .extras import (identity_loss, graph_khop_sampler, graph_reindex,
                     graph_sample_neighbors, LookAhead, ModelAverage)

__all__ += ["segment_sum", "segment_mean", "segment_max", "segment_min",
            "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
            "graph_send_recv", "identity_loss", "graph_khop_sampler",
            "graph_reindex", "graph_sample_neighbors", "LookAhead",
            "ModelAverage"]

# reference path incubate/autograd/{functional,primapi}.py — ours is one
# module; register the subpaths for verbatim reference imports
from ..utils import register_submodule_aliases as _rsa
from . import autograd as _ag
_rsa(__name__ + ".autograd", {"functional": _ag, "primapi": _ag,
                              "utils": _ag})
