"""paddle_tpu.incubate — incubating APIs (reference: python/paddle/incubate/).

Hosts the fused-op functional surface (incubate.nn.functional) mirroring the
reference's fused kernels, re-exported ahead of graduation to paddle_tpu.nn.
"""

from . import nn
from . import asp
from . import operators

__all__ = ["nn", "asp", "operators"]
