"""Training loop with built-in throughput/MFU accounting.

Reference analogue: the hapi Model.fit loop (python/paddle/hapi/model.py:1756)
+ fleet's hybrid training step (SURVEY.md §3.3), redesigned around one jitted
functional step: params/opt-state are donated pytrees, the loss fn comes from
the Layer functional bridge, randomness enters as a key argument, and the LR
is a scalar argument (scheduler stays host-side, never retraces).

MFU = achieved_flops / peak_flops, with model FLOPs from
``model.flops_per_token`` (PaLM convention) and per-chip peak from a small
device table — the calculator the reference lacks (BASELINE.md requires it
from day one).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import rng_tracker
from ..nn.layer import Layer
from ..optimizer.optimizer import Optimizer

# bf16 peak TFLOP/s per chip
PEAK_FLOPS = {
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,   # v5e
    "tpu v5e": 197e12,
    "tpu v5": 459e12,        # v5p
    "tpu v5p": 459e12,
    "tpu v6 lite": 918e12,   # v6e (trillium)
    "cpu": 1e12,             # nominal, for smoke runs
}


def device_peak_flops() -> float:
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return PEAK_FLOPS.get(d.platform, 1e12)


@dataclass
class TrainMetrics:
    step: int
    loss: float
    step_time_s: float
    tokens_per_sec: float
    tokens_per_sec_per_chip: float
    mfu: float
    lr: float

    def as_dict(self):
        return self.__dict__.copy()


class Trainer:
    """Single-program trainer: works 1-chip or over a mesh (pass sharded
    params/opt-state; the jitted step inherits their shardings via GSPMD).

    ``offload_opt_state=True`` parks the optimizer moments in HOST memory
    between steps (pinned_host memory space): train_step pulls them to
    device for the (donated) update and pushes the result back, one
    batched transfer each way. Device HBM then holds params+grads+acts
    plus only a transient optimizer copy — the TPU analogue of the
    reference's GroupSharded CPU offload."""

    def __init__(self, model: Layer, optimizer: Optimizer,
                 loss_key: Optional[str] = None, donate: bool = True,
                 accumulate_steps: int = 1,
                 offload_opt_state: Optional[bool] = None):
        self.model = model
        self.optimizer = optimizer
        self._named = dict(model.named_parameters())
        self.params = model.raw_parameters()
        self.opt_state = optimizer.init_state(self.params)
        # None = inherit from the optimizer flag (group_sharded_parallel /
        # fleet set it); an explicit True/False always wins, including over
        # a flag set later
        self._offload_explicit = offload_opt_state is not None
        if offload_opt_state is None:
            offload_opt_state = getattr(optimizer, "_offload_opt_state",
                                        False)
        self._offload = bool(offload_opt_state)
        if self._offload:
            self.opt_state = self._place_opt_state("pinned_host")
        self._step_fn = None
        self._donate = donate
        self._step = 0
        self._peak = device_peak_flops()
        self._watchdog = None
        self.accumulate_steps = max(1, int(accumulate_steps))

    # -- step function -------------------------------------------------------

    def _build_step(self):
        model, opt = self.model, self.optimizer

        accum = self.accumulate_steps

        # models with a fused forward+backward schedule (1F1B pipeline)
        # provide loss_and_grads instead of being differentiated through
        fused = (getattr(model, "pp_schedule", None) == "1f1b"
                 and hasattr(model, "loss_and_grads"))

        def loss_of(params, batch, key):
            if fused:
                with rng_tracker().scope(key):
                    return model.loss_and_grads(params, **batch)

            def loss_fn(p):
                with rng_tracker().scope(key):
                    out = model.functional_call(p, **batch)
                loss = out[0] if isinstance(out, tuple) else out
                return loss
            return jax.value_and_grad(loss_fn)(params)

        def step_fn(params, opt_state, batch, lr, key):
            if accum == 1:
                loss, grads = loss_of(params, batch, key)
            else:
                # gradient accumulation (reference: GradientMerge pass /
                # accumulate_steps): batch arrays carry a leading microbatch
                # dim [A, ...]; one lax.scan accumulates grads in-place —
                # a single compiled program, activations of only one
                # microbatch live at a time
                keys = jax.random.split(key, accum)

                def body(carry, inp):
                    g_acc, l_acc = carry
                    mb, k = inp
                    l, g = loss_of(params, mb, k)
                    return (jax.tree.map(jnp.add, g_acc, g), l_acc + l), None

                zeros = jax.tree.map(jnp.zeros_like, params)
                (grads, loss_sum), _ = jax.lax.scan(
                    body, (zeros, 0.0), (batch, keys))
                grads = jax.tree.map(lambda g: g / accum, grads)
                loss = loss_sum / accum
            new_params, new_opt_state = opt.apply_gradients(params, grads,
                                                            opt_state, lr=lr)
            return new_params, new_opt_state, loss

        donate = (0, 1) if self._donate else ()
        self._step_fn = jax.jit(step_fn, donate_argnums=donate)

    def _place_opt_state(self, kind: str):
        from ..optimizer.optimizer import place_opt_state
        return place_opt_state(self.opt_state, self.params, kind)

    def train_step(self, batch: Dict[str, jax.Array]) -> float:
        """One optimization step. ``batch`` maps forward kwarg names to
        arrays (e.g. {"input_ids": ..., "labels": ...})."""
        if (not self._offload and not self._offload_explicit
                and getattr(self.optimizer, "_offload_opt_state", False)):
            # group_sharded_parallel(offload=True) ran AFTER this Trainer
            # was built — honor the flag from here on (unless the caller
            # explicitly passed offload_opt_state=False)
            self._offload = True
            self.opt_state = self._place_opt_state("pinned_host")
        if self._step_fn is None:
            self._build_step()
        if self._watchdog is not None:
            self._watchdog.tick()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = jax.random.key(self._step)
        if self._offload:
            # pull the state up for the step, push the update back down:
            # host<->device streams around a device-resident step (the
            # transient device copy is donated straight into the update).
            # In-jit memory-space annotation is deliberately not used —
            # mixed-space operands are rejected by XLA and the CPU test
            # backend lacks annotate_device_placement entirely.
            self.opt_state = self._place_opt_state("device")
        self.params, self.opt_state, loss = self._step_fn(
            self.params, self.opt_state, batch, lr, key)
        if self._offload:
            self.opt_state = self._place_opt_state("pinned_host")
        self._step += 1
        if self._donate:
            # donation invalidates the previous param buffers, which the
            # Layer's Parameters still reference — rebind them to the new
            # arrays so imperative model use never touches deleted buffers
            self.sync_model()
        sched = self.optimizer.lr_scheduler
        if sched is not None:
            sched.step()
        return loss

    # -- full loop with metrics ---------------------------------------------

    def fit(self, data: Iterable[Dict[str, jax.Array]], steps: int,
            log_every: int = 10, on_metrics: Optional[Callable] = None,
            seq_len: Optional[int] = None, checkpoint_manager=None,
            resume=None, anomaly_guard=None, preemption_guard=None):
        """Run the training loop. Beyond the metrics loop, this is the
        fault-tolerant runtime (resilience subsystem):

        * ``checkpoint_manager`` (resilience.CheckpointManager): periodic
          saves every ``save_interval_steps`` plus a final synchronous save;
        * ``resume="auto"``: restore params/opt_state/step/LR-scheduler from
          the newest COMMITTED checkpoint and fast-forward the data cursor
          (via ``data.set_state_dict`` when the loader supports it). With
          resume, ``steps`` is the TOTAL step budget of the run — a relaunch
          trains to the same target as an uninterrupted run;
        * ``preemption_guard`` (resilience.PreemptionGuard): on SIGTERM the
          loop writes one final sync checkpoint at the next step boundary
          and raises TrainingPreempted (exit code = resumable);
        * ``anomaly_guard`` (resilience.AnomalyGuard): NaN/Inf or loss-spike
          steps are skipped (undo the update; needs donate=False) or rolled
          back to the last good checkpoint, within bounded budgets.
        """
        # hung-step watchdog (PT_STEP_TIMEOUT_S): armed only for the
        # duration of this bounded loop — inter-step gaps here ARE steps
        # (device sync + next-batch wait), so a stall is a real hang, and
        # stopping it on exit means eval/checkpoint phases outside fit()
        # can never trigger a spurious kill (reference:
        # phi/core/distributed/comm_task_manager.cc per-task timeouts)
        from ..distributed.watchdog import watchdog_from_env
        if self._watchdog is None:
            self._watchdog = watchdog_from_env()
        if resume and checkpoint_manager is None:
            raise ValueError("resume requires a checkpoint_manager")
        if (anomaly_guard is not None and anomaly_guard.policy == "skip"
                and self._donate):
            raise ValueError(
                "AnomalyGuard(policy='skip') requires Trainer(donate=False): "
                "undoing a poisoned update needs pre-step parameter "
                "references, which buffer donation invalidates. Use "
                "policy='rollback' (with a checkpoint_manager) or disable "
                "donation.")
        if resume and checkpoint_manager is not None:
            self._resume_from(checkpoint_manager, data)
            target = int(steps)
        else:
            target = self._step + int(steps)
        it = iter(data)
        history = []
        t_last = time.perf_counter()
        tokens_since = 0
        loss = None
        try:
            return self._fit_loop(it, target, log_every, on_metrics, seq_len,
                                  history, t_last, tokens_since, loss,
                                  mgr=checkpoint_manager,
                                  anomaly=anomaly_guard,
                                  guard=preemption_guard, data=data)
        finally:
            if self._watchdog is not None:
                self._watchdog.stop()
                self._watchdog = None

    def _fit_loop(self, it, target, log_every, on_metrics, seq_len,
                  history, t_last, tokens_since, loss, mgr=None, anomaly=None,
                  guard=None, data=None):
        while self._step < target:
            if guard is not None and guard.preempted:
                self._preempt_exit(mgr, data)
            try:
                batch = next(it)
            except StopIteration:
                break
            ids = batch.get("input_ids")
            ntok = int(ids.shape[0] * ids.shape[1]) if ids is not None else 0
            prev = None
            if anomaly is not None and not self._donate:
                # pre-step references (immutable jax arrays — free to hold)
                # let "skip" undo a poisoned update without any checkpoint
                sched = self.optimizer.lr_scheduler
                prev = (self.params, self.opt_state,
                        sched.state_dict() if sched is not None else None)
            loss = self.train_step(batch)
            tokens_since += ntok
            if anomaly is not None:
                verdict = anomaly.check(float(loss))
                if verdict != "ok":
                    it = self._handle_anomaly(verdict, anomaly, mgr, prev,
                                              data, it, float(loss))
                    continue
            if self._step % log_every == 0:
                loss_v = float(loss)  # blocks; amortized over log_every
                now = time.perf_counter()
                dt = now - t_last
                tps = tokens_since / dt if dt > 0 else 0.0
                n_dev = jax.device_count()
                sl = seq_len or (ids.shape[1] if ids is not None else 1)
                fpt = (self.model.flops_per_token(sl)
                       if hasattr(self.model, "flops_per_token") else 0.0)
                mfu = (tps / n_dev) * fpt / self._peak if fpt else 0.0
                m = TrainMetrics(step=self._step, loss=loss_v,
                                 step_time_s=dt / log_every,
                                 tokens_per_sec=tps,
                                 tokens_per_sec_per_chip=tps / n_dev,
                                 mfu=mfu, lr=self.optimizer.get_lr())
                history.append(m)
                if on_metrics:
                    on_metrics(m)
                t_last = time.perf_counter()
                tokens_since = 0
            if guard is not None and guard.preempted:
                self._preempt_exit(mgr, data)
            if (mgr is not None
                    and self._step % mgr.save_interval_steps == 0
                    and self._step < target):
                mgr.save(self._step, self._ckpt_tree(data),
                         watchdog=self._watchdog)
        if guard is not None and guard.preempted:
            self._preempt_exit(mgr, data)
        if mgr is not None:
            mgr.save(self._step, self._ckpt_tree(data), async_save=False,
                     watchdog=self._watchdog)
        # write trained params back into the Layer (imperative view);
        # train_step already does this when donation is on
        self.sync_model()
        return history

    # -- resilience runtime --------------------------------------------------

    def _ckpt_tree(self, data=None):
        """Full training state as one checkpointable tree. The structure is
        FIXED (extra always present, same keys) so the restore target always
        matches the saved layout."""
        sched = self.optimizer.lr_scheduler
        if data is not None and hasattr(data, "state_dict"):
            # the loader's own count: batches actually handed out this pass.
            # NOT self._step — anomaly skips consume a batch without keeping
            # the step, so the two drift apart exactly when resume must not
            # replay the poisoned batch
            cursor = int(data.state_dict().get("batches_served", self._step))
        else:
            cursor = self._step    # 1 batch per step for stateless iterables
        return {
            "step": np.asarray(self._step, np.int64),
            "params": self.params,
            "opt_state": self.opt_state,
            "extra": {
                "sched_last_epoch": np.asarray(
                    sched.last_epoch if sched is not None else -1, np.int64),
                # last_lr as VALUE, not formula: adaptive schedulers
                # (ReduceOnPlateau) cannot recompute it from last_epoch
                "sched_last_lr": np.asarray(
                    sched.last_lr if sched is not None else -1.0, np.float64),
                "data_cursor": np.asarray(cursor, np.int64),
            },
        }

    def _apply_restored(self, tree) -> int:
        self.params = tree["params"]
        self.opt_state = tree["opt_state"]
        if self._offload:
            self.opt_state = self._place_opt_state("pinned_host")
        self._step = int(np.asarray(tree["step"]))
        sched = self.optimizer.lr_scheduler
        le = int(np.asarray(tree["extra"]["sched_last_epoch"]))
        llr = float(np.asarray(tree["extra"]["sched_last_lr"]))
        if sched is not None and le >= 0:
            # set_state_dict, NOT step(epoch=le): ReduceOnPlateau.step is a
            # no-op without metrics, which would silently reset its decayed
            # LR to the constructor value
            sched.set_state_dict({"last_epoch": le, "last_lr": (
                llr if llr >= 0 else sched.last_lr)})
        self.sync_model()
        return int(np.asarray(tree["extra"]["data_cursor"]))

    def _resume_from(self, mgr, data) -> Optional[int]:
        """resume="auto": restore the newest committed checkpoint (corrupt
        ones are quarantined by the manager and the previous step is used)
        and position the data cursor."""
        res = mgr.restore(self._ckpt_tree(), watchdog=self._watchdog)
        if res is None:
            return None          # nothing saved yet: cold start
        step, tree = res
        cursor = self._apply_restored(tree)
        if hasattr(data, "set_state_dict"):
            data.set_state_dict({"batches_served": cursor})
        return step

    def _preempt_exit(self, mgr, data=None):
        """Step-boundary preemption: one final SYNCHRONOUS checkpoint, then
        exit with the resumable status (the elastic relauncher resumes
        instead of restarting)."""
        from ..resilience.preemption import TrainingPreempted
        if mgr is not None:
            mgr.save(self._step, self._ckpt_tree(data), async_save=False,
                     watchdog=self._watchdog)
        self.sync_model()
        raise TrainingPreempted(self._step)

    def _handle_anomaly(self, verdict, anomaly, mgr, prev, data, it, loss):
        """Apply the anomaly verdict; returns the (possibly replaced) data
        iterator."""
        from ..resilience.anomaly import SKIP
        if verdict == SKIP and prev is not None:
            # undo this step's (poisoned) update in memory and move past
            # the batch
            params, opt_state, sched_sd = prev
            self.params, self.opt_state = params, opt_state
            sched = self.optimizer.lr_scheduler
            if sched is not None and sched_sd is not None:
                sched.set_state_dict(sched_sd)
            self._step -= 1
            self.sync_model()
            return it
        if verdict == "abort" or mgr is None:
            # no checkpoint to roll back to (or policy says die): fail loudly
            anomaly.raise_divergence(self._step, loss)
        res = mgr.restore(self._ckpt_tree(), watchdog=self._watchdog)
        if res is None:
            anomaly.raise_divergence(self._step, loss)
        _, tree = res
        cursor = self._apply_restored(tree)
        if data is not None and hasattr(data, "set_state_dict"):
            # replay from the checkpointed cursor; without a stateful
            # loader the current iterator continues forward (documented:
            # rollback then sees new batches rather than a replay)
            close = getattr(it, "close", None)
            if close is not None:
                close()        # retire the old pass (and its prefetch thread)
            data.set_state_dict({"batches_served": cursor})
            return iter(data)
        return it

    def sync_model(self):
        for k, v in self.params.items():
            self._named[k].value = v

    def state_dict(self):
        return {"params": self.params, "opt_state": self.opt_state,
                "step": self._step}

    def set_state_dict(self, sd):
        self.params = sd["params"]
        self.opt_state = sd["opt_state"]
        self._step = sd["step"]
