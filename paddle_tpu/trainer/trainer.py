"""Training loop with built-in throughput/MFU accounting.

Reference analogue: the hapi Model.fit loop (python/paddle/hapi/model.py:1756)
+ fleet's hybrid training step (SURVEY.md §3.3), redesigned around one jitted
functional step: params/opt-state are donated pytrees, the loss fn comes from
the Layer functional bridge, randomness enters as a key argument, and the LR
is either a pure on-device function of the step counter (functional
schedulers) or a cached scalar argument.

**Superstep dispatch** (reference analogue: the new executor's async
dispatch + GradientMerge, SURVEY §L5): ``fit(steps_per_dispatch=K)`` fuses K
optimizer steps into ONE compiled ``lax.scan`` over a device-stacked batch
feed. Per-step host work — key creation, LR transfer, loss fence — leaves
the critical path entirely: PRNG keys derive on-device via
``fold_in(base_key, step)`` from the opt-state step counter, the LR is
evaluated in-jit (``scheduler.lr_of(step)``), and per-step losses accumulate
into a device array the host fetches in batches at log/anomaly/checkpoint
boundaries only. The scan body IS the per-step function, so K>1 is
bit-identical to K=1.

**Compile/AOT cache** (core/compile_cache.py): step executables are cached
process-wide by a structural fingerprint; ``precompile()`` AOT-lowers and
serializes them via ``jax.export`` next to the checkpoint dir so a resumed
worker restarts without re-tracing.

MFU = achieved_flops / peak_flops, with model FLOPs from
``model.flops_per_token`` (PaLM convention) and per-chip peak from a small
device table — the calculator the reference lacks (BASELINE.md requires it
from day one).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..core import compile_cache
from ..core.rng import rng_tracker
from ..distributed.overlap import overlap_fingerprint as _overlap_fingerprint
from ..nn.layer import Layer
from ..optimizer.optimizer import Optimizer
from ..profiler import RecordEvent

# span names the trainer emits through RecordEvent (profiler traces and
# the flight recorder's span ring both see them; near-zero cost when
# neither is attached — same contract as SERVING_EVENTS)
TRAINER_EVENTS = ("trainer::dispatch", "trainer::checkpoint")

# bf16 peak TFLOP/s per chip
PEAK_FLOPS = {
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,   # v5e
    "tpu v5e": 197e12,
    "tpu v5": 459e12,        # v5p
    "tpu v5p": 459e12,
    "tpu v6 lite": 918e12,   # v6e (trillium)
    "cpu": 1e12,             # nominal, for smoke runs
}


def device_peak_flops() -> float:
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return PEAK_FLOPS.get(d.platform, 1e12)


@dataclass
class TrainMetrics:
    step: int
    loss: float
    step_time_s: float
    tokens_per_sec: float
    tokens_per_sec_per_chip: float
    mfu: float
    lr: float

    def as_dict(self):
        return self.__dict__.copy()


class Trainer:
    """Single-program trainer: works 1-chip or over a mesh (pass sharded
    params/opt-state; the jitted step inherits their shardings via GSPMD).

    ``offload_opt_state=True`` parks the optimizer moments in HOST memory
    between steps (pinned_host memory space): train_step pulls them to
    device for the (donated) update and pushes the result back, one
    batched transfer each way. Device HBM then holds params+grads+acts
    plus only a transient optimizer copy — the TPU analogue of the
    reference's GroupSharded CPU offload.

    ``seed`` fixes the base PRNG key; step keys derive on-device as
    ``fold_in(key(seed), step)`` so neither the per-step nor the superstep
    path ever creates a key host-side."""

    def __init__(self, model: Layer, optimizer: Optimizer,
                 loss_key: Optional[str] = None, donate: bool = True,
                 accumulate_steps: int = 1,
                 offload_opt_state: Optional[bool] = None,
                 seed: int = 0):
        self.model = model
        self.optimizer = optimizer
        self._named = dict(model.named_parameters())
        # plain dict, not raw_parameters()' OrderedDict: apply_gradients
        # rebuilds plain dicts, and a treedef flip between the first and
        # second dispatch would cost a spurious recompile
        self.params = dict(model.raw_parameters())
        self.opt_state = optimizer.init_state(self.params)
        # None = inherit from the optimizer flag (group_sharded_parallel /
        # fleet set it); an explicit True/False always wins, including over
        # a flag set later
        self._offload_explicit = offload_opt_state is not None
        if offload_opt_state is None:
            offload_opt_state = getattr(optimizer, "_offload_opt_state",
                                        False)
        self._offload = bool(offload_opt_state)
        if self._offload:
            self.opt_state = self._place_opt_state("pinned_host")
        self._donate = donate
        self._step = 0
        self._seed = int(seed)
        self._peak = device_peak_flops()
        self._watchdog = None
        self._active_plan = None      # set by apply_plan
        self._active_mesh = None
        self.accumulate_steps = max(1, int(accumulate_steps))
        # compiled-step machinery (built lazily on first dispatch)
        self._one_step = None          # shared python body (step == scan body)
        self._lr_fn = None
        self._step_jit = None
        self._superstep_jit = None
        self._step_exec: Dict = {}     # aval-signature -> compiled callable
        self._superstep_exec: Dict = {}
        self._fast_exec: Dict = {}     # (kind, batch shapes) -> callable
        self._built_sched = None
        self._lr_cache = None          # (host float, device f32 scalar)
        self._base_key_data = None
        self._aot_dir: Optional[str] = None
        #: host-side dispatch accounting: `dispatch_host_s` is the wall time
        #: spent ENQUEUEING compiled programs (not waiting on them) — the
        #: per-step host overhead the superstep amortizes (bench.py reports
        #: dispatch_overhead_s_per_step = dispatch_host_s / steps).
        self.dispatch_stats = {"steps": 0, "dispatches": 0,
                               "dispatch_host_s": 0.0}
        # cost observatory (ISSUE 9): lazily attached at the first log
        # boundary with the metrics plane on; publishes the step-time
        # breakdown + analytical-MFU gauges (observability/costs/live.py).
        # _last_exec tracks the executable the CURRENT dispatch actually
        # ran (bucketed batch shapes mean several live executables — the
        # gauges must attribute the one on the clock, not the first
        # compiled)
        self._cost_watch = None
        self._cost_watch_kind = None
        self._last_exec = None
        self._last_exec_kind = None

    # -- step function -------------------------------------------------------

    def _build_step(self):
        model, opt = self.model, self.optimizer

        accum = self.accumulate_steps

        # models with a fused forward+backward schedule (1F1B pipeline)
        # provide loss_and_grads instead of being differentiated through
        fused = (getattr(model, "pp_schedule", None) == "1f1b"
                 and hasattr(model, "loss_and_grads"))

        sched = opt.lr_scheduler
        # functional scheduler: LR becomes a pure on-device function of the
        # step counter, evaluated inside the compiled program — the same
        # derivation in the per-step jit and the superstep scan body, so
        # the two paths stay bit-identical
        lr_fn = (sched.lr_of
                 if sched is not None and getattr(sched, "functional", False)
                 else None)
        self._built_sched = sched

        def loss_of(params, batch, key):
            if fused:
                with rng_tracker().scope(key):
                    return model.loss_and_grads(params, **batch)

            def loss_fn(p):
                with rng_tracker().scope(key):
                    out = model.functional_call(p, **batch)
                loss = out[0] if isinstance(out, tuple) else out
                return loss
            return jax.value_and_grad(loss_fn)(params)

        def one_step(params, opt_state, batch, lr, key_data):
            compile_cache.note_trace()
            # the opt-state step counter IS the trainer step (both restored
            # together on resume/rollback): derive key + LR from it on-device
            step = opt_state["step"]
            key = jax.random.fold_in(jax.random.wrap_key_data(key_data),
                                     step)
            lr_t = lr_fn(step) if lr_fn is not None else lr
            if accum == 1:
                loss, grads = loss_of(params, batch, key)
            else:
                # gradient accumulation (reference: GradientMerge pass /
                # accumulate_steps): batch arrays carry a leading microbatch
                # dim [A, ...]; one lax.scan accumulates grads in-place —
                # a single compiled program, activations of only one
                # microbatch live at a time
                keys = jax.random.split(key, accum)

                def body(carry, inp):
                    g_acc, l_acc = carry
                    mb, k = inp
                    l, g = loss_of(params, mb, k)
                    return (jax.tree.map(jnp.add, g_acc, g), l_acc + l), None

                zeros = jax.tree.map(jnp.zeros_like, params)
                (grads, loss_sum), _ = jax.lax.scan(
                    body, (zeros, 0.0), (batch, keys))
                grads = jax.tree.map(lambda g: g / accum, grads)
                loss = loss_sum / accum
            new_params, new_opt_state = opt.apply_gradients(params, grads,
                                                            opt_state,
                                                            lr=lr_t)
            return new_params, new_opt_state, loss

        def superstep(params, opt_state, batch_stack, lr_stack, key_data):
            # K fused steps, one dispatch: the scan body IS one_step, so
            # numerics are bit-identical to K calls of the per-step jit.
            # raw_parameters() hands an OrderedDict while apply_gradients
            # rebuilds plain dicts — normalize so the scan carry structure
            # is closed under the body
            params = dict(params)

            def body(carry, inp):
                p, s = carry
                mb, lr_i = inp
                p, s, loss = one_step(p, s, mb, lr_i, key_data)
                return (p, s), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), (batch_stack, lr_stack))
            return params, opt_state, losses

        donate = (0, 1) if self._donate else ()
        self._one_step = one_step
        self._lr_fn = lr_fn
        self._step_jit = jax.jit(one_step, donate_argnums=donate)
        self._superstep_jit = jax.jit(superstep, donate_argnums=donate)
        self._step_exec = {}
        self._superstep_exec = {}
        self._fast_exec = {}
        self._static_fp = None

    def _ensure_built(self):
        if (self._one_step is None
                or self.optimizer.lr_scheduler is not self._built_sched):
            self._build_step()

    # -- compile-cache plumbing ---------------------------------------------

    def _fp_parts(self):
        """Structural fingerprint of the traced program: everything that
        changes the compiled step WITHOUT changing argument avals (model
        wiring, optimizer/scheduler hyperparameters, donation/accum flags).
        Conservative by design — an over-keyed miss costs one compile, an
        under-keyed hit would be a correctness bug."""
        if getattr(self, "_static_fp", None) is not None:
            return self._static_fp

        def scalars(obj):
            # "name" is a process-serial label (LRScheduler registry), not
            # program structure — keying on it would defeat reuse. Scalar
            # SEQUENCES (milestones/boundaries/values...) and CALLABLE attrs
            # (a resolved activation fn: relu vs gelu with identical shapes)
            # are constants the trace bakes in, so they must key too.
            out = []
            for k, v in vars(obj).items():
                if k == "name":
                    continue
                if isinstance(v, (int, float, bool, str)):
                    out.append((k, v))
                elif isinstance(v, (list, tuple)) and all(
                        isinstance(x, (int, float, bool, str)) for x in v):
                    out.append((k, tuple(v)))
                elif callable(v) and not isinstance(v, Layer):
                    # qualname, never repr(): a repr with an object address
                    # would be unique per construction and kill reuse
                    out.append((k, f"{getattr(v, '__module__', '?')}."
                                   f"{getattr(v, '__qualname__', type(v).__name__)}"))
            return sorted(out)

        model, opt = self.model, self.optimizer
        cfg = getattr(model, "cfg", None)
        try:
            # per-sublayer SCALAR attrs too, not just the type: Dropout p,
            # norm eps, a scale constant — all baked into the trace with no
            # aval footprint. (Python closures can never be fingerprinted
            # exhaustively; this covers every attribute-carried constant.)
            structure = tuple(
                (n, type(l).__qualname__, tuple(scalars(l)))
                for n, l in model.named_sublayers())
        except Exception:
            structure = ()
        sched, clip = opt.lr_scheduler, opt.grad_clip

        def sched_constants(s):
            # the schedule FORMULA is baked into the trace (in-jit lr_of):
            # key on its constants — including those of a WRAPPED scheduler
            # (LinearWarmup.lr_after) — but NOT on mutable progress state
            # (last_epoch/last_lr advance every step — including them would
            # break artifact reuse across a resume, the whole point)
            from ..optimizer.lr import LRScheduler
            mutable = set(s.state_dict())
            consts = [(k, v) for k, v in scalars(s) if k not in mutable]
            nested = tuple(
                (k, type(v).__qualname__, sched_constants(v))
                for k, v in sorted(vars(s).items())
                if isinstance(v, LRScheduler))
            return (tuple(consts), nested)

        sched_part = ()
        if sched is not None and self._lr_fn is not None:
            sched_part = sched_constants(sched)
        import os
        # LABELED parts (ISSUE 8): the fingerprint used to be a bare
        # positional tuple, so a stale-AOT-artifact rejection could only
        # say "fingerprint mismatch". Named keys make
        # compile_cache.explain_fingerprint_change render actionable paths
        # (env.PT_NAIVE_LOSS_HEAD: False -> True). Hash COVERAGE (which
        # program facts key the cache) is identical, but the JSON
        # rendering — and hence the hash VALUE — changes once at this
        # boundary: pre-existing AOT artifacts recompile one time (their
        # tuple-era sidecars carry no "parts", so that one rejection is
        # silent, exactly the old behavior).
        self._static_fp = {
            "jax_version": jax.__version__,
            "backend": jax.default_backend(),
            "model_class": type(model).__qualname__,
            "model_scalars": scalars(model),
            "config_scalars": (scalars(cfg) if cfg is not None
                               and hasattr(cfg, "__dict__") else ()),
            # quantized layouts retrace the whole program with different
            # param avals AND different traced ops (registry int8_matmul
            # vs dense matmul) — config_scalars already covers the str
            # fields, but the labeled entry makes a stale-artifact
            # rejection render as "quantization.weight_dtype: native ->
            # int8" instead of a config_scalars diff (ISSUE 17)
            "quantization": {
                "weight_dtype": getattr(cfg, "weight_dtype", "native"),
                "kv_dtype": getattr(cfg, "kv_dtype", "native"),
            },
            # trace-affecting env escapes: the loss-head override flips
            # which program gets traced with identical avals and cfg —
            # without this key a restart under PT_NAIVE_LOSS_HEAD=1 would
            # aot-hit the stale FUSED executable (and vice versa)
            "env": {
                "PT_NAIVE_LOSS_HEAD":
                    bool(os.environ.get("PT_NAIVE_LOSS_HEAD")),
                "PT_DISABLE_PALLAS":
                    bool(os.environ.get("PT_DISABLE_PALLAS")),
                # overlap scheduler flags change the compiled schedule
                # (async start/done placement) with identical avals — a
                # flag flip between runs must not aot-hit the executable
                # compiled under the other schedule (ISSUE 14)
                "overlap": _overlap_fingerprint(),
            },
            "sublayers": structure,
            "optimizer_class": type(opt).__qualname__,
            "optimizer_scalars": scalars(opt),
            "scheduler_class": (type(sched).__qualname__
                                if sched is not None else None),
            "scheduler_constants": sched_part,
            "functional_lr": bool(self._lr_fn),
            "grad_clip_class": (type(clip).__qualname__
                                if clip is not None else None),
            "grad_clip_scalars": scalars(clip) if clip is not None else (),
            "donate": self._donate,
            "accumulate_steps": self.accumulate_steps,
        }
        return self._static_fp

    def _dispatch(self, kind: str, args):
        """Dispatch one compiled program through the process-wide compile
        cache (core/compile_cache.py): first call per argument-shape
        signature resolves an executable (in-process hit → AOT artifact →
        lower+compile); subsequent calls are a dict lookup + enqueue."""
        t0 = time.perf_counter()
        # fast path: params/opt_state avals are fixed between builds, so
        # steady-state lookup keys only on the batch leaves' shapes —
        # flattening the full param tree per step is exactly the recurring
        # host work this runtime exists to strip
        batch = args[2]
        try:
            # shape AND dtype: a same-shape batch whose leaf dtype drifts
            # (e.g. labels int32 → int64 from a numpy default) must fall
            # through to the aval-keyed slow path and recompile, not hit a
            # stale executable and die on an aval-mismatch TypeError
            fast = (kind, tuple(sorted((k, v.shape, str(v.dtype))
                                       for k, v in batch.items())))
        except Exception:
            fast = None
        fn = self._fast_exec.get(fast) if fast is not None else None
        if fn is None:
            jitted = (self._step_jit if kind == "step"
                      else self._superstep_jit)
            exec_cache = (self._step_exec if kind == "step"
                          else self._superstep_exec)
            sig = compile_cache.aval_signature(args)
            fn = exec_cache.get(sig)
            if fn is None:
                parts = {"static": self._fp_parts(), "kind": kind,
                         "avals": sig}
                fp = compile_cache.fingerprint(
                    (self._fp_parts(), kind, sig))
                fn, _ = compile_cache.acquire(
                    fp, jitted, args, aot_dir=self._aot_dir, name=kind,
                    donate_argnums=(0, 1) if self._donate else (),
                    fp_parts=parts)
                exec_cache[sig] = fn
            if fast is not None:
                self._fast_exec[fast] = fn
        self._last_exec = fn
        self._last_exec_kind = kind
        with RecordEvent("trainer::dispatch"):
            out = fn(*args)
        self.dispatch_stats["dispatches"] += 1
        self.dispatch_stats["dispatch_host_s"] += time.perf_counter() - t0
        return out

    def _key_data(self):
        """Cached base-key data (uint32): created ONCE, folded with the step
        counter on-device — never a fresh jax.random.key per step."""
        if self._base_key_data is None:
            self._base_key_data = jax.random.key_data(
                jax.random.key(self._seed))
        return self._base_key_data

    def _lr_scalar(self):
        """Device LR scalar, re-transferred only when the host scheduler
        actually changed the value (satellite: trainer.py no longer pays a
        host→device LR copy per step). With a functional scheduler the lr
        argument is dead (one_step computes lr_of(step) in-jit) — a fixed
        zero avoids re-syncing a value nobody reads."""
        if self._lr_fn is not None:
            if self._lr_cache is None or self._lr_cache[0] is not None:
                self._lr_cache = (None, jnp.zeros((), jnp.float32))
            return self._lr_cache[1]
        host = float(self.optimizer.get_lr())
        if self._lr_cache is None or self._lr_cache[0] != host:
            self._lr_cache = (host, jnp.asarray(host, jnp.float32))
        return self._lr_cache[1]

    def precompile(self, sample_batch: Dict[str, jax.Array],
                   steps_per_dispatch: int = 1,
                   cache_dir: Optional[str] = None) -> Dict[str, Any]:
        """AOT-lower and compile the training (super)step before the first
        batch arrives, and persist a ``jax.export`` artifact for restarts.

        ``cache_dir`` (defaults to the dir wired by a previous call or by
        ``fit(checkpoint_manager=...)``, i.e. ``<ckpt_root>/_compile_cache``)
        receives the serialized StableHLO + fingerprint sidecar; a relaunch
        whose fingerprint matches deserializes it instead of re-tracing
        (``compile_cache.stats()["traces"]`` proves it). Returns
        ``{"kind", "outcome" (hit|aot_hit|miss), "fingerprint", "aot_dir"}``.
        """
        self._ensure_built()
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
            self._aot_dir = cache_dir
        k = max(1, int(steps_per_dispatch))
        lr = self._lr_scalar()
        kd = self._key_data()
        if k == 1:
            kind = "step"
            args = (self.params, self.opt_state, sample_batch, lr, kd)
            jitted, exec_cache = self._step_jit, self._step_exec
        else:
            from ..io.dataloader import stack_batches
            kind = "superstep"
            stack = stack_batches([sample_batch] * k)
            lr_stack = jnp.zeros((k,), jnp.float32)
            args = (self.params, self.opt_state, stack, lr_stack, kd)
            jitted, exec_cache = self._superstep_jit, self._superstep_exec
        # avals keep the inputs' SHARDINGS (compile_cache.to_avals): the
        # executable is specialized to placement, and the cache key
        # (aval_signature) includes it — an unsharded lowering stored under
        # a sharded key would blow up at the first real dispatch
        avals = compile_cache.to_avals(args)
        sig = compile_cache.aval_signature(args)
        fp = compile_cache.fingerprint((self._fp_parts(), kind, sig))
        fn, outcome = compile_cache.acquire(
            fp, jitted, avals, aot_dir=self._aot_dir, name=kind,
            save_artifact=self._aot_dir is not None,
            donate_argnums=(0, 1) if self._donate else (),
            fp_parts={"static": self._fp_parts(), "kind": kind,
                      "avals": sig})
        exec_cache[sig] = fn
        return {"kind": kind, "outcome": outcome, "fingerprint": fp,
                "aot_dir": self._aot_dir}

    def _place_opt_state(self, kind: str):
        from ..optimizer.optimizer import place_opt_state
        return place_opt_state(self.opt_state, self.params, kind)

    def _adopt_offload_flag(self):
        """group_sharded_parallel(offload=True) may run AFTER this Trainer
        was built — honor the optimizer's flag from here on (unless the
        caller explicitly passed offload_opt_state=False). Shared by the
        per-step and superstep entry points."""
        if (not self._offload and not self._offload_explicit
                and getattr(self.optimizer, "_offload_opt_state", False)):
            self._offload = True
            self.opt_state = self._place_opt_state("pinned_host")

    def apply_plan(self, plan, devices=None):
        """Adopt a sharding-planner plan (ISSUE 11): place params and
        optimizer state per the emitted ``ShardingPlan`` and return the
        mesh to train under. The next dispatch recompiles against the
        new placements automatically (the compile-cache aval signature
        includes shardings). Usage::

            report = auto_parallel.plan(cfg, n_devices=8)
            hm = trainer.apply_plan(report.chosen.plan)
            with hm:
                trainer.fit(loader, steps=...)
        """
        from ..parallel.api import shard_optimizer_state
        hm = plan.apply(self.model, devices=devices)
        self.params = dict(self.model.raw_parameters())
        self.opt_state = shard_optimizer_state(
            self.opt_state, plan.param_specs, mesh=hm)
        # remembered so fit() can hand the plan to the checkpoint manager
        # (saves record it as _PLAN.json; restores on a different mesh
        # reshard against it) without extra caller wiring
        self._active_plan = plan
        self._active_mesh = hm
        return hm

    def train_step(self, batch: Dict[str, jax.Array]) -> jax.Array:
        """One optimization step. ``batch`` maps forward kwarg names to
        arrays (e.g. {"input_ids": ..., "labels": ...}). Returns the loss
        as a DEVICE scalar — callers fence (float()) only when they need
        the value."""
        self._adopt_offload_flag()
        self._ensure_built()
        if self._watchdog is not None:
            self._watchdog.tick()
        lr = self._lr_scalar()
        kd = self._key_data()
        if self._offload:
            # pull the state up for the step, push the update back down:
            # host<->device streams around a device-resident step (the
            # transient device copy is donated straight into the update).
            # In-jit memory-space annotation is deliberately not used —
            # mixed-space operands are rejected by XLA and the CPU test
            # backend lacks annotate_device_placement entirely.
            self.opt_state = self._place_opt_state("device")
        self.params, self.opt_state, loss = self._dispatch(
            "step", (self.params, self.opt_state, batch, lr, kd))
        if self._offload:
            self.opt_state = self._place_opt_state("pinned_host")
        self._step += 1
        self.dispatch_stats["steps"] += 1
        if self._donate:
            # donation invalidates the previous param buffers, which the
            # Layer's Parameters still reference — rebind them to the new
            # arrays so imperative model use never touches deleted buffers
            self.sync_model()
        sched = self.optimizer.lr_scheduler
        if sched is not None:
            sched.step()
        return loss

    # -- full loop with metrics ---------------------------------------------

    def fit(self, data: Iterable[Dict[str, jax.Array]], steps: int,
            log_every: int = 10, on_metrics: Optional[Callable] = None,
            seq_len: Optional[int] = None, checkpoint_manager=None,
            resume=None, anomaly_guard=None, preemption_guard=None,
            steps_per_dispatch: int = 1):
        """Run the training loop. Beyond the metrics loop, this is the
        fault-tolerant runtime (resilience subsystem):

        * ``checkpoint_manager`` (resilience.CheckpointManager): periodic
          saves every ``save_interval_steps`` plus a final synchronous save;
        * ``resume="auto"``: restore params/opt_state/step/LR-scheduler from
          the newest COMMITTED checkpoint and fast-forward the data cursor
          (via ``data.set_state_dict`` when the loader supports it). With
          resume, ``steps`` is the TOTAL step budget of the run — a relaunch
          trains to the same target as an uninterrupted run;
        * ``preemption_guard`` (resilience.PreemptionGuard): on SIGTERM the
          loop writes one final sync checkpoint at the next step boundary
          and raises TrainingPreempted (exit code = resumable);
        * ``anomaly_guard`` (resilience.AnomalyGuard): NaN/Inf or loss-spike
          steps are skipped (undo the update; needs donate=False) or rolled
          back to the last good checkpoint, within bounded budgets. With
          ``check_every > 1`` (and a non-skip policy) loss verdicts are
          consumed as a batched window — ONE device fence per window instead
          of one per step;
        * ``steps_per_dispatch=K`` (superstep): K steps compiled into one
          ``lax.scan`` dispatch over stacked batches; losses are fetched
          asynchronously at log/anomaly/checkpoint boundaries. Bit-identical
          to K=1 (shared step body). Checkpoint/anomaly cadence aligns to
          dispatch boundaries (first boundary at-or-after the configured
          interval); resume may land mid-superstep — the next dispatch is
          simply sized ``min(K, target - step)``. Incompatible with
          ``policy="skip"`` (a mid-scan poisoned update cannot be undone
          from pre-step references). The hung-step watchdog
          (``PT_STEP_TIMEOUT_S``) is ticked per DISPATCH and around window
          fetches, so calibrate it against ``ring_depth*K`` step times, not
          one.
        """
        # hung-step watchdog (PT_STEP_TIMEOUT_S): armed only for the
        # duration of this bounded loop — inter-step gaps here ARE steps
        # (device sync + next-batch wait), so a stall is a real hang, and
        # stopping it on exit means eval/checkpoint phases outside fit()
        # can never trigger a spurious kill (reference:
        # phi/core/distributed/comm_task_manager.cc per-task timeouts)
        from ..distributed.watchdog import watchdog_from_env
        if self._watchdog is None:
            self._watchdog = watchdog_from_env()
        if resume and checkpoint_manager is None:
            raise ValueError("resume requires a checkpoint_manager")
        if (anomaly_guard is not None and anomaly_guard.policy == "skip"
                and self._donate):
            raise ValueError(
                "AnomalyGuard(policy='skip') requires Trainer(donate=False): "
                "undoing a poisoned update needs pre-step parameter "
                "references, which buffer donation invalidates. Use "
                "policy='rollback' (with a checkpoint_manager) or disable "
                "donation.")
        K = max(1, int(steps_per_dispatch))
        if K > 1 and anomaly_guard is not None \
                and anomaly_guard.policy == "skip":
            raise ValueError(
                "steps_per_dispatch>1 cannot honor AnomalyGuard("
                "policy='skip'): a poisoned update inside a compiled "
                "superstep cannot be undone from pre-step references. Use "
                "policy='rollback' (checkpoint-backed) or "
                "steps_per_dispatch=1.")
        if checkpoint_manager is not None and self._aot_dir is None:
            # precompiled AOT artifacts live next to the checkpoints — a
            # resumed worker picks them up without re-tracing
            d = os.path.join(checkpoint_manager.root, "_compile_cache")
            if os.path.isdir(d):
                self._aot_dir = d
        if (checkpoint_manager is not None and self._active_plan is not None
                and getattr(checkpoint_manager, "plan", None) is None):
            # hand the applied ShardingPlan to the manager: saves record
            # it as _PLAN.json, and a restore whose saved plan has
            # different axes goes through the reshard path (ISSUE 15)
            checkpoint_manager.plan = self._active_plan
            if checkpoint_manager.mesh is None:
                checkpoint_manager.mesh = getattr(
                    self._active_mesh, "mesh", self._active_mesh)
            if checkpoint_manager.spec_tree is None:
                checkpoint_manager.spec_tree = dict(
                    self._active_plan.param_specs)
        if (checkpoint_manager is not None
                and _obs.flight_recorder.recorder().active):
            # crash dumps land next to the quarantine dir so a post-mortem
            # ships with the checkpoint state it describes
            _obs.flight_recorder.set_dir(
                os.path.join(checkpoint_manager.root, "_flight"))
        # goodput ledger: the whole fit window is accounted wall-time;
        # everything not claimed by a span (compile/save/restore/preempt)
        # books as productive_step, and metering happens only at the
        # boundaries this loop already crosses — no new device fences
        led = _obs.ledger()
        led.run_start()
        try:
            if resume and checkpoint_manager is not None:
                self._resume_from(checkpoint_manager, data)
                target = int(steps)
            else:
                target = self._step + int(steps)
            it = iter(data)
            history = []
            t_last = time.perf_counter()
            tokens_since = 0
            loss = None
            if K > 1:
                return self._fit_superstep(it, target, K, log_every,
                                           on_metrics, seq_len, history,
                                           mgr=checkpoint_manager,
                                           anomaly=anomaly_guard,
                                           guard=preemption_guard, data=data)
            return self._fit_loop(it, target, log_every, on_metrics, seq_len,
                                  history, t_last, tokens_since, loss,
                                  mgr=checkpoint_manager,
                                  anomaly=anomaly_guard,
                                  guard=preemption_guard, data=data)
        finally:
            led.run_end()
            if _obs.enabled():
                _obs.publish()       # goodput buckets + snapshot -> exporters
            if self._watchdog is not None:
                self._watchdog.stop()
                self._watchdog = None

    def _publish_step_costs(self, m: "TrainMetrics", kind: str = "step",
                            steps_per_exec: int = 1) -> None:
        """Cost-observatory gauges at a log boundary (ISSUE 9): the
        measured step time split into compute/collective/host/stall, plus
        analytical MFU / HBM-BW utilization and the predicted-over-
        measured drift ratio — all derived from the ACTIVE executable's
        optimized HLO by the one ``observability/costs`` analyzer.
        Lazily attached, cached per executable, and fully guarded: the
        loop never fails (or slows down, beyond one HLO parse per
        compile) on account of its own telemetry."""
        if not _obs.enabled():
            return
        try:
            if (self._cost_watch is None
                    or self._cost_watch_kind != kind):
                from ..observability.costs import CostWatch
                self._cost_watch = CostWatch("train")
                self._cost_watch_kind = kind
            watch = self._cost_watch
            # attribute the executable the clocked window actually
            # dispatched (re-observed on change — bucketed shapes mean
            # several live executables; reports are cached per id)
            if self._last_exec_kind == kind:
                watch.observe_executable(self._last_exec)
            # per-WINDOW host overhead: the lifetime average would carry
            # the first dispatch's trace+compile seconds forever and the
            # host bucket would swallow the whole breakdown
            ds = self.dispatch_stats
            mark = getattr(self, "_cost_disp_mark", None) or (0, 0.0)
            dsteps = ds["steps"] - mark[0]
            dhost = ds["dispatch_host_s"] - mark[1]
            self._cost_disp_mark = (ds["steps"], ds["dispatch_host_s"])
            if dsteps <= 0 or dhost < 0:      # stats were reset externally
                dsteps, dhost = max(ds["steps"], 1), ds["dispatch_host_s"]
            watch.publish(m.step_time_s, host_s=dhost / max(dsteps, 1),
                          steps_per_exec=steps_per_exec)
        except Exception:
            pass

    def _fit_loop(self, it, target, log_every, on_metrics, seq_len,
                  history, t_last, tokens_since, loss, mgr=None, anomaly=None,
                  guard=None, data=None):
        # anomaly windowing: policy="skip" must fence every step (the undo
        # needs pre-step references from BEFORE the next step runs);
        # rollback/abort verdicts can consume a batched loss window — one
        # device fence per check_every steps (satellite: trainer.py:283)
        window = []
        per_step_check = (anomaly is None or anomaly.policy == "skip"
                          or getattr(anomaly, "check_every", 1) <= 1)
        while self._step < target:
            if guard is not None and guard.preempted:
                if window:
                    it, _ = self._drain_loss_window(window, anomaly, mgr,
                                                    data, it)
                self._preempt_exit(mgr, data)
            try:
                batch = next(it)
            except StopIteration:
                break
            ids = batch.get("input_ids")
            ntok = int(ids.shape[0] * ids.shape[1]) if ids is not None else 0
            prev = None
            if anomaly is not None and not self._donate:
                # pre-step references (immutable jax arrays — free to hold)
                # let "skip" undo a poisoned update without any checkpoint
                sched = self.optimizer.lr_scheduler
                prev = (self.params, self.opt_state,
                        sched.state_dict() if sched is not None else None)
            loss = self.train_step(batch)
            tokens_since += ntok
            if anomaly is not None:
                if per_step_check:
                    verdict = anomaly.check(float(loss))
                    if verdict != "ok":
                        it = self._handle_anomaly(verdict, anomaly, mgr,
                                                  prev, data, it,
                                                  float(loss))
                        continue
                else:
                    window.append((self._step, loss))
                    if len(window) >= anomaly.check_every:
                        it, rolled = self._drain_loss_window(
                            window, anomaly, mgr, data, it)
                        if rolled:
                            continue
            if self._step % log_every == 0:
                loss_v = float(loss)  # blocks; amortized over log_every
                now = time.perf_counter()
                dt = now - t_last
                tps = tokens_since / dt if dt > 0 else 0.0
                n_dev = jax.device_count()
                sl = seq_len or (ids.shape[1] if ids is not None else 1)
                fpt = (self.model.flops_per_token(sl)
                       if hasattr(self.model, "flops_per_token") else 0.0)
                mfu = (tps / n_dev) * fpt / self._peak if fpt else 0.0
                m = TrainMetrics(step=self._step, loss=loss_v,
                                 step_time_s=dt / log_every,
                                 tokens_per_sec=tps,
                                 tokens_per_sec_per_chip=tps / n_dev,
                                 mfu=mfu, lr=self.optimizer.get_lr())
                history.append(m)
                _obs.observe_train_metrics(m)
                self._publish_step_costs(m)
                # SLO sentry (ISSUE 10): rules evaluate at the same log
                # boundary the gauges above were refreshed at — no
                # sentry installed or plane off is a load + branch
                _obs.sentry.maybe_tick()
                if on_metrics:
                    on_metrics(m)
                t_last = time.perf_counter()
                tokens_since = 0
            if guard is not None and guard.preempted:
                if window:
                    it, _ = self._drain_loss_window(window, anomaly, mgr,
                                                    data, it)
                self._preempt_exit(mgr, data)
            if (mgr is not None
                    and self._step % mgr.save_interval_steps == 0
                    and self._step < target):
                if window:
                    # never checkpoint params the guard has not cleared
                    it, rolled = self._drain_loss_window(window, anomaly,
                                                         mgr, data, it)
                    if rolled:
                        continue
                self._save_ckpt(mgr, data)
        if window:
            it, rolled = self._drain_loss_window(window, anomaly, mgr,
                                                 data, it)
            if rolled and self._step < target:
                # rollback at the tail re-enters training for the remainder
                return self._fit_loop(it, target, log_every, on_metrics,
                                      seq_len, history,
                                      time.perf_counter(), 0, loss, mgr=mgr,
                                      anomaly=anomaly, guard=guard,
                                      data=data)
        if guard is not None and guard.preempted:
            self._preempt_exit(mgr, data)
        if mgr is not None:
            self._save_ckpt(mgr, data, async_save=False)
        # write trained params back into the Layer (imperative view);
        # train_step already does this when donation is on
        self.sync_model()
        return history

    # -- superstep loop ------------------------------------------------------

    def _fit_superstep(self, it, target, K, log_every, on_metrics, seq_len,
                       history, mgr=None, anomaly=None, guard=None,
                       data=None):
        """K-steps-per-dispatch loop: stack K batches → ONE compiled scan →
        append the [K] device loss vector to a small in-flight ring. The
        host only fences at boundaries (ring full / log / anomaly window /
        checkpoint / end), so between boundaries the device queue stays
        full and per-step host work is one dict lookup + enqueue."""
        from ..io.dataloader import stack_batches
        self._adopt_offload_flag()
        self._ensure_built()
        ring = []          # (last_step, [ntok per step], device losses [k])
        ring_depth = 2
        state = {"tokens": 0, "steps": 0, "t_last": time.perf_counter(),
                 "sl": seq_len or 1}
        last_saved = self._step
        exhausted = False

        def drain(it):
            """Fetch every pending loss window with ONE host sync, then run
            anomaly verdicts + metric emission in step order."""
            nonlocal exhausted
            if not ring:
                return it, False
            entries = list(ring)
            ring.clear()
            if self._watchdog is not None:
                self._watchdog.tick()    # the fetch below blocks on device
            flat = np.asarray(jnp.concatenate([e[2] for e in entries]))
            if self._watchdog is not None:
                self._watchdog.tick()
            # amortized timing: every step since the last emission shares
            # the wall span [t_last, now] equally — multiple log boundaries
            # inside ONE drain must not each claim a microsecond window
            # (that read as multi-million tokens/sec)
            now = time.perf_counter()
            new_steps = sum(len(e[1]) for e in entries)
            span = max(now - state["t_last"], 1e-9)
            per_step_s = span / max(state["steps"] + new_steps, 1)
            i = 0
            for last_step, ntoks, _ in entries:
                first = last_step - len(ntoks) + 1
                for j, ntok in enumerate(ntoks):
                    step = first + j
                    v = float(flat[i])
                    i += 1
                    if anomaly is not None:
                        verdict = anomaly.check(v)
                        if verdict != "ok":
                            # a rollback rewinds a stateful loader to the
                            # checkpoint cursor — the replay pass may have
                            # batches even if the old iterator ran dry
                            exhausted = False
                            return self._handle_anomaly(
                                verdict, anomaly, mgr, None, data, it,
                                v), True
                    state["tokens"] += ntok
                    state["steps"] += 1
                    if step % log_every == 0:
                        dt = per_step_s * max(state["steps"], 1)
                        tps = state["tokens"] / dt if dt > 0 else 0.0
                        n_dev = jax.device_count()
                        fpt = (self.model.flops_per_token(state["sl"])
                               if hasattr(self.model, "flops_per_token")
                               else 0.0)
                        mfu = (tps / n_dev) * fpt / self._peak if fpt else 0.0
                        sched = self.optimizer.lr_scheduler
                        # the host scheduler mirror has already advanced past
                        # this window — report the LR AT the logged step
                        # (same convention as the per-step loop: lr of
                        # metric.step)
                        lr_at = (float(np.asarray(sched.lr_of(step)))
                                 if sched is not None
                                 else self.optimizer.get_lr())
                        m = TrainMetrics(
                            step=step, loss=v,
                            step_time_s=per_step_s,
                            tokens_per_sec=tps,
                            tokens_per_sec_per_chip=tps / n_dev,
                            mfu=mfu, lr=lr_at)
                        history.append(m)
                        _obs.observe_train_metrics(m)
                        self._publish_step_costs(m, kind="superstep",
                                                 steps_per_exec=K)
                        _obs.sentry.maybe_tick()
                        if on_metrics:
                            on_metrics(m)
                        # advance by the consumed share; the steps after the
                        # last boundary keep their slice of the span
                        state["t_last"] += dt
                        state["tokens"] = 0
                        state["steps"] = 0
            return it, False

        while True:
            if guard is not None and guard.preempted:
                it, _ = drain(it)
                self._preempt_exit(mgr, data)
            if self._step >= target or exhausted:
                it, rolled = drain(it)
                if rolled and self._step < target:
                    # re-anchor the save cadence at the restored step, or
                    # the whole replay window would go uncheckpointed
                    last_saved = self._step
                    continue
                break
            k = min(K, target - self._step)
            batches = []
            try:
                while len(batches) < k:
                    batches.append(next(it))
            except StopIteration:
                exhausted = True
                if not batches:
                    continue
                k = len(batches)   # loader tail: smaller final dispatch
            if self._watchdog is not None:
                self._watchdog.tick()
            ids = batches[-1].get("input_ids")
            if seq_len is None and ids is not None:
                state["sl"] = ids.shape[1]
            ntoks = [int(b["input_ids"].shape[0] * b["input_ids"].shape[1])
                     if b.get("input_ids") is not None else 0
                     for b in batches]
            start = self._step
            sched = self.optimizer.lr_scheduler
            if sched is not None and getattr(sched, "functional", False):
                # LR computed in-jit from the step counter; the stack is a
                # dead scan input (zeros keep the signature K-shaped)
                lr_stack = jnp.zeros((k,), jnp.float32)
            elif sched is not None:
                lr_stack = jnp.asarray(
                    [sched.lr_of(start + i) for i in range(k)], jnp.float32)
            else:
                lr_stack = jnp.full((k,), float(self.optimizer.get_lr()),
                                    jnp.float32)
            stack = stack_batches(batches)
            if self._offload:
                self.opt_state = self._place_opt_state("device")
            self.params, self.opt_state, losses = self._dispatch(
                "superstep", (self.params, self.opt_state, stack, lr_stack,
                              self._key_data()))
            if self._offload:
                self.opt_state = self._place_opt_state("pinned_host")
            self._step += k
            self.dispatch_stats["steps"] += k
            if self._donate:
                self.sync_model()
            if sched is not None:
                for _ in range(k):     # host mirror advances at boundaries
                    sched.step()
            ring.append((self._step, ntoks, losses))
            crossed_log = (self._step // log_every) > (start // log_every)
            if len(ring) >= ring_depth or crossed_log:
                it, rolled = drain(it)
                if rolled:
                    last_saved = self._step
                    continue
            if (mgr is not None and self._step < target
                    and (self._step // mgr.save_interval_steps)
                    > (last_saved // mgr.save_interval_steps)):
                it, rolled = drain(it)   # validate before checkpointing
                last_saved = self._step
                if rolled:
                    continue
                # async: the save enqueues (synchronous device->host
                # snapshot, background serialize/IO) and the NEXT
                # superstep dispatches immediately — the write overlaps
                # compute instead of extending the drain. Commit
                # (PENDING -> _COMMITTED, PR 1 protocol) happens at the
                # manager's next finalize: the following save, a
                # restore, or the sync end-of-fit save below (ISSUE 14).
                self._save_ckpt(mgr, data, async_save=True)
        if guard is not None and guard.preempted:
            self._preempt_exit(mgr, data)
        if mgr is not None:
            self._save_ckpt(mgr, data, async_save=False)
        self.sync_model()
        return history

    # -- resilience runtime --------------------------------------------------

    def _save_ckpt(self, mgr, data, async_save=None):
        """One checkpoint save from the fit loop: traced as a
        trainer::checkpoint span, watermarked in the goodput ledger (the
        anchor a later rollback reclassifies against)."""
        with RecordEvent("trainer::checkpoint"):
            # async_save=None = manager default (same contract as
            # CheckpointManager.save itself)
            mgr.save(self._step, self._ckpt_tree(data),
                     async_save=async_save, watchdog=self._watchdog)
        _obs.ledger().note_checkpoint(self._step)

    def _drain_loss_window(self, window, anomaly, mgr, data, it):
        """Consume a pending (step, device-loss) window with ONE device→host
        sync; returns ``(iterator, rolled_back)``. Verdicts run in step
        order so budgets/EWMA see the same sequence the per-step path
        would."""
        entries = list(window)
        window.clear()
        if self._watchdog is not None:
            self._watchdog.tick()        # the fetch below blocks on device
        vals = np.asarray(jnp.stack([l for _, l in entries]))
        for (s, _), v in zip(entries, vals):
            verdict = anomaly.check(float(v))
            if verdict != "ok":
                return self._handle_anomaly(verdict, anomaly, mgr, None,
                                            data, it, float(v)), True
        return it, False

    def _ckpt_tree(self, data=None):
        """Full training state as one checkpointable tree. The structure is
        FIXED (extra always present, same keys) so the restore target always
        matches the saved layout."""
        sched = self.optimizer.lr_scheduler
        if data is not None and hasattr(data, "state_dict"):
            # the loader's own count: batches actually handed out this pass.
            # NOT self._step — anomaly skips consume a batch without keeping
            # the step, so the two drift apart exactly when resume must not
            # replay the poisoned batch
            cursor = int(data.state_dict().get("batches_served", self._step))
        else:
            cursor = self._step    # 1 batch per step for stateless iterables
        return {
            "step": np.asarray(self._step, np.int64),
            "params": self.params,
            "opt_state": self.opt_state,
            "extra": {
                "sched_last_epoch": np.asarray(
                    sched.last_epoch if sched is not None else -1, np.int64),
                # last_lr as VALUE, not formula: adaptive schedulers
                # (ReduceOnPlateau) cannot recompute it from last_epoch
                "sched_last_lr": np.asarray(
                    sched.last_lr if sched is not None else -1.0, np.float64),
                "data_cursor": np.asarray(cursor, np.int64),
            },
        }

    def _apply_restored(self, tree) -> int:
        self.params = tree["params"]
        self.opt_state = tree["opt_state"]
        if self._offload:
            self.opt_state = self._place_opt_state("pinned_host")
        self._step = int(np.asarray(tree["step"]))
        sched = self.optimizer.lr_scheduler
        le = int(np.asarray(tree["extra"]["sched_last_epoch"]))
        llr = float(np.asarray(tree["extra"]["sched_last_lr"]))
        if sched is not None and le >= 0:
            # set_state_dict, NOT step(epoch=le): ReduceOnPlateau.step is a
            # no-op without metrics, which would silently reset its decayed
            # LR to the constructor value
            sched.set_state_dict({"last_epoch": le, "last_lr": (
                llr if llr >= 0 else sched.last_lr)})
        self._lr_cache = None     # host LR may have moved: re-sync the scalar
        self._fast_exec = {}      # restored arrays may carry new placements
        self.sync_model()
        return int(np.asarray(tree["extra"]["data_cursor"]))

    def _resume_from(self, mgr, data) -> Optional[int]:
        """resume="auto": restore the newest committed checkpoint (corrupt
        ones are quarantined by the manager and the previous step is used)
        and position the data cursor."""
        res = mgr.restore(self._ckpt_tree(), watchdog=self._watchdog)
        if res is None:
            return None          # nothing saved yet: cold start
        step, tree = res
        cursor = self._apply_restored(tree)
        if hasattr(data, "set_state_dict"):
            data.set_state_dict({"batches_served": cursor})
        return step

    def _preempt_exit(self, mgr, data=None):
        """Step-boundary preemption: one final SYNCHRONOUS checkpoint, then
        exit with the resumable status (the elastic relauncher resumes
        instead of restarting)."""
        from ..resilience.preemption import TrainingPreempted
        # the wind-down books as preemption_lost (minus the nested
        # checkpoint_save span the manager opens for the final save)
        with _obs.ledger().span("preemption_lost"):
            if mgr is not None:
                self._save_ckpt(mgr, data, async_save=False)
            self.sync_model()
            if _obs.REGISTRY.enabled:
                _obs.REGISTRY.counter(
                    "pt_preemptions_total",
                    "orderly SIGTERM checkpoint-and-exit events").inc()
            _obs.flight_recorder.maybe_dump(
                "preemption", extra={"step": self._step})
        raise TrainingPreempted(self._step)

    def _handle_anomaly(self, verdict, anomaly, mgr, prev, data, it, loss):
        """Apply the anomaly verdict; returns the (possibly replaced) data
        iterator."""
        from ..resilience.anomaly import SKIP
        if verdict == SKIP and prev is not None:
            # undo this step's (poisoned) update in memory and move past
            # the batch
            params, opt_state, sched_sd = prev
            self.params, self.opt_state = params, opt_state
            sched = self.optimizer.lr_scheduler
            if sched is not None and sched_sd is not None:
                sched.set_state_dict(sched_sd)
            self._step -= 1
            self.sync_model()
            return it
        if verdict == "abort" or mgr is None:
            # no checkpoint to roll back to (or policy says die): fail loudly
            anomaly.raise_divergence(self._step, loss)
        res = mgr.restore(self._ckpt_tree(), watchdog=self._watchdog)
        if res is None:
            anomaly.raise_divergence(self._step, loss)
        _, tree = res
        cursor = self._apply_restored(tree)
        # productive time since the restored step's watermark is replayed
        # ground: reclassify it as rollback_wasted
        _obs.ledger().note_rollback(self._step)
        if data is not None and hasattr(data, "set_state_dict"):
            # replay from the checkpointed cursor; without a stateful
            # loader the current iterator continues forward (documented:
            # rollback then sees new batches rather than a replay)
            close = getattr(it, "close", None)
            if close is not None:
                close()        # retire the old pass (and its prefetch thread)
            data.set_state_dict({"batches_served": cursor})
            return iter(data)
        return it

    def sync_model(self):
        for k, v in self.params.items():
            self._named[k].value = v

    def state_dict(self):
        return {"params": self.params, "opt_state": self.opt_state,
                "step": self._step}

    def set_state_dict(self, sd):
        self.params = sd["params"]
        self.opt_state = sd["opt_state"]
        self._step = sd["step"]
