"""DataLoader: batched, prefetching host→device input pipeline.

Reference: python/paddle/io/dataloader/dataloader_iter.py:150,358 —
single-process and multi-process iterators; worker processes feed batches
through shared memory (mmap allocator) with a prefetch depth of
``num_workers * prefetch_factor``.

TPU-first redesign: the expensive device is fed by an *async prefetcher* that
overlaps host-side batch assembly with device compute:

- worker parallelism uses a thread pool by default (numpy slicing releases
  the GIL; no fork() hazards with a live XLA runtime — the reference's
  fork-based workers are unsafe next to initialized accelerators) and a
  process pool (`multiprocessing_context='spawn'`) when the per-sample
  transform is Python-bound;
- `prefetch_to_device` moves finished batches onto the accelerator
  (optionally with a NamedSharding for per-host sharded global arrays) ahead
  of the consumer, the device_put analogue of the reference's
  pin-memory+H2D stream overlap.
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
from concurrent.futures import ThreadPoolExecutor, ProcessPoolExecutor
from typing import Any, Callable, Iterator, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, SequenceSampler, RandomSampler


def default_collate_fn(batch):
    """Stack samples into batch arrays (reference:
    dataloader/collate.py default_collate_fn): dict → dict of stacked,
    tuple/list → tuple of stacked, scalars/arrays → stacked ndarray."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, collections.abc.Mapping):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, collections.abc.Sequence):
        transposed = list(zip(*batch))
        return tuple(default_collate_fn(list(f)) for f in transposed)
    # jax arrays / arbitrary array-likes
    try:
        return np.stack([np.asarray(b) for b in batch])
    except Exception:
        return list(batch)


def stack_batches(batches):
    """Stack a list of per-step batches into one superstep feed: every leaf
    gains a leading ``[K, ...]`` dispatch dimension (the trainer's
    ``fit(steps_per_dispatch=K)`` scans over it). Stacking happens with
    jnp so device-prefetched batches stay on device — no host round trip.
    Composes with gradient accumulation: ``[A, ...]`` microbatch arrays
    stack to ``[K, A, ...]``."""
    import jax
    import jax.numpy as jnp

    if not batches:
        raise ValueError("stack_batches needs at least one batch")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *batches)


def superbatches(iterable, k: int, drop_last: bool = False):
    """Group an iterable of batches into stacked superstep feeds of ``k``
    (the final partial group is yielded unstacked-shorter unless
    ``drop_last``). Useful for feeding ``Trainer.fit(steps_per_dispatch=k)``
    from a pipeline that wants the stacking off the training thread."""
    buf = []
    for b in iterable:
        buf.append(b)
        if len(buf) == k:
            yield stack_batches(buf)
            buf = []
    if buf and not drop_last:
        yield stack_batches(buf)


def _fetch_map(dataset, indices, collate_fn):
    return collate_fn([dataset[i] for i in indices])


_WORKER_STATE = {}


_WORKER_ID_LOCK = threading.Lock()


def _worker_init(dataset, collate_fn, num_workers=0):
    _WORKER_STATE["dataset"] = dataset
    _WORKER_STATE["collate_fn"] = collate_fn
    import multiprocessing as mp
    ident = mp.current_process()._identity
    if ident:  # pool worker process: 1-based fork-order id
        worker_id = (ident[0] - 1) % max(num_workers, 1)
    else:  # thread pool: processwide counter + lock
        with _WORKER_ID_LOCK:
            worker_id = _WORKER_STATE.setdefault("_next_id", 0)
            _WORKER_STATE["_next_id"] = worker_id + 1
    _set_worker_info(WorkerInfo(id=worker_id, num_workers=num_workers,
                                dataset=dataset))


def _worker_fetch(indices):
    return _fetch_map(_WORKER_STATE["dataset"], indices,
                      _WORKER_STATE["collate_fn"])


def _shm_worker_loop(ring_name, index_queue, dataset, collate_fn):
    """Worker-process loop for the native shared-memory transport: pop
    (seq, indices) work items, fetch+collate, push pickled batches into the
    ShmRing (reference: the mmap-allocator path of dataloader_iter.py:358)."""
    import pickle
    from paddle_tpu.native import ShmRing
    ring = ShmRing.open(ring_name)
    try:
        while True:
            item = index_queue.get()
            if item is None:
                ring.push(pickle.dumps(("__worker_done__", None)), timeout=600)
                return
            seq, indices = item
            try:
                batch = _fetch_map(dataset, indices, collate_fn)
                payload = pickle.dumps((seq, batch), protocol=4)
            except BaseException as e:  # surface in the parent
                payload = pickle.dumps((seq, e), protocol=4)
            ring.push(payload, timeout=600)
    finally:
        ring._h = None  # opener must never shm_unlink; the parent owns it


class _PrefetchIterator:
    """Pulls batches from an executor pipeline with bounded depth."""

    def __init__(self, submit_iter: Iterator, depth: int):
        self._submit_iter = submit_iter
        self._pending = collections.deque()
        self._depth = max(depth, 1)
        self._fill()

    def _fill(self):
        while len(self._pending) < self._depth:
            try:
                self._pending.append(next(self._submit_iter))
            except StopIteration:
                break

    def __iter__(self):
        return self

    def __next__(self):
        if not self._pending:
            raise StopIteration
        fut = self._pending.popleft()
        self._fill()
        return fut.result() if hasattr(fut, "result") else fut


class DataLoader:
    """Reference-shaped DataLoader (paddle.io.DataLoader).

    Args mirror the reference: dataset, batch_size, shuffle, drop_last,
    collate_fn, num_workers, prefetch_factor, batch_sampler. TPU additions:
    ``prefetch_to_device`` (device_put finished batches ahead of use) and
    ``sharding`` (a NamedSharding applied on transfer — per-host sharded
    global batches for multi-host input).
    """

    def __init__(self, dataset: Dataset, batch_size: Optional[int] = 1,
                 shuffle: bool = False, drop_last: bool = False,
                 collate_fn: Optional[Callable] = None, num_workers: int = 0,
                 prefetch_factor: int = 2,
                 batch_sampler: Optional[BatchSampler] = None,
                 use_shared_memory: bool = False,  # accepted for parity
                 multiprocessing_context: Optional[str] = None,
                 prefetch_to_device: bool = False, sharding=None,
                 return_list: bool = True):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.use_shared_memory = use_shared_memory
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.prefetch_to_device = prefetch_to_device or sharding is not None
        self.sharding = sharding
        self.multiprocessing_context = multiprocessing_context
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            if batch_sampler is not None:
                raise ValueError("batch_sampler is invalid for IterableDataset")
            self.batch_size = batch_size
            self.drop_last = drop_last
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            if batch_size is None:
                raise ValueError("batch_size or batch_sampler required for "
                                 "map-style datasets")
            self.batch_size = batch_size
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
        self._batches_served = 0
        self._skip_batches = 0

    # -- iteration cursor (resilience: resume/rollback positions the loader)

    def state_dict(self):
        """Cursor of the current iteration pass: how many batches have been
        handed out (skipped-on-resume batches included, so a resumed pass
        continues the count). Checkpointed by Trainer.fit as the
        data-iterator cursor."""
        return {"batches_served": self._batches_served}

    def set_state_dict(self, sd) -> None:
        """Fast-forward the NEXT iteration pass past ``batches_served``
        batches. Batches are still fetched and dropped (not re-indexed), so
        for DETERMINISTIC samplers the resumed pass is bit-identical to an
        uninterrupted one. An unseeded shuffle draws a fresh permutation per
        pass — the skip-ahead then replays a different order (warned below);
        pass a seeded ``RandomSampler(data, generator=...)`` via
        ``batch_sampler`` for bit-exact shuffled resume."""
        self._skip_batches = max(0, int(sd.get("batches_served", 0)))
        # baseline the cursor NOW, not lazily at the pass's first next():
        # a checkpoint taken before the resumed pass yields its first batch
        # (e.g. preemption latched during restore) must not persist a stale
        # count from before this call
        self._batches_served = self._skip_batches
        samp = getattr(self.batch_sampler, "sampler", None)
        if self._skip_batches > 0 and isinstance(samp, RandomSampler):
            import warnings
            # unseeded: each pass draws fresh OS entropy. Seeded: the shared
            # generator's state advanced during the interrupted pass, so a
            # new pass STILL permutes differently. Either way the skip-ahead
            # replays a different order.
            warnings.warn(
                "resuming a shuffle=True DataLoader: a new pass draws a new "
                "permutation (RandomSampler state is not checkpointed), so "
                f"skipping the first {self._skip_batches} batches does not "
                "reproduce the pre-crash order — already-seen samples may "
                "repeat this epoch. Use shuffle=False (or a deterministic "
                "per-epoch sampler) for bit-exact resume.",
                RuntimeWarning, stacklevel=2)

    # -- iteration ---------------------------------------------------------

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _device_put(self, batch):
        if not self.prefetch_to_device:
            return batch
        import jax
        from jax.tree_util import tree_map
        if self.sharding is not None:
            return tree_map(lambda x: jax.device_put(x, self.sharding), batch)
        return tree_map(jax.device_put, batch)

    def _iter_batches_host(self):
        if self._iterable:
            it = iter(self.dataset)
            if self.batch_size is None:
                yield from it
                return
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
            return
        if self.num_workers == 0:
            for indices in self.batch_sampler:
                yield _fetch_map(self.dataset, indices, self.collate_fn)
            return
        if self.use_shared_memory:
            try:
                from paddle_tpu import native
                if native.is_available():
                    yield from self._iter_batches_shm()
                    return
            except Exception:
                pass  # fall through to the portable executor path
        # worker pool: submit index lists, consume in order with prefetch
        if self.multiprocessing_context is not None:
            import multiprocessing as mp
            # dataset/collate_fn ship ONCE via the initializer (worker
            # globals), not per submit — per-batch pickling of an in-memory
            # dataset would dwarf the fetch itself.
            pool = ProcessPoolExecutor(
                max_workers=self.num_workers,
                mp_context=mp.get_context(self.multiprocessing_context),
                initializer=_worker_init,
                initargs=(self.dataset, self.collate_fn, self.num_workers))
            fetch = _worker_fetch
            submit_args = lambda idx: (idx,)
        else:
            _WORKER_STATE.pop("_next_id", None)  # fresh ids per loader
            pool = ThreadPoolExecutor(
                max_workers=self.num_workers,
                initializer=_worker_init,
                initargs=(self.dataset, self.collate_fn, self.num_workers))
            fetch = _fetch_map
            submit_args = lambda idx: (self.dataset, idx, self.collate_fn)
        try:
            submits = (pool.submit(fetch, *submit_args(idx))
                       for idx in self.batch_sampler)
            yield from _PrefetchIterator(
                submits, self.num_workers * self.prefetch_factor)
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    def _iter_batches_shm(self):
        """Multi-process fetch over the native shared-memory ring: workers
        pickle batches straight into a process-shared ring buffer instead of
        the multiprocessing pipe, and the parent re-orders by sequence
        number. Mirrors the reference's shared-memory DataLoader fast path."""
        import pickle
        import multiprocessing as mp
        from paddle_tpu.native import ShmRing

        ctx = mp.get_context(self.multiprocessing_context or "spawn")
        ring = ShmRing(capacity=128 << 20)
        index_queue = ctx.Queue()
        procs = [ctx.Process(target=_shm_worker_loop,
                             args=(ring.name, index_queue, self.dataset,
                                   self.collate_fn), daemon=True)
                 for _ in range(self.num_workers)]
        for p in procs:
            p.start()
        try:
            total = 0
            depth = self.num_workers * self.prefetch_factor
            sampler_it = iter(self.batch_sampler)
            in_flight = 0
            for _ in range(depth):
                try:
                    index_queue.put((total, next(sampler_it)))
                    total += 1
                    in_flight += 1
                except StopIteration:
                    break
            next_seq = 0
            done_workers = 0
            stash = {}
            while in_flight > 0 or stash:
                while next_seq in stash:
                    item = stash.pop(next_seq)
                    next_seq += 1
                    if isinstance(item, BaseException):
                        raise item
                    yield item
                if in_flight == 0:
                    continue
                payload = None
                while payload is None:
                    try:
                        payload = ring.pop(timeout=5)
                        if payload is None:  # ring closed & drained
                            raise RuntimeError(
                                "DataLoader shared-memory ring closed with "
                                f"{in_flight} batches still pending")
                    except TimeoutError:
                        # a worker that crashed (unclean exit) takes its
                        # in-flight batch with it — even one such death means
                        # the missing seq will never arrive
                        dead = [p for p in procs
                                if not p.is_alive() and p.exitcode not in (0, None)]
                        if dead or not any(p.is_alive() for p in procs):
                            codes = [p.exitcode for p in procs]
                            raise RuntimeError(
                                "DataLoader shared-memory worker(s) died "
                                f"unexpectedly (exit codes {codes}) with "
                                f"{in_flight} batches still pending") from None
                seq, item = pickle.loads(payload)
                if seq == "__worker_done__":
                    done_workers += 1
                    continue
                in_flight -= 1
                stash[seq] = item
                try:
                    index_queue.put((total, next(sampler_it)))
                    total += 1
                    in_flight += 1
                except StopIteration:
                    pass
        finally:
            for _ in procs:
                index_queue.put(None)
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()
            ring.destroy()

    def __call__(self):
        """Legacy idiom parity: ``for batch in loader():`` — the reference
        DataLoader is callable and returns its iterator
        (python/paddle/io/reader.py doctest usage)."""
        return iter(self)

    def superbatches(self, k: int, drop_last: bool = False):
        """Iterate stacked superstep feeds of ``k`` batches each (see
        :func:`stack_batches`). The cursor (``batches_served``) still counts
        MICRObatches, so checkpoint resume positions are step-granular."""
        return superbatches(iter(self), k, drop_last=drop_last)

    def __iter__(self):
        skip = self._skip_batches
        self._skip_batches = 0
        # the replayed prefix counts as served so a resumed pass continues
        # the cursor; the per-yield increment below counts only batches the
        # CONSUMER actually received (prefetched-but-unconsumed batches in
        # the device queue must not advance the checkpointed cursor)
        self._batches_served = skip

        def host_skipped():
            n = 0
            for b in self._iter_batches_host():
                n += 1
                if n <= skip:
                    continue   # fast-forward host-side: no device transfer
                yield b

        for batch in self._iter_all(host_skipped()):
            self._batches_served += 1
            yield batch

    def _iter_all(self, host):
        if not self.prefetch_to_device:
            yield from host
            return
        # async device prefetch: keep `prefetch_factor` batches in flight
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_factor)
        _END = object()
        stop = threading.Event()

        def bounded_put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for b in host:
                    if not bounded_put(self._device_put(b)):
                        return             # consumer gone (close/rollback)
                bounded_put(_END)
            except BaseException as e:  # propagate into the consumer
                bounded_put(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # abandoned mid-pass (generator .close(), trainer rollback):
            # unblock and retire the producer so it cannot keep device
            # buffers pinned for the rest of the run
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5.0)


class WorkerInfo:
    """Worker context for IterableDataset sharding (reference:
    python/paddle/io/dataloader/worker.py WorkerInfo/get_worker_info)."""

    def __init__(self, id: int, num_workers: int, dataset=None, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_WORKER_INFO = threading.local()


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a DataLoader worker returns its WorkerInfo; None in the main
    process (reference: io/dataloader/worker.py get_worker_info)."""
    return getattr(_WORKER_INFO, "info", None)


def _set_worker_info(info: Optional[WorkerInfo]) -> None:
    _WORKER_INFO.info = info
