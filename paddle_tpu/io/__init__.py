"""paddle_tpu.io — data input pipeline.

Reference: python/paddle/io/ (Dataset family, samplers, DataLoader with
multiprocess workers and shared-memory transfer — SURVEY.md §2.4 io/data).
TPU redesign notes in dataloader.py.
"""

from .dataset import (Dataset, IterableDataset, TensorDataset, ComposeDataset,
                      ChainDataset, ConcatDataset, Subset, random_split)
from .sampler import (Sampler, SequenceSampler, RandomSampler, SubsetRandomSampler,
                      WeightedRandomSampler, BatchSampler,
                      DistributedBatchSampler)
from .dataloader import (DataLoader, default_collate_fn, get_worker_info,
                         WorkerInfo, stack_batches, superbatches)

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "default_collate_fn", "stack_batches", "superbatches",
]
