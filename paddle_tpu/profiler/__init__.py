"""paddle_tpu.profiler — host + device tracing with the reference's API shape.

Reference: python/paddle/profiler/profiler.py:346 ``Profiler`` (scheduler at
``make_scheduler:117``, chrome export at ``export_chrome_tracing:215``),
stats in profiler_statistic.py, ips timer in timer.py; C++ engine
paddle/fluid/platform/profiler/ (HostTracer RecordEvent instrumentation +
CUPTI CudaTracer).

TPU-native redesign: host events are collected in-process (perf_counter_ns
spans per thread); the device side is XLA's own profiler (jax.profiler →
xplane/TensorBoard trace, the CUPTI slot). The scheduler state machine,
RecordEvent instrumentation API, chrome-trace export, and summary stats keep
the reference's shape so profiling code ports 1:1.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict
from enum import Enum, IntEnum
from typing import Callable, Iterable, Optional

__all__ = [
    "ProfilerTarget", "ProfilerState", "make_scheduler", "RecordEvent",
    "Profiler", "export_chrome_tracing", "export_protobuf", "load_profiler_result",
    "SummaryView", "SortedKeys", "benchmark", "SERVING_EVENTS",
    "serving_trace",
]

# tick-level spans the async ContinuousBatchingEngine emits through
# RecordEvent (near-zero cost unless a Profiler is recording): request
# admission, per-slot prefill (full or chunked), decode-block dispatch,
# and the async device→host drain/reconcile. A chrome trace of one
# serving run shows dispatch N+1 opening before drain N closes — the
# overlap the engine's in-flight window exists to create.
SERVING_EVENTS = ("serving::admit", "serving::prefill",
                  "serving::dispatch", "serving::drain")


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last RECORD step of a cycle: trace is handed out


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Step→state schedule (reference profiler.py:117): skip_first CLOSED
    steps once, then cycles of [closed CLOSED | ready READY | record RECORD],
    the last record step returning RECORD_AND_RETURN. repeat=0 → forever."""
    if closed < 0 or ready < 0 or record <= 0:
        raise ValueError("make_scheduler: closed/ready >= 0, record >= 1")
    span = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat and step >= repeat * span:
            return ProfilerState.CLOSED
        pos = step % span
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == span - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def _default_scheduler(step: int) -> ProfilerState:
    # reference default: record everything from start()
    return ProfilerState.RECORD


# ---------------------------------------------------------------------------
# host event collection
# ---------------------------------------------------------------------------

class _HostEvent:
    __slots__ = ("name", "start_ns", "end_ns", "tid", "event_type")

    def __init__(self, name, start_ns, end_ns, tid, event_type):
        self.name = name
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.tid = tid
        self.event_type = event_type


class _Collector:
    """Process-wide host-event sink; enabled only while a Profiler records."""

    def __init__(self):
        self.events: list[_HostEvent] = []
        self.enabled = False
        self._lock = threading.Lock()

    def add(self, ev: _HostEvent):
        with self._lock:
            if self.enabled:
                self.events.append(ev)

    def drain(self) -> list[_HostEvent]:
        with self._lock:
            evs, self.events = self.events, []
        return evs


_collector = _Collector()

# optional second sink: a bounded deque the observability flight recorder
# attaches so the last few hundred spans survive to a crash dump EVEN when
# no Profiler is recording. None (the default) keeps RecordEvent's
# near-zero disabled cost: one module-global load + None check.
_flight_sink = None


def set_flight_sink(sink) -> None:
    """Attach/detach (None) the flight-recorder span ring. Entries are
    ``(name, start_ns, end_ns, tid, event_type)`` tuples appended at span
    end; the deque's maxlen bounds memory."""
    global _flight_sink
    _flight_sink = sink


class RecordEvent:
    """Instrumentation span (reference: paddle.profiler.RecordEvent; C++
    platform/profiler RecordEvent). Usable as context manager or
    begin()/end() pair; near-zero overhead when no profiler is recording."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._start_ns = None

    def begin(self):
        self._start_ns = time.perf_counter_ns()

    def end(self):
        if self._start_ns is None:
            return
        if _collector.enabled:
            _collector.add(_HostEvent(self.name, self._start_ns,
                                      time.perf_counter_ns(),
                                      threading.get_ident(), self.event_type))
        sink = _flight_sink
        if sink is not None:
            sink.append((self.name, self._start_ns, time.perf_counter_ns(),
                         threading.get_ident(), self.event_type))
        self._start_ns = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


# ---------------------------------------------------------------------------
# trace result + exporters
# ---------------------------------------------------------------------------

class ProfilerResult:
    def __init__(self, events: list[_HostEvent], step_range, device_trace_dir):
        self.events = events
        self.step_range = step_range
        self.device_trace_dir = device_trace_dir

    def chrome_trace(self) -> dict:
        items = []
        for ev in self.events:
            items.append({
                "name": ev.name, "ph": "X", "cat": ev.event_type,
                "pid": os.getpid(), "tid": ev.tid,
                "ts": ev.start_ns / 1000.0,
                "dur": (ev.end_ns - ev.start_ns) / 1000.0,
            })
        return {"traceEvents": items,
                "metadata": {"framework": "paddle_tpu",
                             "steps": list(self.step_range)}}

    def save(self, path: str):
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready handler writing chrome://tracing JSON
    (reference profiler.py:215)."""
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof: "Profiler"):
        name = worker_name or f"host_{os.getpid()}"
        n = prof._export_count
        path = os.path.join(dir_name, f"{name}_step{n}.json")
        prof.result.save(path)
        return path

    return handler


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """Parity shim for the reference's protobuf exporter: the device side is
    already written as xplane protos by jax.profiler into the trace dir; the
    host side exports chrome JSON next to it."""
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


class SortedKeys(IntEnum):
    """Summary sort orders (reference: python/paddle/profiler/profiler.py
    SortedKeys enum). IntEnum: reference code compares members to ints.
    Host events are the only table here (the device side is xplane), so
    the GPU* keys sort by the same host aggregates as their CPU twins."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


# sort key per order: aggregate of the per-name duration list, table sorted
# DESCENDING on it (largest first — the reference's convention); *Min uses
# the smallest single call so "which op has the worst best-case" reads off
# the top.
_SORT_AGG = {
    SortedKeys.CPUTotal: sum, SortedKeys.GPUTotal: sum,
    SortedKeys.CPUAvg: lambda d: sum(d) / len(d),
    SortedKeys.GPUAvg: lambda d: sum(d) / len(d),
    SortedKeys.CPUMax: max, SortedKeys.GPUMax: max,
    SortedKeys.CPUMin: min, SortedKeys.GPUMin: min,
}


# ---------------------------------------------------------------------------
# Profiler
# ---------------------------------------------------------------------------

class Profiler:
    """Reference-shaped profiler (profiler.py:346).

        p = Profiler(scheduler=make_scheduler(closed=1, ready=1, record=2),
                     on_trace_ready=export_chrome_tracing("./prof"))
        p.start()
        for step, batch in enumerate(loader):
            train(batch)
            p.step()
        p.stop()
        print(p.summary())

    ``timer_only=True`` collects ips/step timing without event tracing.
    Device-side tracing (XLA xplane) activates when ``trace_device=True`` and
    writes TensorBoard-compatible traces into ``device_trace_dir``.
    """

    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, trace_device: bool = False,
                 device_trace_dir: str = "./profiler_device_trace"):
        del targets  # host events always on; device via trace_device
        if scheduler is None:
            self.scheduler = _default_scheduler
        elif callable(scheduler):
            self.scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            lo, hi = scheduler
            self.scheduler = make_scheduler(closed=max(lo, 0), ready=0,
                                            record=hi - lo, repeat=1)
        else:
            raise TypeError(f"bad scheduler {scheduler!r}")
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.trace_device = trace_device
        self.device_trace_dir = device_trace_dir
        self.step_num = 0
        self.result: Optional[ProfilerResult] = None
        self._state = ProfilerState.CLOSED
        self._record_start_step = 0
        self._export_count = 0
        self._step_times: list[float] = []
        self._samples_total = 0
        self._last_step_t: Optional[float] = None
        self._device_tracing = False

    # -- state machine -----------------------------------------------------

    def _transition(self, new_state: ProfilerState):
        old = self._state
        recording = lambda s: s in (ProfilerState.RECORD,
                                    ProfilerState.RECORD_AND_RETURN)
        # RECORD_AND_RETURN marks the *last* step of a cycle: its trace is
        # exported on the next transition regardless of destination state,
        # so back-to-back cycles (RAR→RECORD, RAR→RAR) each export.
        was_recording = recording(old)
        if old == ProfilerState.RECORD_AND_RETURN:
            self._finish_cycle()
            was_recording = False
        if old == new_state and new_state != ProfilerState.RECORD_AND_RETURN \
                and was_recording == recording(new_state):
            return
        if not was_recording and recording(new_state):
            self._record_start_step = self.step_num
            if not self.timer_only:
                _collector.enabled = True
                _collector.drain()
            if self.trace_device:
                self._start_device_trace()
        elif was_recording and not recording(new_state):
            self._finish_cycle()
        self._state = new_state

    def _start_device_trace(self):
        try:
            import jax
            os.makedirs(self.device_trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.device_trace_dir)
            self._device_tracing = True
        except Exception:
            self._device_tracing = False

    def _stop_device_trace(self):
        if self._device_tracing:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_tracing = False

    def _finish_cycle(self):
        _collector.enabled = False
        events = _collector.drain()
        self._stop_device_trace()
        self.result = ProfilerResult(
            events, range(self._record_start_step, self.step_num + 1),
            self.device_trace_dir if self.trace_device else None)
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)
        self._export_count += 1

    # -- user API ----------------------------------------------------------

    def start(self):
        self._last_step_t = time.perf_counter()
        self._transition(self.scheduler(self.step_num))

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
            if num_samples is not None:
                # samples processed by the step that just finished —
                # accumulated so step_info can report TRUE samples/sec
                self._samples_total += int(num_samples)
        self._last_step_t = now
        self.step_num += 1
        self._transition(self.scheduler(self.step_num))

    def stop(self):
        self._transition(ProfilerState.CLOSED)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- reporting ---------------------------------------------------------

    def step_info(self, unit: str = "samples/sec") -> str:
        """Throughput line. When ``step(num_samples=...)`` supplied sample
        counts, reports accumulated-samples / elapsed ("<rate> <unit>");
        otherwise the rate is steps/sec and is LABELED steps/sec — the old
        behavior reported steps/sec under a "samples/sec" banner."""
        if not self._step_times:
            return "no steps recorded"
        total = sum(self._step_times)
        avg = total / len(self._step_times)
        if self._samples_total and total > 0:
            rate, label = self._samples_total / total, unit
        else:
            rate, label = 1.0 / avg, "steps/sec"
        return f"avg step time {avg * 1000:.2f} ms ({rate:.2f} {label})"

    def summary(self, sorted_by=None, views=None) -> str:
        """Aggregated per-name host-event table (profiler_statistic shape),
        sorted by ``sorted_by`` (a :class:`SortedKeys`, its int value, or
        None = CPUTotal)."""
        if sorted_by is None:
            sorted_by = SortedKeys.CPUTotal
        elif not isinstance(sorted_by, SortedKeys):
            sorted_by = SortedKeys(sorted_by)
        agg_fn = _SORT_AGG[sorted_by]
        agg: dict[str, list[float]] = defaultdict(list)
        events = self.result.events if self.result else []
        for ev in events:
            agg[ev.name].append((ev.end_ns - ev.start_ns) / 1e6)
        rows = sorted(agg.items(), key=lambda kv: -agg_fn(kv[1]))
        lines = [f"{'Name':<40} {'Calls':>6} {'Total(ms)':>12} "
                 f"{'Avg(ms)':>10} {'Max(ms)':>10} {'Min(ms)':>10}"]
        for name, durs in rows:
            lines.append(f"{name[:40]:<40} {len(durs):>6} {sum(durs):>12.3f} "
                         f"{sum(durs) / len(durs):>10.3f} {max(durs):>10.3f} "
                         f"{min(durs):>10.3f}")
        lines.append(self.step_info())
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# ips benchmark timer (reference: python/paddle/profiler/timer.py, used by
# hapi to report ips)
# ---------------------------------------------------------------------------

class _BenchmarkTimer:
    def __init__(self):
        self.reset()

    def reset(self):
        self._times: list[float] = []
        self._samples: list[int] = []
        self._t0: Optional[float] = None

    def begin(self):
        self._t0 = time.perf_counter()

    def step(self, num_samples: int = 1):
        now = time.perf_counter()
        if self._t0 is not None:
            self._times.append(now - self._t0)
            self._samples.append(num_samples)
        self._t0 = now

    def report(self) -> dict:
        if not self._times:
            return {"ips": 0.0, "avg_step_ms": 0.0, "steps": 0}
        total = sum(self._times)
        return {"ips": sum(self._samples) / total if total else 0.0,
                "avg_step_ms": total / len(self._times) * 1000.0,
                "steps": len(self._times)}


_benchmark = _BenchmarkTimer()


def benchmark() -> _BenchmarkTimer:
    """Global ips timer (reference: paddle.profiler.utils.benchmark)."""
    return _benchmark


class serving_trace:
    """Context manager tracing a serving-engine run into a chrome trace:

        with profiler.serving_trace("./prof") as p:
            engine.run()
        # ./prof/<worker>_step0.json: admit/prefill/dispatch/drain spans

    Wraps a RECORD-always Profiler wired to ``export_chrome_tracing`` so
    the engine's SERVING_EVENTS spans (and any other RecordEvent in the
    process) land in one chrome://tracing JSON per recording."""

    def __init__(self, dir_name: str, worker_name: Optional[str] = None,
                 trace_device: bool = False):
        self._prof = Profiler(
            on_trace_ready=export_chrome_tracing(dir_name, worker_name),
            trace_device=trace_device)

    def __enter__(self) -> Profiler:
        self._prof.start()
        return self._prof

    def __exit__(self, *exc):
        self._prof.stop()
        return False
