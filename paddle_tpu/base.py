"""Base framework plumbing: places, mode switches, grad-mode guards,
ParamAttr, DataParallel, print options, RNG-state capture.

Reference: python/paddle/base/{framework.py,core.py,dygraph/base.py} and
python/paddle/framework/random.py. On TPU the runtime underneath is jax —
places map to jax.Device, "dynamic vs static mode" collapses (ops are
functional and trace-friendly either way), and grad-mode guards gate our
autograd surface (autograd.no_grad) rather than a global tracer.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


# -- places (reference: paddle.CPUPlace/CUDAPlace/...; phi Place) ------------

class _Place:
    """Device handle with the reference's Place API shape. Resolves to a
    jax.Device; accepted anywhere paddle_tpu takes a ``place``/``device``."""

    _platform: str = "cpu"

    def __init__(self, device_id: int = 0):
        self._id = int(device_id)

    def get_device_id(self) -> int:
        return self._id

    def jax_device(self):
        devs = [d for d in jax.devices() if d.platform == self._platform]
        if not devs:  # graceful degrade (e.g. CUDAPlace on a TPU host)
            devs = jax.devices()
        return devs[min(self._id, len(devs) - 1)]

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._id == other._id)

    def __hash__(self):
        return hash((type(self).__name__, self._id))

    def __repr__(self):
        return f"{type(self).__name__}({self._id})"


class CPUPlace(_Place):
    _platform = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(_Place):
    _platform = "tpu"


class CUDAPlace(_Place):
    """Accepted for API parity; resolves to the accelerator (TPU) if
    present, else CPU — there is no CUDA in this stack."""
    _platform = "tpu"


class CUDAPinnedPlace(_Place):
    _platform = "cpu"

    def __init__(self):
        super().__init__(0)


class IPUPlace(_Place):
    _platform = "cpu"

    def __init__(self):
        super().__init__(0)


class XPUPlace(_Place):
    _platform = "tpu"


# -- dynamic/static mode (reference: base/framework.py in_dynamic_mode) ------

_static_mode = threading.local()


def in_dynamic_mode() -> bool:
    """True unless ``enable_static`` was called. Ops behave identically in
    both modes here (jax traces the same functions); the switch only drives
    the static.Program facade (static/__init__.py)."""
    return not getattr(_static_mode, "on", False)


def in_dynamic_or_pir_mode() -> bool:
    return True


def enable_static() -> None:
    _static_mode.on = True


def disable_static() -> None:
    _static_mode.on = False


def disable_signal_handler() -> None:
    """No-op: jax installs no signal handlers to disable (reference:
    paddle.disable_signal_handler guards the C++ fault handlers)."""


# -- grad-mode guards (reference: base/dygraph/base.py) ----------------------

_grad_mode = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_grad_mode, "enabled", True)


@contextlib.contextmanager
def _grad_guard(flag: bool):
    prev = is_grad_enabled()
    _grad_mode.enabled = flag
    try:
        yield
    finally:
        _grad_mode.enabled = prev


def enable_grad():
    """Context manager enabling gradient tracking (paddle.enable_grad)."""
    return _grad_guard(True)


def set_grad_enabled(mode: bool):
    """Context manager pinning grad mode (paddle.set_grad_enabled)."""
    return _grad_guard(bool(mode))


# -- ParamAttr / LazyGuard (reference: python/paddle/base/param_attr.py) -----

class ParamAttr:
    """Parameter attribute bundle (name/initializer/lr/regularizer/
    trainable). Layers accept it for ``weight_attr``/``bias_attr``; fields
    map onto Parameter metadata + the optimizer's per-param options."""

    def __init__(self, name: Optional[str] = None, initializer=None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, do_model_average: bool = True,
                 need_clip: bool = True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


class LazyGuard:
    """Context manager deferring parameter materialization (reference:
    python/paddle/fluid/lazy_init.py LazyGuard). Inside the guard,
    ``create_parameter`` produces ABSTRACT values (jax.ShapeDtypeStruct)
    instead of running initializers — so an 8B/70B model can be
    constructed for sharding-plan and memory-fit analysis (eval_shape
    style) without materializing a single weight. Materialize later by
    re-building the model outside the guard, or use the abstract tree with
    jax.jit(...).lower() / NamedSharding.shard_shape."""

    _active = False

    def __enter__(self):
        self._prev = type(self)._active
        type(self)._active = True
        return self

    def __exit__(self, *exc):
        type(self)._active = self._prev   # nesting-safe restore
        return False


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Free-standing parameter factory (paddle.create_parameter /
    paddle.static.create_parameter)."""
    from .nn import initializer as init_mod
    from .nn.layer import Parameter
    from .core import dtype as _dt
    trainable = attr.trainable if attr is not None else True
    if LazyGuard._active:
        import jax
        import numpy as _np
        value = jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                     _np.dtype(_dt.convert_dtype(dtype)))
        return Parameter(value, trainable=trainable)
    init = default_initializer
    if attr is not None and getattr(attr, "initializer", None) is not None:
        init = attr.initializer
    if init is None:
        init = init_mod.Constant(0.0) if is_bias else init_mod.XavierUniform()
    value = init(tuple(int(s) for s in shape), _dt.convert_dtype(dtype))
    return Parameter(value, trainable=trainable)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from .core import dtype as _dt
    return jnp.full(tuple(int(s) for s in shape), value,
                    _dt.convert_dtype(dtype))


# -- DataParallel (reference: python/paddle/distributed/parallel.py:202) -----

def DataParallel(layer, strategy=None, comm_buffer_size_MB: int = 25,
                 last_comm_buffer_size_MB: int = 1,
                 find_unused_parameters: bool = False, group=None):
    """DP wrapper. Under GSPMD there is no reducer to install: marking the
    batch dim sharded over "dp" makes XLA emit the fused gradient
    all-reduces the EagerReducer provides in the reference
    (collective/reducer.cc). The layer itself is returned (its parameters
    replicated, inputs expected dp-sharded) — kept callable for API parity
    with ``paddle.DataParallel(model)``."""
    from .parallel.mesh import current_mesh
    mesh = current_mesh()
    if mesh is not None and "dp" in mesh.axis_names:
        from .parallel.api import shard_layer
        shard_layer(layer)
    return layer


# -- print options (reference: python/paddle/tensor/to_string.py) ------------

_print_opts = {"precision": 8, "threshold": 1000, "edgeitems": 3,
               "linewidth": 80, "sci_mode": None}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Mirrors paddle.set_printoptions by driving numpy's print options
    (arrays print through numpy)."""
    kw = {}
    if precision is not None:
        _print_opts["precision"] = kw["precision"] = int(precision)
    if threshold is not None:
        _print_opts["threshold"] = kw["threshold"] = int(threshold)
    if edgeitems is not None:
        _print_opts["edgeitems"] = kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        _print_opts["linewidth"] = kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        _print_opts["sci_mode"] = sci_mode
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


# -- RNG state capture (reference: python/paddle/framework/random.py) --------

def get_rng_state(device=None):
    """Snapshot all named RNG streams (keys + counters) as an opaque,
    picklable state list."""
    from .core.rng import rng_tracker
    tr = rng_tracker()
    return [{"name": n,
             "key": np.asarray(jax.random.key_data(k)),
             "counter": tr._counters.get(n, 0)}
            for n, k in tr._keys.items()]


def set_rng_state(state_list, device=None):
    from .core.rng import rng_tracker
    tr = rng_tracker()
    for st in state_list:
        key = jax.random.wrap_key_data(jnp.asarray(st["key"]))
        tr.add(st["name"], key)
        tr._counters[st["name"]] = int(st["counter"])


def get_cuda_rng_state():
    """Accelerator alias of get_rng_state (no separate device generator:
    jax PRNG keys are device-agnostic values)."""
    return get_rng_state()


def set_cuda_rng_state(state_list):
    set_rng_state(state_list)


def check_shape(shape):
    """Validate a shape argument the way paddle.static.nn checks inputs."""
    if isinstance(shape, (list, tuple)):
        for s in shape:
            if not isinstance(s, (int, np.integer)) and s is not None:
                raise TypeError(f"shape entries must be int, got {type(s)}")
    return True


# doctests use paddle.base.set_flags/get_flags (reference: base/framework.py)
from .core.flags import get_flags, set_flags  # noqa: E402,F401
