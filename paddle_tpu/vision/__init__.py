"""paddle_tpu.vision — vision domain library (reference: python/paddle/vision/).

Subpackages: transforms (host-side preprocessing with native C++ normalize
fast path), datasets (local-file readers + hermetic fake data), models
(classification backbones; OCR det/rec live in paddle_tpu.models.vision).
"""

from . import ops
from . import transforms

# reference layout is a PACKAGE (vision/transforms/{transforms,functional});
# ours is one module carrying both the classes and the functional surface.
# Register the functional submodule path so the reference import idiom
# `import paddle.vision.transforms.functional as F` works verbatim.
import sys as _sys
transforms.functional = transforms
_sys.modules[__name__ + ".transforms.functional"] = transforms
from . import datasets
from . import models
from .models import (LeNet, VGG, vgg11, vgg13, vgg16, vgg19, MobileNetV1,
                     MobileNetV2, mobilenet_v1, mobilenet_v2, ResNet,
                     resnet18, resnet34, resnet50, resnet101, SqueezeNet,
                     squeezenet1_0)
from .datasets import (MNIST, FashionMNIST, Cifar10, Cifar100,
                       FakeImageDataset, DatasetFolder, ImageFolder)

__all__ = ["transforms", "datasets", "models", "ops"]

# -- image backend control (reference: python/paddle/vision/image.py) -------
_IMAGE_BACKEND = "pil"


def get_image_backend() -> str:
    return _IMAGE_BACKEND


def set_image_backend(backend: str) -> None:
    global _IMAGE_BACKEND
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"expected 'pil', 'cv2' or 'tensor', got {backend!r}")
    _IMAGE_BACKEND = backend


def image_load(path: str, backend=None):
    """Load an image via the active backend (reference: vision/image.py
    image_load). cv2 is not shipped; PIL covers decode."""
    backend = backend or _IMAGE_BACKEND
    from PIL import Image
    img = Image.open(path)
    if backend in ("cv2", "tensor"):
        import numpy as np
        return np.asarray(img)
    return img


__all__ += ["get_image_backend", "set_image_backend", "image_load"]

from . import image  # paddle.vision.image module path

from ..utils import register_submodule_aliases as _rsa
from . import models as _models, datasets as _datasets
_rsa(__name__ + ".models", {n: _models for n in (
    "resnet", "vgg", "mobilenetv1", "mobilenetv2", "mobilenetv3",
    "densenet", "alexnet", "squeezenet", "googlenet", "inceptionv3",
    "shufflenetv2", "lenet")})
_rsa(__name__ + ".datasets", {n: _datasets for n in (
    "mnist", "cifar", "flowers", "voc2012")})
