"""paddle_tpu.vision — vision domain library (reference: python/paddle/vision/).

Subpackages: transforms (host-side preprocessing with native C++ normalize
fast path), datasets (local-file readers + hermetic fake data), models
(classification backbones; OCR det/rec live in paddle_tpu.models.vision).
"""

from . import transforms
from . import datasets
from . import models
from .models import (LeNet, VGG, vgg11, vgg13, vgg16, vgg19, MobileNetV1,
                     MobileNetV2, mobilenet_v1, mobilenet_v2, ResNet,
                     resnet18, resnet34, resnet50, resnet101, SqueezeNet,
                     squeezenet1_0)
from .datasets import (MNIST, FashionMNIST, Cifar10, Cifar100,
                       FakeImageDataset, DatasetFolder, ImageFolder)

__all__ = ["transforms", "datasets", "models"]
