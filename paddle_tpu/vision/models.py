"""paddle_tpu.vision.models — classification backbones (reference:
python/paddle/vision/models/: LeNet, VGG, ResNet, MobileNetV1/V2/V3,
GoogLeNet, ShuffleNetV2, ...).

ResNet (+OCR det/rec heads) live in paddle_tpu.models.vision; this module
adds the remaining reference families that the target configs touch. All
NCHW, bf16-friendly, compiled by XLA (convs tile onto the MXU; no custom
kernels needed at these sizes).
"""

from __future__ import annotations

from .. import nn
from ..models.vision import (ResNet, resnet18, resnet50, BasicBlock,
                             BottleneckBlock, ConvBNLayer)

__all__ = [
    "LeNet", "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2",
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
    "SqueezeNet", "squeezenet1_0",
]


class LeNet(nn.Layer):
    """reference: python/paddle/vision/models/lenet.py"""

    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Flatten(), nn.Linear(400, 120), nn.Linear(120, 84),
            nn.Linear(84, num_classes))

    def forward(self, x):
        return self.fc(self.features(x))


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def make_layers(cfg, batch_norm: bool = False):
    """Build the VGG feature extractor from a config list (reference:
    vision/models/vgg.py make_layers — ints are conv widths, 'M' pools)."""
    layers = []
    in_ch = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_ch, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_ch = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    """reference: python/paddle/vision/models/vgg.py — takes a FEATURES
    layer (make_layers result) like the reference; a config-letter string
    is also accepted and built internally."""

    def __init__(self, features="D", num_classes: int = 1000,
                 batch_norm: bool = False, with_pool: bool = True):
        super().__init__()
        if isinstance(features, str):
            features = make_layers(_VGG_CFGS[features],
                                   batch_norm=batch_norm)
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        self.classifier = nn.Sequential(
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(0.5),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(0.5),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        x = x.reshape(x.shape[0], -1)
        return self.classifier(x)


def vgg11(**kw):
    return VGG("A", **kw)


def vgg13(**kw):
    return VGG("B", **kw)


def vgg16(**kw):
    return VGG("D", **kw)


def vgg19(**kw):
    return VGG("E", **kw)


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_ch, out_ch, stride):
        super().__init__()
        self.dw = ConvBNLayer(in_ch, in_ch, 3, stride=stride, groups=in_ch)
        self.pw = ConvBNLayer(in_ch, out_ch, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    """reference: python/paddle/vision/models/mobilenetv1.py"""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000):
        super().__init__()
        s = lambda c: max(int(c * scale), 8)
        cfg = [(s(32), s(64), 1), (s(64), s(128), 2), (s(128), s(128), 1),
               (s(128), s(256), 2), (s(256), s(256), 1), (s(256), s(512), 2),
               *[(s(512), s(512), 1)] * 5,
               (s(512), s(1024), 2), (s(1024), s(1024), 1)]
        self.stem = ConvBNLayer(3, s(32), 3, stride=2)
        self.blocks = nn.Sequential(
            *[_DepthwiseSeparable(i, o, st) for i, o, st in cfg])
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        x = self.pool(x).reshape(x.shape[0], -1)
        return self.fc(x)


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_ch * expand_ratio))
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(in_ch, hidden, 1, act="relu6"))
        layers += [ConvBNLayer(hidden, hidden, 3, stride=stride, groups=hidden,
                               act="relu6"),
                   ConvBNLayer(hidden, out_ch, 1, act=None)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """reference: python/paddle/vision/models/mobilenetv2.py"""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000):
        super().__init__()
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_ch = max(int(32 * scale), 8)
        self.stem = ConvBNLayer(3, in_ch, 3, stride=2, act="relu6")
        blocks = []
        for t, c, n, s in cfg:
            out_ch = max(int(c * scale), 8)
            for i in range(n):
                blocks.append(_InvertedResidual(in_ch, out_ch,
                                                s if i == 0 else 1, t))
                in_ch = out_ch
        self.blocks = nn.Sequential(*blocks)
        last = max(int(1280 * scale), 1280)
        self.head = ConvBNLayer(in_ch, last, 1, act="relu6")
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(nn.Dropout(0.2),
                                        nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.head(self.blocks(self.stem(x)))
        x = self.pool(x).reshape(x.shape[0], -1)
        return self.classifier(x)


def mobilenet_v1(scale: float = 1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


def mobilenet_v2(scale: float = 1.0, **kw):
    return MobileNetV2(scale=scale, **kw)


def resnet34(**kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], **kw)


def resnet101(**kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], **kw)


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_ch, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1),
                                     nn.ReLU())

    def forward(self, x):
        import jax.numpy as jnp
        s = self.squeeze(x)
        return jnp.concatenate([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    """reference: python/paddle/vision/models/squeezenet.py — takes
    version ('1.0' 7x7 stem / '1.1' 3x3 stem, earlier pools),
    num_classes, with_pool like the reference signature."""

    def __init__(self, version: str = "1.0", num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        if version not in ("1.0", "1.1"):
            raise ValueError(f"supported versions are '1.0' and '1.1', "
                             f"but input version is {version!r}")
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        head = [nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU()]
        if with_pool:
            head.append(nn.AdaptiveAvgPool2D(1))
        self.classifier = nn.Sequential(*head)

    def forward(self, x):
        x = self.classifier(self.features(x))
        if self.with_pool:
            x = x.reshape(x.shape[0], -1)
        return x


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **kw)


def resnet34(**kw):  # noqa: F811 — original kept above; ensure export
    return ResNet(34, **kw)


# -- round-3 parity batch: deep/grouped/wide + classic families -------------
from .models_extras import (  # noqa: E402
    AlexNet, alexnet, DenseNet, densenet121, densenet161, densenet169,
    densenet201, densenet264, GoogLeNet, googlenet, InceptionV3,
    inception_v3, MobileNetV3Small, MobileNetV3Large, mobilenet_v3_small,
    mobilenet_v3_large, ShuffleNetV2, shufflenet_v2_x0_25,
    shufflenet_v2_x0_33, shufflenet_v2_x0_5, shufflenet_v2_x1_0,
    shufflenet_v2_x1_5, shufflenet_v2_x2_0, shufflenet_v2_swish,
    resnet152, resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
    resnext101_64x4d, resnext152_32x4d, resnext152_64x4d, wide_resnet50_2,
    wide_resnet101_2)
