"""paddle_tpu.vision.transforms — host-side image preprocessing.

Reference: python/paddle/vision/transforms/{transforms.py,functional.py}
(Compose, Resize, RandomCrop, Normalize, ToTensor, ...).

TPU-first design: transforms run on the *host* over numpy/PIL (they feed the
DataLoader workers; the chip only sees assembled batches), with the native
C++ normalize fast path (csrc/pt_native.cc pt_normalize_u8_f32) used for the
u8→f32 conversion that dominates input-pipeline time. Randomness uses
per-call numpy Generators seeded from the framework seed — reproducible and
fork-safe, no global PRNG state races between workers.
"""

from __future__ import annotations

import numbers
import random as _pyrandom
from typing import Callable, Optional, Sequence

import numpy as np

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
    "Normalize", "Transpose", "Pad", "RandomRotation", "Grayscale",
    "BrightnessTransform", "ContrastTransform", "SaturationTransform",
    "HueTransform", "ColorJitter", "RandomErasing",
    # functional
    "to_tensor", "resize", "crop", "center_crop", "hflip", "vflip",
    "normalize", "pad", "rotate", "to_grayscale", "adjust_brightness",
    "adjust_contrast", "adjust_hue", "erase",
]


def _is_pil(img):
    try:
        from PIL import Image
        return isinstance(img, Image.Image)
    except ImportError:
        return False


def _to_numpy(img) -> np.ndarray:
    """HWC uint8/float numpy view of a PIL image or ndarray."""
    if _is_pil(img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def _to_pil(arr: np.ndarray):
    from PIL import Image
    if arr.shape[-1] == 1:
        arr = arr[:, :, 0]
    return Image.fromarray(arr)


# ---------------------------------------------------------------------------
# functional
# ---------------------------------------------------------------------------

def to_tensor(img, data_format: str = "CHW") -> np.ndarray:
    """u8 HWC → f32 [0,1] CHW (reference: transforms.functional.to_tensor)."""
    arr = _to_numpy(img)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return arr


def resize(img, size, interpolation: str = "bilinear"):
    """size: int (short side) or (h, w)."""
    from PIL import Image
    pil = img if _is_pil(img) else _to_pil(_to_numpy(img).astype(np.uint8))
    w, h = pil.size
    if isinstance(size, int):
        if w <= h:
            ow, oh = size, max(int(size * h / w), 1)
        else:
            oh, ow = size, max(int(size * w / h), 1)
    else:
        oh, ow = size
    resample = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
                "bicubic": Image.BICUBIC, "lanczos": Image.LANCZOS}[interpolation]
    out = pil.resize((ow, oh), resample)
    return out if _is_pil(img) else _to_numpy(out)


def crop(img, top: int, left: int, height: int, width: int):
    arr = _to_numpy(img)
    out = arr[top:top + height, left:left + width]
    return _to_pil(out) if _is_pil(img) else out


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _to_numpy(img)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return crop(img, top, left, th, tw)


def hflip(img):
    arr = _to_numpy(img)[:, ::-1]
    return _to_pil(arr) if _is_pil(img) else arr


def vflip(img):
    arr = _to_numpy(img)[::-1]
    return _to_pil(arr) if _is_pil(img) else arr


def normalize(img, mean, std, data_format: str = "CHW",
              to_rgb: bool = False) -> np.ndarray:
    """(x - mean) / std. u8 HWC input takes the native C++ fast path."""
    arr = np.asarray(img)
    if arr.dtype == np.uint8 and data_format == "HWC":
        try:
            from ..native import normalize_images, is_available
            if is_available():
                # native op folds /255; reference Normalize does NOT rescale,
                # so pre-scale mean/std accordingly
                m = np.asarray(mean, np.float32) / 255.0
                s = np.asarray(std, np.float32) / 255.0
                return normalize_images(arr, m, s)
        except Exception:
            pass
    arr = arr.astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    return (arr - mean) / std


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    arr = _to_numpy(img)
    if isinstance(padding, numbers.Number):
        pl = pt_ = pr = pb = int(padding)
    elif len(padding) == 2:
        pl, pt_ = padding
        pr, pb = padding
    else:
        pl, pt_, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kwargs = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(arr, ((pt_, pb), (pl, pr), (0, 0)), mode=mode, **kwargs)
    return _to_pil(out) if _is_pil(img) else out


def rotate(img, angle: float, interpolation: str = "nearest", expand=False,
           center=None, fill=0):
    from PIL import Image
    pil = img if _is_pil(img) else _to_pil(_to_numpy(img).astype(np.uint8))
    resample = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
                "bicubic": Image.BICUBIC}[interpolation]
    out = pil.rotate(angle, resample=resample, expand=expand, center=center,
                     fillcolor=fill)
    return out if _is_pil(img) else _to_numpy(out)


def to_grayscale(img, num_output_channels: int = 1):
    arr = _to_numpy(img).astype(np.float32)
    gray = (0.2989 * arr[..., 0] + 0.5870 * arr[..., 1] + 0.1140 * arr[..., 2])
    gray = np.clip(gray, 0, 255).astype(np.uint8)[..., None]
    out = np.repeat(gray, num_output_channels, axis=-1)
    return _to_pil(out) if _is_pil(img) else out


def adjust_brightness(img, factor: float):
    arr = _to_numpy(img).astype(np.float32) * factor
    out = np.clip(arr, 0, 255).astype(np.uint8)
    return _to_pil(out) if _is_pil(img) else out


def adjust_contrast(img, factor: float):
    arr = _to_numpy(img).astype(np.float32)
    mean = arr.mean()
    out = np.clip((arr - mean) * factor + mean, 0, 255).astype(np.uint8)
    return _to_pil(out) if _is_pil(img) else out


def adjust_saturation(img, factor: float):
    arr = _to_numpy(img).astype(np.float32)
    gray = (0.2989 * arr[..., :1] + 0.5870 * arr[..., 1:2]
            + 0.1140 * arr[..., 2:3])
    out = np.clip(gray + (arr - gray) * factor, 0, 255).astype(np.uint8)
    return _to_pil(out) if _is_pil(img) else out


def adjust_hue(img, factor: float):
    """factor in [-0.5, 0.5] — fraction of the hue circle."""
    if not -0.5 <= factor <= 0.5:
        raise ValueError("hue factor must be in [-0.5, 0.5]")
    from PIL import Image
    pil = img if _is_pil(img) else _to_pil(_to_numpy(img).astype(np.uint8))
    hsv = np.asarray(pil.convert("HSV")).copy()
    hsv[..., 0] = (hsv[..., 0].astype(np.int16)
                   + int(factor * 255)) % 256
    out = Image.fromarray(hsv.astype(np.uint8), "HSV").convert("RGB")
    return out if _is_pil(img) else _to_numpy(out)


def erase(img, i: int, j: int, h: int, w: int, v, inplace: bool = False):
    arr = _to_numpy(img)
    arr = arr if inplace else arr.copy()
    arr[i:i + h, j:j + w] = v
    return _to_pil(arr) if _is_pil(img) else arr


# ---------------------------------------------------------------------------
# transform classes
# ---------------------------------------------------------------------------

class BaseTransform:
    """Reference: transforms.BaseTransform — keys select which elements of a
    (img, label, ...) tuple get transformed."""

    def __init__(self, keys: Optional[Sequence[str]] = None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if not isinstance(inputs, tuple):
            return self._apply_image(inputs)
        keys = self.keys or ("image",) * len(inputs)
        out = []
        for key, item in zip(keys, inputs):
            out.append(self._apply_image(item) if key == "image" else item)
        return tuple(out)


class Compose:
    def __init__(self, transforms: Sequence[Callable]):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format: str = "CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation: str = "bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed: bool = False,
                 fill=0, padding_mode: str = "constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = pad(img, self.padding, self.fill, self.padding_mode)
        arr = _to_numpy(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (0, 0, max(tw - w, 0), max(th - h, 0)), self.fill,
                      self.padding_mode)
            arr = _to_numpy(img)
            h, w = arr.shape[:2]
        top = _pyrandom.randint(0, max(h - th, 0))
        left = _pyrandom.randint(0, max(w - tw, 0))
        return crop(img, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation: str = "bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_numpy(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * _pyrandom.uniform(*self.scale)
            aspect = np.exp(_pyrandom.uniform(np.log(self.ratio[0]),
                                              np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                top = _pyrandom.randint(0, h - ch)
                left = _pyrandom.randint(0, w - cw)
                return resize(crop(img, top, left, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if _pyrandom.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if _pyrandom.random() < self.prob else img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format: str = "CHW",
                 to_rgb: bool = False, keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format
        self.to_rgb = to_rgb

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format, self.to_rgb)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.transpose(_to_numpy(img), self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode: str = "constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation: str = "nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = _pyrandom.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand, self.center,
                      self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels: int = 1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_brightness(img, _pyrandom.uniform(
            max(0, 1 - self.value), 1 + self.value))


class ContrastTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_contrast(img, _pyrandom.uniform(
            max(0, 1 - self.value), 1 + self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_saturation(img, _pyrandom.uniform(
            max(0, 1 - self.value), 1 + self.value))


class HueTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, _pyrandom.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        _pyrandom.shuffle(order)
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class RandomErasing(BaseTransform):
    def __init__(self, prob: float = 0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace: bool = False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if _pyrandom.random() >= self.prob:
            return img
        arr = _to_numpy(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * _pyrandom.uniform(*self.scale)
            aspect = np.exp(_pyrandom.uniform(np.log(self.ratio[0]),
                                              np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / aspect)))
            ew = int(round(np.sqrt(target * aspect)))
            if eh < h and ew < w:
                i = _pyrandom.randint(0, h - eh)
                j = _pyrandom.randint(0, w - ew)
                return erase(img, i, j, eh, ew, self.value, self.inplace)
        return img


# -- round-3 parity batch: affine/perspective (reference:
#    python/paddle/vision/transforms/{functional.py,transforms.py}) --------

def _affine_matrix(angle, translate, scale, shear, center):
    a = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in shear)
    cx, cy = center
    # paddle/torchvision convention: M = T(center) R(angle) Sh(shear)
    # Scale T(-center) + translate
    rot = np.array([[np.cos(a + sy) / np.cos(sy),
                     -np.cos(a + sy) * np.tan(sx) / np.cos(sy)
                     - np.sin(a), 0],
                    [np.sin(a + sy) / np.cos(sy),
                     -np.sin(a + sy) * np.tan(sx) / np.cos(sy)
                     + np.cos(a), 0],
                    [0, 0, 1]])
    rot[:2, :2] *= scale
    t_pre = np.array([[1, 0, cx + translate[0]], [0, 1, cy + translate[1]],
                      [0, 0, 1]])
    t_post = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]])
    return t_pre @ rot @ t_post




def _float_chw(arr):
    """True for paddle-Tensor-style images: float CHW with a small leading
    channel dim AND genuinely-image-sized spatial dims (a thin float HWC
    strip like (3, W, 3) must NOT be misread as CHW)."""
    return (isinstance(arr, np.ndarray) and arr.ndim == 3
            and arr.dtype.kind == "f" and arr.shape[0] in (1, 3, 4)
            and arr.shape[1] > 4 and arr.shape[2] > 4)


def _warp_via_pil(img, pil_fn, fill=0):
    """Apply a PIL-image warp to any input form: PIL stays PIL; uint8 HWC
    round-trips as before; float CHW tensors warp per channel in PIL mode
    F (32-bit float — no quantization) and come back float CHW.
    ``pil_fn(pil, fill_scalar)`` receives a per-channel scalar fill when
    the caller passed a sequence."""
    from PIL import Image

    def fill_for(c):
        if isinstance(fill, (list, tuple)):
            return fill[c] if c < len(fill) else fill[-1]
        return fill

    if _is_pil(img):
        return pil_fn(img, fill)
    arr = _to_numpy(img)
    if _float_chw(arr):
        outs = [np.asarray(pil_fn(Image.fromarray(
            np.ascontiguousarray(arr[c]).astype(np.float32), mode="F"),
            float(fill_for(c))))
            for c in range(arr.shape[0])]
        return np.stack(outs, axis=0).astype(arr.dtype)
    return _to_numpy(pil_fn(_to_pil(arr.astype(np.uint8)), fill))


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine warp (reference: vision/transforms/functional.py affine).
    Accepts PIL, uint8 HWC arrays, and float CHW tensors (warped in PIL
    mode F, no quantization)."""
    from PIL import Image
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    resample = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
                "bicubic": Image.BICUBIC}[interpolation]

    def warp(pil, fill_v):
        w, h = pil.size
        c = (w * 0.5, h * 0.5) if center is None else center
        m = _affine_matrix(angle, translate, scale, shear, c)
        inv = np.linalg.inv(m)
        return pil.transform((w, h), Image.AFFINE,
                             data=inv[:2].reshape(-1), resample=resample,
                             fillcolor=fill_v)

    return _warp_via_pil(img, warp, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective warp mapping startpoints->endpoints (reference:
    vision/transforms/functional.py perspective)."""
    from PIL import Image
    # solve the 8-dof homography endpoints -> startpoints (PIL convention)
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        b.append(sx)
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b.append(sy)
    coeffs = np.linalg.solve(np.asarray(a, np.float64),
                             np.asarray(b, np.float64))
    resample = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
                "bicubic": Image.BICUBIC}[interpolation]

    def warp(pil, fill_v):
        return pil.transform(pil.size, Image.PERSPECTIVE, data=coeffs,
                             resample=resample, fillcolor=fill_v)

    return _warp_via_pil(img, warp, fill)


class RandomAffine(BaseTransform):
    """reference: vision/transforms/transforms.py RandomAffine."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        self.degrees = ((-degrees, degrees)
                        if isinstance(degrees, numbers.Number) else degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        angle = _pyrandom.uniform(*self.degrees)
        w, h = (_to_numpy(img).shape[1], _to_numpy(img).shape[0])
        tx = ty = 0.0
        if self.translate is not None:
            tx = _pyrandom.uniform(-self.translate[0], self.translate[0]) * w
            ty = _pyrandom.uniform(-self.translate[1], self.translate[1]) * h
        scale = (_pyrandom.uniform(*self.scale) if self.scale is not None
                 else 1.0)
        if self.shear is None:
            shear = (0.0, 0.0)
        elif isinstance(self.shear, numbers.Number):
            shear = (_pyrandom.uniform(-self.shear, self.shear), 0.0)
        else:
            shear = (_pyrandom.uniform(-self.shear[0], self.shear[0]),
                     _pyrandom.uniform(-self.shear[1], self.shear[1])
                     if len(self.shear) > 1 else 0.0)
        return affine(img, angle, (tx, ty), scale, shear,
                      interpolation=self.interpolation, fill=self.fill,
                      center=self.center)

    def __call__(self, img):
        return self._apply_image(img)


class RandomPerspective(BaseTransform):
    """reference: vision/transforms/transforms.py RandomPerspective."""

    def __init__(self, prob: float = 0.5, distortion_scale: float = 0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _points(self, w, h):
        d = self.distortion_scale
        half_w, half_h = w // 2, h // 2
        tl = (_pyrandom.randint(0, int(d * half_w)),
              _pyrandom.randint(0, int(d * half_h)))
        tr = (w - 1 - _pyrandom.randint(0, int(d * half_w)),
              _pyrandom.randint(0, int(d * half_h)))
        br = (w - 1 - _pyrandom.randint(0, int(d * half_w)),
              h - 1 - _pyrandom.randint(0, int(d * half_h)))
        bl = (_pyrandom.randint(0, int(d * half_w)),
              h - 1 - _pyrandom.randint(0, int(d * half_h)))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        return start, [tl, tr, br, bl]

    def __call__(self, img):
        if _pyrandom.random() >= self.prob:
            return img
        arr = _to_numpy(img)
        h, w = arr.shape[0], arr.shape[1]
        start, end = self._points(w, h)
        return perspective(img, start, end,
                           interpolation=self.interpolation, fill=self.fill)
